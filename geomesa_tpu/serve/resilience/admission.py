"""Priority-aware admission control with load shedding.

≙ the overload-control discipline of Zhou et al., *Overload Control for
Scaling WeChat Microservices* (SoCC 2018): requests are classed by business
priority at the entry point and an overloaded server rejects excess work
EARLY — a bounded amount of in-flight work per class, shed-with-backpressure
(HTTP 429 + Retry-After) past the bound — instead of queueing until every
admitted request misses its deadline (queueing collapse).

Two classes:

  interactive   dashboard/map-tile style point queries; the class whose
                tail latency the system protects. Served first by the
                scheduler's priority queue.
  batch         analytics / bulk scans; bounded lower so background load
                can never starve interactive traffic.

Accounting is in-flight based (admitted minus completed, counted via a
future done-callback), so the bound covers queued AND executing work — the
quantity that actually determines how long a newly admitted request waits.
"""

from __future__ import annotations

import threading
from typing import Dict

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics

PRIORITIES = ("interactive", "batch")


def normalize_priority(p) -> str:
    """Canonical priority class for a request parameter; unknown values
    fall back to interactive (a typo must not silently deprioritize)."""
    p = str(p or "interactive").lower()
    if p in ("batch", "analytics", "background", "bulk"):
        return "batch"
    return "interactive"


class ShedError(Exception):
    """The request was rejected by admission control (→ HTTP 429). Carries
    the Retry-After the client should honor."""

    def __init__(self, priority: str, in_flight: int, limit: int,
                 retry_after_s: float):
        super().__init__(
            f"overloaded: {in_flight}/{limit} {priority} queries in flight; "
            f"retry after {retry_after_s:g}s")
        self.priority = priority
        self.in_flight = in_flight
        self.limit = limit
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Bounded in-flight work per priority class; excess sheds."""

    def __init__(self, interactive_limit=None, batch_limit=None):
        self._lock = threading.Lock()
        self._limits_override = {"interactive": interactive_limit,
                                 "batch": batch_limit}
        self._in_flight: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._admitted: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._shed: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._draining = False
        _metrics.set_gauge("admission.in_flight.interactive",
                           lambda: self._in_flight["interactive"])
        _metrics.set_gauge("admission.in_flight.batch",
                           lambda: self._in_flight["batch"])

    def _limit(self, priority: str) -> int:
        ov = self._limits_override.get(priority)
        if ov is not None:
            return int(ov)
        prop = config.ADMIT_INTERACTIVE if priority == "interactive" \
            else config.ADMIT_BATCH
        return int(prop.get())

    def admit(self, priority: str) -> str:
        """Admit one request of ``priority`` (returns the normalized class)
        or raise ShedError. The caller MUST pair a successful admit with
        exactly one ``release`` (the scheduler wires it to the request
        future's done-callback, covering every resolution path)."""
        p = normalize_priority(priority)
        if self._draining:
            # rolling restart / failover drain: shed EVERYTHING (even with
            # admission disabled) so in-flight work settles and a promote
            # can measure a quiesced node
            with self._lock:
                self._shed[p] += 1
                n = self._in_flight[p]
            _metrics.inc("admission.shed")
            _metrics.inc(f"admission.shed.{p}")
            raise ShedError(p, n, 0,
                            float(config.ADMIT_RETRY_AFTER_S.get()))
        if not config.ADMIT_ENABLED.get():
            with self._lock:
                self._in_flight[p] += 1
                self._admitted[p] += 1
            _metrics.inc("admission.admitted")
            return p
        limit = self._limit(p)
        with self._lock:
            n = self._in_flight[p]
            if n >= limit:
                self._shed[p] += 1
            else:
                self._in_flight[p] = n + 1
                self._admitted[p] += 1
                n = -1
        if n >= 0:
            _metrics.inc("admission.shed")
            _metrics.inc(f"admission.shed.{p}")
            raise ShedError(p, n, limit,
                            float(config.ADMIT_RETRY_AFTER_S.get()))
        _metrics.inc("admission.admitted")
        return p

    def release(self, priority: str) -> None:
        with self._lock:
            self._in_flight[priority] = max(
                0, self._in_flight[priority] - 1)

    def drain(self, draining: bool = True) -> None:
        """Enter (or leave) drain mode: every new request sheds with 429 +
        Retry-After while already-admitted work completes — the rolling-
        restart / pre-failover quiesce step."""
        self._draining = bool(draining)
        _metrics.inc("admission.drains" if draining
                     else "admission.undrains")

    @property
    def draining(self) -> bool:
        return self._draining

    def in_flight_total(self) -> int:
        with self._lock:
            return sum(self._in_flight.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": bool(config.ADMIT_ENABLED.get()),
                "draining": self._draining,
                "in_flight": dict(self._in_flight),
                "limits": {p: self._limit(p) for p in PRIORITIES},
                "admitted": dict(self._admitted),
                "shed": dict(self._shed),
                "retry_after_s": float(config.ADMIT_RETRY_AFTER_S.get()),
            }
