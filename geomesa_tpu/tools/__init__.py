"""Ops tools: the command-line surface (≙ geomesa-tools, SURVEY.md §2.11)."""
