"""Command-line interface.

≙ reference `geomesa-tools` (SURVEY.md §2.11 — tools/Runner.scala:24 command
tree: create-schema / ingest / export / explain / stats-* / delete /
remove-schema). The "catalog" is a checkpoint directory (io.checkpoint);
mutating commands load → act → save.

    geomesa-tpu create-schema -s STORE -f NAME --spec 'dtg:Date,*geom:Point'
    geomesa-tpu ingest        -s STORE -f NAME data.csv [--converter conv.json | --infer]
    geomesa-tpu count         -s STORE -f NAME [-q ECQL]
    geomesa-tpu export        -s STORE -f NAME [-q ECQL] --format csv [-o out.csv]
    geomesa-tpu explain       -s STORE -f NAME -q ECQL
    geomesa-tpu stats         -s STORE -f NAME [--attr A] [--kind histogram|topk|bounds|count|minmax]
    geomesa-tpu delete        -s STORE -f NAME -q ECQL
    geomesa-tpu debug         metrics|traces|trace|events|slo|kernels|scheduler|cache|admission|wal|replication|workload|cluster|balance
                              [--format prometheus] [--slow MS] [--errors]
                              [--kind K] [--addr HOST:PORT ...] [-s STORE -f NAME -q ECQL]
                              [--id TRACE_ID --fleet]   (debug trace: stitched tree)
    geomesa-tpu cluster-dryrun [--procs N] [--n ROWS] [--out DIR] [--no-web]
    geomesa-tpu serve         -s STORE [--durable] [--ship-port P] [--port W]
    geomesa-tpu replica       --dir DIR --follow HOST:PORT [--port W] [--id ID]
    geomesa-tpu router        --endpoint NAME=HOST:PORT ... [--port P]
    geomesa-tpu fleet         status --addr HOST:PORT [--addr ...] [--json]
    geomesa-tpu soak          [--mini] [--scoreboard PATH] [--half chaos|clean]
    geomesa-tpu perfwatch     check|update|show [--run BENCH_summary.json]
                              [--baseline perf/baselines.json] [--k 3]
                              [--report out.json]
    geomesa-tpu recover       --dir DURABILITY_DIR
    geomesa-tpu describe / list / remove-schema
"""

from __future__ import annotations

import argparse
import csv as _csv
import json
import os
import sys


def _load(store_dir: str, must_exist: bool = False):
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.io.checkpoint import load_store
    if os.path.exists(os.path.join(store_dir, "catalog.json")):
        return load_store(store_dir)
    if must_exist:
        raise SystemExit(f"No store at {store_dir} (missing catalog.json)")
    return TpuDataStore()


def _save(store, store_dir: str) -> None:
    from geomesa_tpu.io.checkpoint import save_store
    save_store(store, store_dir)


def cmd_create_schema(args):
    store = _load(args.store)
    store.create_schema(args.feature, args.spec)
    _save(store, args.store)
    print(f"Created schema {args.feature!r}")


def cmd_list(args):
    store = _load(args.store, must_exist=True)
    for name in store.get_type_names():
        t = store.tables.get(name)
        print(f"{name}\t{0 if t is None else len(t)} features")


def cmd_describe(args):
    store = _load(args.store, must_exist=True)
    sft = store.get_schema(args.feature)
    for a in sft.attributes:
        star = "*" if a.default else " "
        print(f"{star} {a.name}: {a.type_name} {a.options or ''}")
    if sft.user_data:
        print(f"user-data: {sft.user_data}")


def cmd_ingest(args):
    from geomesa_tpu.convert import (SimpleFeatureConverter,
                                     converter_config_from_inference,
                                     infer_schema)
    store = _load(args.store)
    fmt = args.format or ("json" if args.files[0].endswith((".json", ".jsonl"))
                          else "tsv" if args.files[0].endswith(".tsv")
                          else "csv")
    delim = "\t" if fmt == "tsv" else ","

    if args.converter:
        with open(args.converter) as fh:
            config = json.load(fh)
        sft = store.get_schema(args.feature)
    elif args.infer:
        if fmt == "json":
            raise SystemExit(
                "--infer only supports delimited input; for JSON provide a "
                "--converter config")
        with open(args.files[0], newline="") as fh:
            rows = list(_csv.reader(fh, delimiter=delim))
        if not rows or not rows[0]:
            raise SystemExit(f"Cannot infer a schema from empty {args.files[0]}")
        names, sample = rows[0], rows[1:101]
        spec, transforms = infer_schema(names, sample)
        config = converter_config_from_inference(spec, transforms)
        if args.feature not in store.get_type_names():
            store.create_schema(args.feature, spec)
            print(f"Inferred schema: {spec}")
        sft = store.get_schema(args.feature)
    else:
        raise SystemExit("ingest requires --converter CONF or --infer")

    conv = SimpleFeatureConverter(config, sft)
    total = 0
    for path in args.files:
        if fmt == "json":
            table = conv.convert_json(path)
        else:
            table = conv.convert_delimited(path, delimiter=delim)
        store.load(args.feature, table)
        total += len(table)
    _save(store, args.store)
    msg = f"Ingested {total} features into {args.feature!r}"
    if conv.skipped:
        msg += f" ({conv.skipped} bad records skipped)"
    print(msg)


def cmd_count(args):
    store = _load(args.store, must_exist=True)
    print(store.count(args.feature, args.cql or "INCLUDE"))


def cmd_export(args):
    from geomesa_tpu.io.export import export
    store = _load(args.store, must_exist=True)
    res = store.query(args.feature, args.cql or "INCLUDE")
    table = res.table
    if args.max is not None and len(table) > args.max:
        import numpy as np
        table = table.take(np.arange(args.max))
    if getattr(args, "select", None):
        # geometry-catalog projections: st_* terms through the vmapped
        # kernels, geometry values as WKT — CSV or JSON columns
        from geomesa_tpu.geom.functions import projection_columns
        cols = projection_columns(table, None, args.select)
        if args.format == "json":
            out = json.dumps({"count": len(table), "columns": cols})
        else:
            import csv as _csv
            import io as _io
            buf = _io.StringIO()
            w = _csv.writer(buf)
            w.writerow(list(cols))
            for row in zip(*cols.values()):
                w.writerow(row)
            out = buf.getvalue()
        if args.output:
            with open(args.output, "w") as fh:
                fh.write(out)
            print(f"Exported {len(table)} projected rows to {args.output}")
        else:
            sys.stdout.write(out)
        return
    out = export(table, args.format, args.output)
    if args.output:
        print(f"Exported {len(table)} features to {args.output}")
    else:
        sys.stdout.write(out)


def cmd_explain(args):
    store = _load(args.store, must_exist=True)
    plan = store.explain(args.feature, args.cql)
    print(json.dumps({k: str(v) for k, v in plan.items()}, indent=2))


def cmd_stats(args):
    store = _load(args.store, must_exist=True)
    s = store.stats(args.feature)
    kind = args.kind
    if kind == "count":
        print(s.get_count(args.cql, exact=not args.no_exact))
    elif kind == "bounds":
        print(s.get_bounds())
    elif kind == "minmax":
        mm = s.get_min_max(_require_attr(store, args))
        print(json.dumps(mm.to_json()))
    elif kind == "topk":
        print(json.dumps(s.get_top_k(_require_attr(store, args)).topk(10)))
    elif kind == "histogram":
        h = s.get_histogram(_require_attr(store, args), bins=args.bins, f=args.cql)
        if h is None:
            raise SystemExit(f"{args.attr!r} is not a binnable attribute")
        edges = h.bin_edges()
        width = max(int(c) for c in h.counts) or 1
        for i, c in enumerate(h.counts):
            bar = "#" * max(1 if c else 0, int(40 * int(c) / width))
            print(f"[{edges[i]:>12.2f} .. {edges[i+1]:>12.2f}] {int(c):>9} {bar}")
    else:
        raise SystemExit(f"Unknown stats kind {kind!r}")


def _require_attr(store, args) -> str:
    if not args.attr:
        raise SystemExit(f"stats --kind {args.kind} requires --attr")
    sft = store.get_schema(args.feature)
    try:
        sft.attribute(args.attr)
    except KeyError:
        raise SystemExit(
            f"No attribute {args.attr!r} in {args.feature!r} "
            f"(have {[a.name for a in sft.attributes]})")
    return args.attr


def cmd_delete(args):
    store = _load(args.store, must_exist=True)
    n = store.remove_features(args.feature, args.cql)
    _save(store, args.store)
    print(f"Deleted {n} features")


def cmd_age_off(args):
    """Run the TTL compaction (≙ the reference's age-off maintenance
    command over DtgAgeOffIterator-configured tables)."""
    store = _load(args.store, must_exist=True)
    n = store.age_off(args.feature)
    if n:
        _save(store, args.store)
    print(f"Aged off {n} features")


def cmd_reindex(args):
    """Rebuild a type's device indexes build-then-swap (the maintenance
    analogue of the reference's offline reindex jobs). Runs in the
    foreground here — against a live server use POST /types/{t}/reindex,
    which builds off the serving path and swaps atomically."""
    import json as _json
    store = _load(args.store, must_exist=True)
    st = store.reindex(args.feature, background=False)
    _save(store, args.store)
    print(_json.dumps(st, indent=2, default=str))


def cmd_recover(args):
    """Crash recovery (the runbook command): load the newest valid snapshot
    under the durability dir, replay the WAL suffix past it (truncating a
    torn tail at the first bad CRC), rebuild indexes, then write a fresh
    post-recovery snapshot so the next restart replays nothing."""
    from geomesa_tpu.datastore import TpuDataStore
    d = args.dir or args.store
    if not d:
        raise SystemExit("recover requires --dir (or -s) DURABILITY_DIR")
    store = TpuDataStore.open(d)
    report = store.recovery_report
    out = report.to_dict() if report is not None else {"recovered": False}
    out["rows"] = {t: (0 if store.tables.get(t) is None
                       else len(store.tables[t]))
                   for t in store.get_type_names()}
    out["post_recovery_snapshot"] = store.durability.snapshot()
    store.close()
    print(json.dumps(out, indent=2, default=str))


def cmd_debug(args):
    """Observability surface: dump the process metrics registry, the
    recent-trace ring, the query-scheduler state, or the WAL segment
    inspector (≙ the reference's stats/audit debug commands plus an
    accumulo-style wal-info). With a store + feature + CQL, runs the
    query first so the dump reflects a real execution — the offline way to
    read a trace tree. ``debug scheduler`` drives the warm query THROUGH the
    scheduler (a concurrent burst, so the dump shows real coalescing:
    queue depth, batch-size histogram, flush reasons, cache hit rates).
    ``debug wal -s DIR`` lists every segment's records (seq ranges, kinds,
    torn-tail diagnostics) without opening the store."""
    from geomesa_tpu.metrics import REGISTRY
    from geomesa_tpu.trace import RING
    if args.what == "wal":
        if not args.store:
            raise SystemExit("debug wal requires -s DURABILITY_DIR")
        from geomesa_tpu.durability import wal as _walmod
        out = _walmod.inspect(os.path.join(args.store, "wal"))
        out["journal"] = _walmod.inspect(
            os.path.join(args.store, "journal"), name="journal")["segments"]
        print(json.dumps(out, indent=2))
        return
    store = None
    if args.store:
        store = _load(args.store, must_exist=True)
        if args.feature and args.cql:
            if args.what in ("scheduler", "workload", "cache"):
                ns = store.count_many(args.feature, [args.cql] * 8)
                print(f"# ran 8x count({args.feature!r}, {args.cql!r}) "
                      f"through the scheduler -> {ns[0]}", file=sys.stderr)
            else:
                n = store.count(args.feature, args.cql)
                print(f"# ran count({args.feature!r}, {args.cql!r}) -> {n}",
                      file=sys.stderr)
    if args.what == "metrics":
        if args.format == "prometheus":
            sys.stdout.write(REGISTRY.to_prometheus())
        else:
            print(json.dumps(REGISTRY.snapshot(), indent=2, default=str))
    elif args.what == "admission":
        # the overload runbook surface: live queue depths per priority
        # class, shed/retry/breaker counters, deadline histograms
        out = {}
        if store is not None:
            sched = store.scheduler()
            out["admission"] = sched.admission.stats()
            out["breaker"] = sched.breaker.stats()
            out["queue_depth"] = sched._queue.qsize()
            out["healthy"] = sched.healthy()
        snap = REGISTRY.snapshot_prefixed(
            "admission.", "breaker.", "retry.", "degrade.",
            "scheduler.deadline", "scheduler.degraded",
            "scheduler.worker_deaths", "scheduler.restarts", "deadline.")
        out["metrics"] = {k: v for k, v in snap.items() if v}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "scheduler":
        out = {}
        if store is not None:
            out = store.scheduler().stats()
        snap = REGISTRY.snapshot()
        # process-wide serving metrics ride along (a store-less dump still
        # shows whatever this process observed)
        out["metrics"] = {
            "counters": {k: v for k, v in snap["counters"].items()
                         if k.startswith("scheduler.")},
            "histograms": {k: v for k, v in snap["histograms"].items()
                           if k.startswith("scheduler.")},
            "gauges": {k: v for k, v in snap["gauges"].items()
                       if k.startswith(("scheduler.", "kernels."))},
        }
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "cache":
        # the hot-result cache: hit/miss/invalidation counters + per-cell
        # warmth (cross-check against `debug workload` hot cells and the
        # doctor's hot_skew suspects). With -s/-f/-q the repeated count
        # warms the cache first, so the dump shows a real hit.
        out = {}
        if store is not None:
            out["result_cache"] = store.scheduler().results.stats()
        snap = REGISTRY.snapshot_prefixed("result_cache.")
        out["metrics"] = {k: v for k, v in snap.items() if v}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "events":
        # the flight recorder: one wide event per query/count/batch, with
        # the same filters the /events route takes
        from geomesa_tpu.obs.flight import RECORDER
        out = {"recorder": RECORDER.stats(),
               "events": RECORDER.recent(limit=args.limit,
                                         slow_ms=args.slow,
                                         errors=args.errors,
                                         kind=args.kind,
                                         type_name=args.feature,
                                         since_ms=args.since_ms)}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "timeline":
        # retained metric timelines as ASCII sparklines — this process's
        # history rings, or a RUNNING node's GET /history via --addr
        # (one row per series; --name narrows, --since-ms/--tier slice)
        from geomesa_tpu.obs import history as _history
        if args.addr:
            import urllib.parse
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                prefix = f"{addr} " if len(args.addr) > 1 else ""
                try:
                    with urllib.request.urlopen(base + "/history",
                                                timeout=5) as r:
                        summary = json.loads(r.read().decode())["history"]
                    names = summary.get("series") or []
                    if args.name:
                        names = [n for n in names if n == args.name]
                    for n in names:
                        q = f"/history?name={urllib.parse.quote(n)}"
                        if args.since_ms is not None:
                            q += f"&since_ms={args.since_ms}"
                        if args.tier is not None:
                            q += f"&tier={args.tier}"
                        with urllib.request.urlopen(base + q,
                                                    timeout=5) as r:
                            samples = json.loads(
                                r.read().decode())["samples"]
                        print(prefix + _history.render_timeline(n, samples))
                except OSError as e:
                    print(f"{addr}: UNREACHABLE ({e})")
        else:
            h = _history.HISTORY
            h.maybe_sample()    # a fresh CLI read still shows this tick
            names = [args.name] if args.name else h.series_names()
            if not names:
                print("timeline: no retained series yet "
                      "(GEOMESA_TPU_HISTORY off, or nothing sampled)")
            for n in names:
                print(_history.render_timeline(
                    n, h.range(n, since_ms=args.since_ms or 0,
                               tier=args.tier)))
    elif args.what == "replication":
        # fleet runbook surface: role/lag/ship state (from a RUNNING node
        # via --addr, since replication state lives in the serving
        # process), plus this process's replication/router/drill counters
        out = {}
        for addr in (args.addr or []):
            base = addr if addr.startswith("http") else f"http://{addr}"
            import urllib.request
            node = {}
            for path, key in (("/replication", "replication"),
                              ("/healthz", "healthz")):
                try:
                    with urllib.request.urlopen(base + path,
                                                timeout=5) as r:
                        node[key] = json.loads(r.read().decode())
                except OSError as e:
                    node[key] = {"error": str(e)}
            if len(args.addr) == 1:
                out.update(node)  # the established single-node shape
            else:
                out.setdefault("nodes", {})[addr] = node
        snap = REGISTRY.snapshot_prefixed("replication.", "router.",
                                          "drill.")
        out["metrics"] = {k: v for k, v in snap.items() if v}
        gauges = REGISTRY.snapshot()["gauges"]
        out["lag"] = {k: gauges[k] for k in
                      ("replication.lag_seqs", "replication.lag_ms",
                       "replication.followers") if k in gauges}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "cluster":
        # the partition plane runbook surface: process count, per-process
        # rows, Morton key-range ownership, mesh topology (axes, ICI/DCN
        # shape), psum round counters — this process's runtime, or a
        # RUNNING cluster node's GET /cluster via --addr (fleet parity
        # with `debug replication`)
        out = {}
        if args.addr:
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                try:
                    with urllib.request.urlopen(base + "/cluster",
                                                timeout=5) as r:
                        node = json.loads(r.read().decode())
                except OSError as e:
                    node = {"error": str(e)}
                if len(args.addr) == 1:
                    out.update(node)
                else:
                    out.setdefault("nodes", {})[addr] = node
        else:
            from geomesa_tpu.cluster.runtime import runtime as _cluster_rt
            out = _cluster_rt(init=False).state()
        snap = REGISTRY.snapshot_prefixed("cluster.")
        metrics = {k: v for k, v in snap.items() if v}
        if metrics:
            out["metrics"] = metrics
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "balance":
        # the shard balance observatory runbook surface: per-shard load
        # shares joined from hot cells x key-range ownership, imbalance
        # score, projected split points — this process's ledger, or a
        # RUNNING cluster node's GET /cluster/balance via --addr (one
        # addr flattens; several nest per node)
        out = {}
        if args.addr:
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                try:
                    with urllib.request.urlopen(base + "/cluster/balance",
                                                timeout=5) as r:
                        node = json.loads(r.read().decode())
                except OSError as e:
                    node = {"error": str(e)}
                if len(args.addr) == 1:
                    out.update(node)
                else:
                    out.setdefault("nodes", {})[addr] = node
        else:
            from geomesa_tpu.obs.shardwatch import WATCH
            out = WATCH.balance()
        snap = REGISTRY.snapshot_prefixed("cluster.collective.")
        metrics = {k: v for k, v in snap.items() if v}
        if metrics:
            out["collective"] = metrics
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "trace":
        # the stitched cross-process tree for one global trace id:
        # collect this process's halves plus every --addr node's
        # GET /traces?id= halves, stitch, render (--fleet implied by any
        # --addr; without addrs it stitches whatever is local)
        from geomesa_tpu.obs import federation as _fed
        if not args.id:
            raise SystemExit("debug trace requires --id GLOBAL_TRACE_ID")
        nodes = {"local": None}
        for i, addr in enumerate(args.addr or []):
            nodes[f"addr{i}"] = addr
        halves = _fed.collect_trace(args.id, nodes)
        st = _fed.stitch(halves)
        print(_fed.render_stitched(st))
        if args.format == "json":
            print(json.dumps({"id": args.id, "stitched": st,
                              "halves": len(halves)}, indent=2,
                             default=str))
    elif args.what == "slo":
        # burn-rate runbook surface: compliance + multi-window burn rates
        # + page/ticket state per objective — this process's engine, or a
        # RUNNING node's GET /slo via --addr (fleet parity with
        # `debug replication` / `debug workload`)
        out = {}
        if args.addr:
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                try:
                    with urllib.request.urlopen(base + "/slo",
                                                timeout=5) as r:
                        node = json.loads(r.read().decode())
                except OSError as e:
                    node = {"error": str(e)}
                if len(args.addr) == 1:
                    out.update(node)
                else:
                    out.setdefault("nodes", {})[addr] = node
        else:
            from geomesa_tpu.obs.slo import ENGINE
            out = {"slo": ENGINE.evaluate()}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "incidents":
        # the doctor's incident ledger: active + recently-resolved, with
        # correlated timelines — local, or a RUNNING node's /incidents
        out = {}
        if args.addr:
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                try:
                    with urllib.request.urlopen(base + "/incidents",
                                                timeout=5) as r:
                        node = json.loads(r.read().decode())
                except OSError as e:
                    node = {"error": str(e)}
                if len(args.addr) == 1:
                    out.update(node)
                else:
                    out.setdefault("nodes", {})[addr] = node
        else:
            from geomesa_tpu.obs.doctor import DOCTOR
            out = DOCTOR.incidents()
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "workload":
        # workload intelligence: windowed rollups, heavy-hitter plan
        # hashes/tenants, hot spatial cells — this process's plane, or a
        # RUNNING node's GET /workload via --addr
        out = {}
        if args.addr:
            import urllib.request
            for addr in args.addr:
                base = addr if addr.startswith("http") else f"http://{addr}"
                try:
                    with urllib.request.urlopen(base + "/workload",
                                                timeout=5) as r:
                        node = json.loads(r.read().decode())
                except OSError as e:
                    node = {"error": str(e)}
                if len(args.addr) == 1:
                    out.update(node)
                else:
                    out.setdefault("nodes", {})[addr] = node
        else:
            from geomesa_tpu.obs.workload import WORKLOAD
            out = {"workload": WORKLOAD.summary()}
        print(json.dumps(out, indent=2, default=str))
    elif args.what == "kernels":
        # per-kernel device cost attribution (dispatches, device wait,
        # transfer bytes, compiles, flops/bytes cost model per kernel id
        # + batch tier), headed by the process-wide recompile count and
        # live/peak device memory — the perf-regression postmortem dump
        from geomesa_tpu.index import compiled as _fused
        from geomesa_tpu.index.device import memory_snapshot
        from geomesa_tpu.obs import attrib
        snap = REGISTRY.snapshot()
        print(json.dumps({
            "recompiles": snap["counters"].get("kernels.recompiles", 0),
            "device_memory": memory_snapshot(),
            "kernels": attrib.snapshot(),
            "fused_query": _fused.stats_snapshot(),
        }, indent=2, default=str))
    else:  # traces — filtered through the shared flight-recorder predicate
        from geomesa_tpu.obs.flight import matches
        traces = [t for t in RING.recent(None)
                  if matches(t, slow_ms=args.slow, errors=args.errors,
                             kind=args.kind)]
        print(json.dumps(traces[: args.limit], indent=2))


def cmd_perfwatch(args):
    """Perf regression watch (the bench.py --check logic as a standalone
    command, so a saved BENCH_summary.json gates without re-running the
    bench): ``check`` compares a run summary against the baseline store
    with noise-aware (median + k*MAD) thresholds and exits 3 on confirmed
    regressions; ``update`` folds a run into the rolling baselines;
    ``show`` prints the baseline medians/MADs."""
    from geomesa_tpu.obs import perfwatch as pw
    if args.action == "show":
        b = pw.load_baselines(args.baseline)
        print(json.dumps({
            "updated_ts": b.get("updated_ts"), "runs": b.get("runs"),
            "meta": b.get("meta"),
            "metrics": {k: {kk: v[kk] for kk in ("median", "mad",
                                                 "direction")
                            if kk in v}
                        for k, v in sorted(b.get("metrics", {}).items())},
        }, indent=2))
        return
    with open(args.run) as fh:
        summary = json.load(fh)
    if args.action == "update":
        try:
            b = pw.load_baselines(args.baseline)
        except (FileNotFoundError, ValueError):
            b = pw.empty_baselines()
        pw.save_baselines(pw.update_baselines(b, summary), args.baseline)
        print(f"baselines updated -> {args.baseline}")
        return
    report = pw.check_summary(summary, args.baseline, k=args.k,
                              report_path=args.report)
    print(pw.render(report))
    if not report["ok"]:
        raise SystemExit(3)


def cmd_config(args):
    from geomesa_tpu import config as cfg
    for name, d in cfg.describe().items():
        mark = "" if d["value"] == d["default"] else "  (set)"
        print(f"{name} = {d['value']}{mark}\n    {d['doc']}")


def _configure_cell(spec: str, directory):
    """Bind this process to its shard cell (``--cell SHARD=LO:HI``): the
    ingest gate starts refusing out-of-range writes with 409 and the
    cell fence persists its epoch under the durable directory."""
    from geomesa_tpu.cluster import cells as _cells
    topo = _cells.ShardCells.from_specs([spec])
    _cells.CELLS.configure(topology=None, local=topo.cells[0],
                           directory=directory)
    print(json.dumps({"cell": topo.cells[0].summary(),
                      "fence_epoch": _cells.CELLS.fence.epoch
                      if _cells.CELLS.fence else None}), flush=True)


def cmd_serve(args):
    from geomesa_tpu.web import serve
    if args.durable:
        # a durable store dir (WAL + snapshots): recovery runs on open and
        # every mutation is logged — the shape a replicated fleet requires
        from geomesa_tpu.datastore import TpuDataStore
        store = TpuDataStore.open(args.store)
    else:
        store = _load(args.store, must_exist=True)
    if args.cell:
        _configure_cell(args.cell,
                        args.store if args.durable else None)
    if args.ship_port is not None:
        from geomesa_tpu.replication.shipper import LogShipper
        shipper = LogShipper(store, host=args.host, port=args.ship_port)
        print(json.dumps({"shipping": shipper.address,
                          "epoch": shipper.epoch}), flush=True)
    print(f"Serving {args.store} on http://{args.host}:{args.port}",
          flush=True)
    serve(store, host=args.host, port=args.port)


def cmd_replica(args):
    """Run a read replica: open (or create) the local durable copy at
    --dir, follow the primary's log shipper at --follow host:port, and
    optionally serve the read-only REST API on --port. Runs until
    interrupted; `POST /replication/promote` (or a router failover) turns
    it into a primary in place."""
    import time as _time

    from geomesa_tpu.replication.follower import Follower
    from geomesa_tpu.web import serve
    if args.cell:
        _configure_cell(args.cell, args.dir)
    f = Follower(args.dir, args.follow, follower_id=args.id)
    print(json.dumps({"replica": f.id, "dir": args.dir,
                      "following": args.follow}), flush=True)
    try:
        if args.port:
            print(f"Serving replica on http://{args.host}:{args.port}",
                  flush=True)
            serve(f, host=args.host, port=args.port)
        else:
            while not f.dead:
                _time.sleep(0.5)
            raise SystemExit("replica apply loop died")
    finally:
        f.close()


def cmd_router(args):
    """Run the fleet front door: a health/lag-aware read router over the
    named endpoints, serving routed counts WITH cross-process trace
    propagation plus the federated observability plane (GET /fleet,
    /fleet/metrics, /fleet/slo, the /traces?id= stitcher)."""
    from geomesa_tpu import trace as _t
    from geomesa_tpu.obs import federation as _fed
    from geomesa_tpu.serve.router import (HttpEndpoint, ReplicaRouter,
                                          serve_router)
    eps, nodes = [], {}
    for spec in args.endpoint:
        name, sep, addr = spec.partition("=")
        if not sep:
            name, addr = f"n{len(eps)}", spec
        base = addr if addr.startswith("http") else f"http://{addr}"
        eps.append(HttpEndpoint(name, base))
        nodes[name] = base
    topology = None
    if getattr(args, "shard", None):
        from geomesa_tpu.cluster.cells import ShardCells
        topology = ShardCells.from_specs(args.shard)
    router = ReplicaRouter(eps, topology=topology)
    nodes[_t.node_id()] = None  # federate this router's own counters too
    fed = _fed.configure(nodes)
    print(json.dumps({"router": f"http://{args.host}:{args.port}",
                      "endpoints": sorted(nodes)}), flush=True)
    serve_router(router, host=args.host, port=args.port, federator=fed)


def _render_fleet(fl) -> str:
    lines = ["NODE              ROLE        LAG      SEQ            "
             "BREAKER   QUEUE  FENCED  SLO"]
    for name, n in sorted(fl.get("nodes", {}).items()):
        if not n.get("ok"):
            lines.append(f"{name:<17} DOWN        {n.get('error')}")
            continue
        lag = "-" if n.get("lag_ms") is None else f"{n['lag_ms']}ms"
        seq = f"{n.get('applied_seq')}/{n.get('wal_seq')}"
        lines.append(
            f"{name:<17} {str(n.get('role')):<11} {lag:<8} {seq:<14} "
            f"{str(n.get('breaker')):<9} {str(n.get('queue_depth')):<6} "
            f"{str(n.get('fenced')):<7} {n.get('slo')}")
    for k, v in sorted((fl.get("slo") or {}).items()):
        lines.append(f"slo {k}: status={v.get('status')} "
                     f"compliance={v.get('compliance')} "
                     f"good={v.get('good')}/{v.get('total')}")
    e2e = fl.get("repl_e2e_ms")
    if e2e:
        lines.append(f"repl.e2e: count={e2e.get('count')} "
                     f"p50={e2e.get('p50_ms')}ms p99={e2e.get('p99_ms')}ms "
                     f"exemplars={e2e.get('exemplars')}")
    return "\n".join(lines)


def cmd_fleet(args):
    """Fleet status from anywhere: scrape every --addr node's /healthz +
    bucket-exact metrics state, merge client-side, and print the single
    pane of glass (per-node health/lag/seq, fleet SLO burn rates over
    MERGED samples, the replication e2e pipeline histogram)."""
    from geomesa_tpu.obs import federation as _fed
    if args.action != "status":
        raise SystemExit(f"unknown fleet action {args.action!r}")
    if not args.addr:
        raise SystemExit("fleet status requires --addr HOST:PORT "
                         "(repeatable, one per node)")
    fed = _fed.Federator({a: a for a in args.addr})
    fl = fed.fleet()
    if args.json:
        print(json.dumps(fl, indent=2, default=str))
    else:
        print(_render_fleet(fl))


def cmd_soak(args):
    """Run the fleet soak: launch a real primary+replicas+router fleet
    as subprocesses, drive Zipf multi-tenant traffic through the router,
    execute the chaos timeline (unless --half clean), and write the
    scored scoreboard (JSON + markdown). Exits nonzero when any
    scoreboard check fails."""
    from geomesa_tpu.obs import soakfleet
    halves = ("chaos", "clean") if args.half == "both" else (args.half,)
    board = soakfleet.run(mini=args.mini, scoreboard_path=args.scoreboard,
                          base_dir=args.dir, halves=halves)
    print(soakfleet.render_scoreboard(board))
    if not board.get("ok"):
        raise SystemExit(2)


def cmd_soakcells(args):
    """Run the cluster chaos soak: two replicated shard cells plus a
    shard-aware router as subprocesses, shard-routed writes and
    scatter-gather reads, then the cluster chaos timeline (cell
    failover, mid-ingest handoff, split-brain refusal, shard_dark).
    Exits nonzero when any scoreboard check fails."""
    from geomesa_tpu.obs import soakcells
    halves = ("chaos", "clean") if args.half == "both" else (args.half,)
    board = soakcells.run(mini=args.mini, scoreboard_path=args.scoreboard,
                          base_dir=args.dir, halves=halves)
    print(soakcells.render_scoreboard(board))
    if not board.get("ok"):
        raise SystemExit(2)


def cmd_cluster_dryrun(args):
    """The partition-plane soak: spawn --procs CPU worker processes, build
    ONE table sharded across them by contiguous Morton key-range, and check
    that psum-reduced counts/density and host-merged selects are byte-equal
    to the single-process oracle (same code path, inactive runtime). Exits
    nonzero when any exactness check fails."""
    from geomesa_tpu.cluster.dryrun import run_dryrun
    report = run_dryrun(args.procs, args.n, args.seed,
                        timeout_s=args.timeout_s, out_dir=args.out,
                        web=not args.no_web)
    print(json.dumps({k: report[k] for k in
                      ("ok", "checks", "wall_s", "work_dir")}, indent=2))
    if not report["ok"]:
        raise SystemExit(2)


def cmd_doctor(args):
    """The fleet doctor's verdicts: evaluate the anomaly detectors and
    print ONE line per incident — what fired, since when, suspected
    cause, linked trace. Local by default; with --addr it reads each
    RUNNING node's GET /incidents (repeatable, node-attributed)."""
    from geomesa_tpu.obs.doctor import verdict
    nodes = {}
    if args.addr:
        import urllib.request
        for addr in args.addr:
            base = addr if addr.startswith("http") else f"http://{addr}"
            try:
                with urllib.request.urlopen(base + "/incidents",
                                            timeout=5) as r:
                    nodes[addr] = json.loads(r.read().decode())
            except OSError as e:
                nodes[addr] = {"error": str(e)}
    else:
        from geomesa_tpu.obs.doctor import DOCTOR
        nodes["local"] = DOCTOR.incidents()
    if args.json:
        print(json.dumps(nodes if len(nodes) > 1
                         else next(iter(nodes.values())),
                         indent=2, default=str))
        return
    total = 0
    for name, body in sorted(nodes.items()):
        if body.get("error"):
            print(f"{name}: UNREACHABLE ({body['error']})")
            continue
        incidents = body.get("incidents") or []
        for inc in incidents:
            prefix = f"{name}: " if len(nodes) > 1 else ""
            print(prefix + verdict(inc))
        total += len(incidents)
    if total == 0:
        print("doctor: no incidents — all detectors clear")


def cmd_forensics(args):
    """Forensic bundles the doctor froze at incident open: history
    slices around the firing, matching flight events, retained trace
    gids, replication/cell state, workload hot_set. Without --id, lists
    the captured bundles; with --id, prints that incident's bundle.
    --addr reads a RUNNING node's GET /incidents/{id}/bundle instead."""
    if args.addr:
        import urllib.request
        out = {}
        for addr in args.addr:
            base = addr if addr.startswith("http") else f"http://{addr}"
            if not args.id:
                raise SystemExit("forensics --addr requires --id "
                                 "INCIDENT_ID (list ids with "
                                 "`geomesa-tpu doctor --addr ...`)")
            try:
                with urllib.request.urlopen(
                        base + f"/incidents/{args.id}/bundle",
                        timeout=5) as r:
                    node = json.loads(r.read().decode())
            except OSError as e:
                node = {"error": str(e)}
            if len(args.addr) == 1:
                out.update(node)
            else:
                out.setdefault("nodes", {})[addr] = node
        print(json.dumps(out, indent=2, default=str))
        return
    from geomesa_tpu.obs.forensics import FORENSICS
    if args.id:
        bundle = FORENSICS.get(args.id)
        if bundle is None:
            raise SystemExit(f"no forensic bundle for {args.id}")
        print(json.dumps(bundle, indent=2, default=str))
        return
    bundles = FORENSICS.list()
    if not bundles:
        print("forensics: no bundles captured "
              "(the doctor opens them with incidents)")
        return
    for b in bundles:
        print(f"{b['incident_id']:<10} {b.get('rule', '?'):<20} "
              f"captured_ms={b.get('captured_ms')} "
              f"events={b.get('events')} series={b.get('series')} "
              f"cause={b.get('cause')}")


def cmd_remove_schema(args):
    store = _load(args.store, must_exist=True)
    store.remove_schema(args.feature)
    npz = os.path.join(args.store, f"{args.feature}.npz")
    if os.path.exists(npz):
        os.remove(npz)
    _save(store, args.store)
    print(f"Removed schema {args.feature!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="geomesa-tpu",
        description="TPU-native spatio-temporal datastore tools")
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp, feature=True):
        sp.add_argument("-s", "--store", required=True,
                        help="store (checkpoint) directory")
        if feature:
            sp.add_argument("-f", "--feature", required=True,
                            help="feature type name")

    sp = sub.add_parser("create-schema", help="register a feature type")
    common(sp)
    sp.add_argument("--spec", required=True, help="SFT spec string")
    sp.set_defaults(fn=cmd_create_schema)

    sp = sub.add_parser("list", help="list feature types")
    common(sp, feature=False)
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("describe", help="describe a feature type")
    common(sp)
    sp.set_defaults(fn=cmd_describe)

    sp = sub.add_parser("ingest", help="ingest files through a converter")
    common(sp)
    sp.add_argument("files", nargs="+")
    sp.add_argument("--converter", help="converter config JSON file")
    sp.add_argument("--infer", action="store_true",
                    help="infer schema + converter from the data")
    sp.add_argument("--format", choices=("csv", "tsv", "json"))
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser("count", help="count matching features")
    common(sp)
    sp.add_argument("-q", "--cql", help="ECQL filter")
    sp.set_defaults(fn=cmd_count)

    sp = sub.add_parser("export", help="export matching features")
    common(sp)
    sp.add_argument("-q", "--cql")
    from geomesa_tpu.io.export import FORMATS as _EXPORT_FORMATS
    sp.add_argument("--format", default="csv", choices=_EXPORT_FORMATS,
                    help="|".join(_EXPORT_FORMATS))
    sp.add_argument("-o", "--output")
    sp.add_argument("--max", type=int)
    sp.add_argument("--select",
                    help="projection list, e.g. "
                         "'st_centroid(geom) AS c, val' (st_* terms "
                         "evaluate through the geometry kernels; "
                         "geometry values export as WKT; csv/json only)")
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser("explain", help="show the query plan")
    common(sp)
    sp.add_argument("-q", "--cql", required=True)
    sp.set_defaults(fn=cmd_explain)

    sp = sub.add_parser("stats", help="summary statistics")
    common(sp)
    sp.add_argument("--kind", default="count",
                    choices=("count", "bounds", "minmax", "topk", "histogram"))
    sp.add_argument("--attr")
    sp.add_argument("--bins", type=int, default=20)
    sp.add_argument("-q", "--cql")
    sp.add_argument("--no-exact", action="store_true")
    sp.set_defaults(fn=cmd_stats)

    sp = sub.add_parser("delete", help="delete matching features")
    common(sp)
    sp.add_argument("-q", "--cql", required=True)
    sp.set_defaults(fn=cmd_delete)

    sp = sub.add_parser("remove-schema", help="drop a feature type")
    common(sp)
    sp.set_defaults(fn=cmd_remove_schema)

    sp = sub.add_parser(
        "age-off", help="drop features past their geomesa.feature.expiry TTL")
    common(sp)
    sp.set_defaults(fn=cmd_age_off)

    sp = sub.add_parser(
        "reindex",
        help="rebuild a type's device indexes build-then-swap (bumps the "
             "serving-cache generation)")
    common(sp)
    sp.set_defaults(fn=cmd_reindex)

    sp = sub.add_parser("config", help="list system properties")
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser(
        "recover",
        help="crash-recover a durable store directory (snapshot + WAL "
             "replay, torn tail truncated) and write a fresh snapshot")
    sp.add_argument("--dir", help="durability directory (as passed to "
                                  "TpuDataStore.open / params['durability'])")
    sp.add_argument("-s", "--store", help="alias for --dir")
    sp.set_defaults(fn=cmd_recover)

    sp = sub.add_parser(
        "debug", help="dump metrics, recent query traces, flight-recorder "
                      "events, SLO burn rates, per-kernel attribution, "
                      "scheduler state, admission/overload state, doctor "
                      "incidents, or the WAL segment inspector")
    sp.add_argument("what", choices=("metrics", "traces", "trace", "events",
                                     "slo", "kernels", "scheduler", "cache",
                                     "admission", "wal", "replication",
                                     "workload", "incidents", "cluster",
                                     "balance", "timeline"))
    sp.add_argument("-s", "--store", help="store to exercise first (optional)")
    sp.add_argument("-f", "--feature", help="feature type for the warm query "
                                            "(also the type filter for "
                                            "`debug events`)")
    sp.add_argument("-q", "--cql", help="ECQL filter for the warm query")
    sp.add_argument("--format", default=None,
                    choices=("json", "prometheus"))
    sp.add_argument("--limit", type=int, default=20,
                    help="max traces/events to print")
    # traces/events filters (the shared flight-recorder predicate)
    sp.add_argument("--slow", type=float, default=None, metavar="MS",
                    help="only records at least this slow")
    sp.add_argument("--errors", action="store_true",
                    help="only failed/shed/cancelled records")
    sp.add_argument("--kind", default=None,
                    help="match record kind / trace name / a span kind "
                         "present in the stage breakdown")
    sp.add_argument("--since-ms", type=float, default=None, dest="since_ms",
                    metavar="EPOCH_MS",
                    help="`debug events`/`debug timeline`: only records/"
                         "samples stamped at/after this wall time — the "
                         "same slice filter a forensic bundle uses")
    sp.add_argument("--name", default=None,
                    help="for `debug timeline`: only this history series")
    sp.add_argument("--tier", type=int, default=None, metavar="SECONDS",
                    help="for `debug timeline`: pick the ring tier by "
                         "interval (default: the finest)")
    sp.add_argument("--addr", action="append", default=None,
                    metavar="HOST:PORT",
                    help="a RUNNING node to query (repeatable). "
                         "`debug replication`: its /replication + "
                         "/healthz; `debug trace --fleet`: every node's "
                         "/traces?id= halves for the stitcher")
    sp.add_argument("--id", default=None, metavar="TRACE_ID",
                    help="for `debug trace`: the global trace id to "
                         "stitch (the `trace` field a routed count / "
                         "flight event / exemplar carries)")
    sp.add_argument("--fleet", action="store_true",
                    help="for `debug trace`: fetch remote halves from "
                         "every --addr node (without it, only this "
                         "process's rings are searched)")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser(
        "perfwatch",
        help="noise-aware bench regression gate: check a BENCH_summary "
             "against committed baselines (median + k*MAD), update the "
             "rolling baselines, or show them")
    sp.add_argument("action", choices=("check", "update", "show"))
    sp.add_argument("--run", default="BENCH_summary.json",
                    help="flat run summary emitted by bench.py")
    sp.add_argument("--baseline", default=os.path.join("perf",
                                                       "baselines.json"))
    sp.add_argument("--report", default=None,
                    help="write the structured regression report here")
    sp.add_argument("--k", type=float, default=None,
                    help="MAD multiplier (default GEOMESA_TPU_PERFWATCH_K)")
    sp.set_defaults(fn=cmd_perfwatch)

    sp = sub.add_parser("serve", help="REST/GeoJSON API over a store")
    sp.add_argument("-s", "--store", required=True)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8765)
    sp.add_argument("--durable", action="store_true",
                    help="treat -s as a durability dir (WAL + snapshots): "
                         "recover on open, log every mutation — required "
                         "for --ship-port")
    sp.add_argument("--ship-port", type=int, default=None, metavar="PORT",
                    help="also start the replication log shipper on this "
                         "port (0 = ephemeral); followers connect with "
                         "`geomesa-tpu replica --follow host:port`")
    sp.add_argument("--cell", default=None, metavar="SHARD=LO:HI",
                    help="bind this node to a shard cell: ingests whose "
                         "routing key falls outside [LO,HI] are refused "
                         "with 409 not_owner; the cell fence epoch "
                         "persists under the durable dir")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser(
        "router",
        help="run the fleet front door: health/lag-aware routed reads "
             "with cross-process trace propagation, plus the federated "
             "observability plane (/fleet, /fleet/metrics, the "
             "/traces?id= stitcher)")
    sp.add_argument("--endpoint", action="append", required=True,
                    metavar="NAME=HOST:PORT",
                    help="one serving node's REST base address "
                         "(repeatable; NAME= optional)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8760)
    sp.add_argument("--shard", action="append", default=None,
                    metavar="SHARD=LO:HI=MEMBER[,MEMBER...]",
                    help="one shard cell's key range + member endpoint "
                         "names (repeatable). With a topology the router "
                         "scatter-gathers counts across cells, routes "
                         "writes by key ownership, and serves /shards + "
                         "/handoff")
    sp.set_defaults(fn=cmd_router)

    sp = sub.add_parser(
        "doctor",
        help="fleet doctor verdicts: run the anomaly detectors and print "
             "one line per incident (what fired, since when, suspected "
             "cause, linked trace); --addr reads running nodes")
    sp.add_argument("--addr", action="append", default=None,
                    metavar="HOST:PORT",
                    help="a RUNNING node's REST address (repeatable); "
                         "without it the local process is diagnosed")
    sp.add_argument("--json", action="store_true",
                    help="print the raw incident JSON instead of verdicts")
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser(
        "forensics",
        help="forensic bundles the doctor froze at incident open "
             "(history slices, matching events, trace gids, workload "
             "hot_set): list bundles, or print one with --id; --addr "
             "reads a running node's /incidents/{id}/bundle")
    sp.add_argument("--id", default=None, metavar="INCIDENT_ID",
                    help="print this incident's bundle (e.g. inc-3)")
    sp.add_argument("--addr", action="append", default=None,
                    metavar="HOST:PORT",
                    help="a RUNNING node's REST address (repeatable); "
                         "requires --id")
    sp.set_defaults(fn=cmd_forensics)

    sp = sub.add_parser(
        "fleet",
        help="fleet-wide status: scrape every --addr node, merge "
             "client-side, print per-node health + fleet SLO burn rates")
    sp.add_argument("action", choices=("status",))
    sp.add_argument("--addr", action="append", metavar="HOST:PORT",
                    help="a fleet node's REST address (repeatable)")
    sp.add_argument("--json", action="store_true",
                    help="print the raw merged JSON instead of the table")
    sp.set_defaults(fn=cmd_fleet)

    sp = sub.add_parser(
        "soak",
        help="chaos-scored fleet soak: spawn primary+replicas+router as "
             "subprocesses, drive Zipf traffic through the router, run "
             "the chaos timeline, score the scoreboard")
    sp.add_argument("--mini", action="store_true",
                    help="CI-sized run (short phases); omit for the "
                         "nightly-length soak")
    sp.add_argument("--scoreboard", default=None, metavar="PATH",
                    help="scoreboard JSON path (default "
                         "SOAK_scoreboard.json; markdown lands beside it)")
    sp.add_argument("--half", choices=("both", "chaos", "clean"),
                    default="both",
                    help="run only one half (default: both)")
    sp.add_argument("--dir", default=None,
                    help="scratch directory for the fleet's durable "
                         "stores (default: a temp dir)")
    sp.set_defaults(fn=cmd_soak)

    sp = sub.add_parser(
        "soakcells",
        help="cluster chaos soak: two replicated shard cells + a "
             "shard-aware router as subprocesses, shard-routed writes, "
             "scatter-gather reads, cell failover / handoff / "
             "split-brain / shard_dark chaos, scored scoreboard")
    sp.add_argument("--mini", action="store_true",
                    help="CI-sized run (short phases)")
    sp.add_argument("--scoreboard", default=None, metavar="PATH",
                    help="scoreboard JSON path (default "
                         "SOAKCELLS_scoreboard.json)")
    sp.add_argument("--half", choices=("both", "chaos", "clean"),
                    default="both",
                    help="run only one half (default: both)")
    sp.add_argument("--dir", default=None,
                    help="scratch directory for the cells' durable "
                         "stores (default: a temp dir)")
    sp.set_defaults(fn=cmd_soakcells)

    sp = sub.add_parser(
        "cluster-dryrun",
        help="2-process CPU cluster dryrun: spawn worker subprocesses, "
             "shard one table across them by Morton key-range, check "
             "psum counts / density / merged selects byte-equal against "
             "the single-process oracle")
    sp.add_argument("--procs", type=int, default=2,
                    help="number of worker processes (default 2)")
    sp.add_argument("--n", type=int, default=20000,
                    help="corpus rows (default 20000)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--timeout-s", type=float, default=420.0,
                    help="hard deadline for the worker fleet")
    sp.add_argument("--out", default=None, metavar="DIR",
                    help="directory for rank reports / logs / "
                         "dryrun_report.json (default: a temp dir)")
    sp.add_argument("--no-web", action="store_true",
                    help="skip the per-rank REST server + federation "
                         "registration checks")
    sp.set_defaults(fn=cmd_cluster_dryrun)

    sp = sub.add_parser(
        "replica",
        help="run a read replica: follow a primary's log shipper, apply "
             "shipped WAL frames into a local durable copy, optionally "
             "serve the read-only REST API")
    sp.add_argument("--dir", required=True,
                    help="local durable store directory for this replica")
    sp.add_argument("--follow", required=True, metavar="HOST:PORT",
                    help="the primary's log-shipper address")
    sp.add_argument("--id", default=None, help="stable follower id "
                    "(default: the directory basename)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0,
                    help="serve the read-only REST API here (0 = no HTTP)")
    sp.add_argument("--cell", default=None, metavar="SHARD=LO:HI",
                    help="bind this replica to its shard cell (see "
                         "`serve --cell`); on promote it inherits the "
                         "cell's ingest gate + fence")
    sp.set_defaults(fn=cmd_replica)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
