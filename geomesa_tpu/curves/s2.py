"""S2 curve: Hilbert ordering on the quadrilateralized-sphere cube.

≙ reference ``S2SFC`` (/root/reference/geomesa-z3/src/main/scala/org/
locationtech/geomesa/curve/S2SFC.scala:17,27,61), which delegates to Google's
S2 library (``S2CellId``/``S2RegionCoverer``). Like the Morton interleave the
reference takes from sfcurve, the curve math is implemented here directly —
vectorized numpy over the standard public cell-id scheme:

  lon/lat → unit vector → cube face (6) → quadratic (s,t) projection →
  level-30 (i,j) ints → Hilbert position via the 4-cell lookup recursion →
  63-bit key  [face:3][hilbert_pos:60]

Covering decomposes a lat/lon box into cell-id ranges by BFS over the cell
tree with a CONSERVATIVE lat/lon-rectangle test per cell (corner rect padded
by the cell's angular size, full-longitude for pole cells). The cover is a
superset by construction — exactness always comes from the fp62 device
masks, so cover slop costs only scan width, never correctness.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curves.ranges import IndexRange, merge_ranges

MAX_LEVEL = 30

# Hilbert sub-cell traversal: for each orientation state (0..3), the order
# in which the four (i,j) quadrants are visited, and the child orientation.
# This is the standard 2-bit Hilbert recursion (the same tables S2 uses,
# expressed directly).
_POS_TO_IJ = np.array([
    [0, 1, 3, 2],   # state 0: visits (0,0),(0,1),(1,1),(1,0)
    [0, 2, 3, 1],   # state 1 (swapped axes)
    [3, 2, 0, 1],   # state 2 (inverted)
    [3, 1, 0, 2],   # state 3 (swapped+inverted)
], dtype=np.int64)
_IJ_TO_POS = np.zeros((4, 4), dtype=np.int64)
for _s in range(4):
    for _p in range(4):
        _IJ_TO_POS[_s, _POS_TO_IJ[_s, _p]] = _p
# orientation transition: state x position-visited -> child state
_NEXT_STATE = np.array([
    [1, 0, 0, 3],
    [0, 1, 1, 2],
    [3, 2, 2, 1],
    [2, 3, 3, 0],
], dtype=np.int64)


def _face_uv(x, y, z):
    """Unit-vector → (face, u, v) with the largest-axis rule."""
    ax, ay, az = np.abs(x), np.abs(y), np.abs(z)
    face = np.where(ax >= np.maximum(ay, az),
                    np.where(x >= 0, 0, 3),
                    np.where(ay >= az,
                             np.where(y >= 0, 1, 4),
                             np.where(z >= 0, 2, 5)))
    u = np.empty_like(x)
    v = np.empty_like(x)
    for f, (un, ud, vn, vd) in _FACE_AXES.items():
        m = face == f
        u[m] = un(x[m], y[m], z[m]) / ud(x[m], y[m], z[m])
        v[m] = vn(x[m], y[m], z[m]) / vd(x[m], y[m], z[m])
    return face, u, v


# per-face (u_num, u_den, v_num, v_den) axis selectors (S2's canonical frame)
_FACE_AXES = {
    0: (lambda x, y, z: y, lambda x, y, z: x,
        lambda x, y, z: z, lambda x, y, z: x),
    1: (lambda x, y, z: -x, lambda x, y, z: y,
        lambda x, y, z: z, lambda x, y, z: y),
    2: (lambda x, y, z: -x, lambda x, y, z: z,
        lambda x, y, z: -y, lambda x, y, z: z),
    3: (lambda x, y, z: z, lambda x, y, z: -x,
        lambda x, y, z: y, lambda x, y, z: -x),
    4: (lambda x, y, z: z, lambda x, y, z: -y,
        lambda x, y, z: -x, lambda x, y, z: -y),
    5: (lambda x, y, z: -y, lambda x, y, z: -z,
        lambda x, y, z: -x, lambda x, y, z: -z),
}


def _uv_to_st(u):
    """S2 quadratic projection (area-equalizing). Both where-branches
    evaluate, so clamp the radicands (negative only in the discarded lane)."""
    return np.where(u >= 0,
                    0.5 * np.sqrt(np.maximum(1 + 3 * u, 0.0)),
                    1 - 0.5 * np.sqrt(np.maximum(1 - 3 * u, 0.0)))


def _st_to_uv(s):
    return np.where(s >= 0.5,
                    (1.0 / 3.0) * (4 * s * s - 1),
                    (1.0 / 3.0) * (1 - 4 * (1 - s) * (1 - s)))


def lonlat_to_cell(lon, lat, level: int = MAX_LEVEL):
    """(face, i, j) ints at ``level`` for lon/lat degrees (vectorized)."""
    lon = np.radians(np.asarray(lon, dtype=np.float64))
    lat = np.radians(np.asarray(lat, dtype=np.float64))
    cl = np.cos(lat)
    x, y, z = cl * np.cos(lon), cl * np.sin(lon), np.sin(lat)
    face, u, v = _face_uv(x, y, z)
    size = 1 << level
    i = np.clip((_uv_to_st(u) * size).astype(np.int64), 0, size - 1)
    j = np.clip((_uv_to_st(v) * size).astype(np.int64), 0, size - 1)
    return face.astype(np.int64), i, j


def hilbert_pos(i, j, level: int = MAX_LEVEL):
    """(i, j) → Hilbert position (2*level bits), vectorized lookup descent."""
    i = np.asarray(i, dtype=np.int64)
    j = np.asarray(j, dtype=np.int64)
    pos = np.zeros_like(i)
    state = np.zeros_like(i)
    for l in range(level - 1, -1, -1):
        q = (((i >> l) & 1) << 1) | ((j >> l) & 1)  # quadrant bits (i major)
        p = _IJ_TO_POS[state, q]
        pos = (pos << 2) | p
        state = _NEXT_STATE[state, p]
    return pos


def hilbert_ij(pos, level: int = MAX_LEVEL):
    """Inverse of :func:`hilbert_pos`."""
    pos = np.asarray(pos, dtype=np.int64)
    i = np.zeros_like(pos)
    j = np.zeros_like(pos)
    state = np.zeros_like(pos)
    for l in range(level - 1, -1, -1):
        p = (pos >> (2 * l)) & 3
        q = _POS_TO_IJ[state, p]
        i = (i << 1) | (q >> 1)
        j = (j << 1) | (q & 1)
        state = _NEXT_STATE[state, p]
    return i, j


def cell_id(lon, lat) -> np.ndarray:
    """63-bit sort key: [face:3][hilbert_pos:60] at level 30."""
    face, i, j = lonlat_to_cell(lon, lat)
    return (face << 60) | hilbert_pos(i, j)


def cell_center(face: int, i: int, j: int, level: int) -> Tuple[float, float]:
    """lon/lat degrees of a cell center (host scalar; covering/tests)."""
    size = 1 << level
    s = (i + 0.5) / size
    t = (j + 0.5) / size
    return _st_lonlat(face, s, t)


def _st_lonlat(face, s, t):
    u = _st_to_uv(np.asarray(s, dtype=np.float64))
    v = _st_to_uv(np.asarray(t, dtype=np.float64))
    one = np.ones_like(u)
    # inverse of the _FACE_AXES forward ratios with the major axis at ±1
    if face == 0:
        x, y, z = one, u, v
    elif face == 1:
        x, y, z = -u, one, v
    elif face == 2:
        x, y, z = -u, -v, one
    elif face == 3:
        x, y, z = -one, v, u
    elif face == 4:
        x, y, z = -v, -one, u
    else:
        x, y, z = -v, -u, -one
    lon = np.degrees(np.arctan2(y, x))
    lat = np.degrees(np.arctan2(z, np.hypot(x, y)))
    return lon, lat


class S2SFC:
    """S2 curve facade mirroring the SFC interface (index / ranges)."""

    _cache: dict = {}

    def __init__(self, level: int = MAX_LEVEL):
        self.level = level

    @classmethod
    def apply(cls, level: int = MAX_LEVEL) -> "S2SFC":
        if level not in cls._cache:
            cls._cache[level] = cls(level)
        return cls._cache[level]

    def index(self, lon, lat, lenient: bool = False) -> np.ndarray:
        lon = np.asarray(lon, dtype=np.float64)
        lat = np.asarray(lat, dtype=np.float64)
        if lenient:
            lon = np.clip(lon, -180.0, 180.0)
            lat = np.clip(lat, -90.0, 90.0)
        elif np.any((lon < -180) | (lon > 180) | (lat < -90) | (lat > 90)):
            raise ValueError("Value(s) out of bounds for s2 index")
        return cell_id(lon, lat)

    def invert(self, ids) -> Tuple[np.ndarray, np.ndarray]:
        ids = np.asarray(ids, dtype=np.int64)
        face = ids >> 60
        i, j = hilbert_ij(ids & ((1 << 60) - 1))
        size = 1 << MAX_LEVEL
        out_lon = np.empty(len(ids))
        out_lat = np.empty(len(ids))
        for f in range(6):
            m = face == f
            if not m.any():
                continue
            lon, lat = _st_lonlat(f, (i[m] + 0.5) / size, (j[m] + 0.5) / size)
            out_lon[m] = lon
            out_lat[m] = lat
        return out_lon, out_lat

    # -- covering -----------------------------------------------------------

    def ranges(self, boxes: Sequence[Tuple[float, float, float, float]],
               max_ranges: Optional[int] = None,
               max_level: int = 18) -> List[IndexRange]:
        """Cell-id ranges covering the union of lon/lat boxes.

        BFS over the cell tree with a conservative per-cell lat/lon rect
        (corner rect padded by the cell's angular extent; pole cells span
        all longitudes) — a SUPERSET of every cell intersecting a box. The
        fp62 device masks re-check exactly, so slop only widens the scan.
        """
        max_ranges = max_ranges or 2000
        boxes = [tuple(map(float, b)) for b in boxes]
        out: List[IndexRange] = []
        queue: List[Tuple[int, int, int, int]] = [
            (f, 0, 0, 0) for f in range(6)]
        while queue:
            nxt: List[Tuple[int, int, int, int]] = []
            for face, i, j, level in queue:
                rect = self._cell_rect(face, i, j, level)
                if not any(_rect_overlap(rect, b) for b in boxes):
                    continue
                if level >= max_level or len(out) + len(nxt) >= max_ranges:
                    out.append(self._cell_range(face, i, j, level))
                    continue
                for di in (0, 1):
                    for dj in (0, 1):
                        nxt.append((face, (i << 1) | di, (j << 1) | dj,
                                    level + 1))
            queue = nxt
        return merge_ranges(out)

    # 5 samples per edge: the lat/lon extremes of a cell lie on its
    # boundary (the only interior critical points are the poles, which sit
    # at cell corners for level >= 1), and denser boundary sampling shrinks
    # the conservative pad from 2 cells (r4) to a quarter cell — measured
    # cover slop 1.37x -> 1.10x of true rows on 1M uniform points over
    # random boxes (z2 on the same boxes: 1.02x); superset property pinned
    # by the randomized covers in tests/test_s2.py
    _EDGE_K = np.linspace(0.0, 1.0, 5)
    _EDGE_SS = np.concatenate([_EDGE_K, _EDGE_K, np.zeros(5), np.ones(5)])
    _EDGE_TT = np.concatenate([np.zeros(5), np.ones(5), _EDGE_K, _EDGE_K])

    def _cell_rect(self, face, i, j, level):
        """Conservative (lon0, lat0, lon1, lat1) bounds of a cell;
        (-180, lat0, 180, lat1) for pole-adjacent/antimeridian cells."""
        if level == 0:
            # boundary sampling is blind to the poles at level 0 — they sit
            # INSIDE faces 2/5, not on an edge (from level 1 down they are
            # cell corners). Six whole-sphere rects cost the BFS nothing.
            return (-180.0, -90.0, 180.0, 90.0)
        size = 1 << level
        lon, lat = _st_lonlat(face, (i + self._EDGE_SS) / size,
                              (j + self._EDGE_TT) / size)
        cell = 90.0 / (1 << level)
        pad = cell * 0.25 + 1e-9
        lat0 = max(-90.0, float(lat.min()) - pad)
        lat1 = min(90.0, float(lat.max()) + pad)
        lon0, lon1 = float(lon.min()), float(lon.max())
        # the pole guard stays at the OLD 2-cell width on purpose: near the
        # pole the sampled lon range is meaningless however small the lat
        # pad is, so widen to all longitudes well before it matters
        if lat1 >= 90.0 - 2.0 * cell or lat0 <= -90.0 + 2.0 * cell \
                or (lon1 - lon0) > 180.0:
            return (-180.0, lat0, 180.0, lat1)
        max_abs_lat = max(abs(lat0), abs(lat1))
        lon_pad = min(180.0, pad / max(0.05, float(np.cos(np.radians(max_abs_lat)))))
        return (max(-180.0, lon0 - lon_pad), lat0,
                min(180.0, lon1 + lon_pad), lat1)

    def _cell_range(self, face, i, j, level) -> IndexRange:
        """Leaf-id interval covered by a cell."""
        shift = 2 * (MAX_LEVEL - level)
        pos = hilbert_pos(np.int64(i), np.int64(j), level)
        lo = (np.int64(face) << 60) | (pos << shift)
        return IndexRange(int(lo), int(lo + (1 << shift) - 1), False)


def _rect_overlap(a, b) -> bool:
    ax0, ay0, ax1, ay1 = a
    bx0, by0, bx1, by1 = b
    return ax0 <= bx1 and ax1 >= bx0 and ay0 <= by1 and ay1 >= by0
