"""Host-side z-range cover: decompose integer query boxes into Morton ranges.

The reference gets this from the external sfcurve library (``Z2.zranges`` /
``Z3.zranges``, used at /root/reference/geomesa-z3/.../Z2SFC.scala:52 and
Z3SFC.scala:61). This is a from-scratch implementation of the same idea: a
breadth-first quad/octree traversal that emits a z-interval for each tree cell
fully contained in (or, at the recursion budget, overlapping) any query box,
then sort-merges adjacent intervals.

This code is branchy and recursive by nature, so it stays on the host (plain
Python/numpy) — it produces at most ``max_ranges`` ranges (default mirrors the
reference's ``geomesa.scan.ranges.target`` = 2000, QueryProperties.scala:22),
which then parameterize the device scan kernels.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from geomesa_tpu.curves import zorder


@dataclass(frozen=True)
class IndexRange:
    """Inclusive z-interval [lower, upper]; ``contained`` means every z in the
    interval satisfies the query box (no further filtering needed)."""

    lower: int
    upper: int
    contained: bool = False


def merge_ranges(ranges: List[IndexRange]) -> List[IndexRange]:
    """Sort and merge adjacent/overlapping ranges (sfcurve/XZ2SFC merge rule:
    merge when lower <= current.upper + 1; merged range is contained only if
    both inputs were)."""
    if not ranges:
        return []
    ranges = sorted(ranges, key=lambda r: (r.lower, r.upper))
    out: List[IndexRange] = []
    cur = ranges[0]
    for r in ranges[1:]:
        if r.lower <= cur.upper + 1:
            cur = IndexRange(cur.lower, max(cur.upper, r.upper), cur.contained and r.contained)
        else:
            out.append(cur)
            cur = r
    out.append(cur)
    return out


_EMPTY_COVER = (np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, bool))


def merge_range_arrays(lo: np.ndarray, hi: np.ndarray, cont: np.ndarray):
    """Vectorized sort+merge of inclusive (lo, hi, contained) range arrays
    (same rule as ``merge_ranges``; arrays in, arrays out — no per-range
    Python objects on the query-planning hot path)."""
    if len(lo) == 0:
        return _EMPTY_COVER
    order = np.lexsort((hi, lo))
    lo, hi, cont = lo[order], hi[order], cont[order]
    cmax = np.maximum.accumulate(hi)
    new = np.empty(len(lo), bool)
    new[0] = True
    np.greater(lo[1:], cmax[:-1] + 1, out=new[1:])
    starts = np.flatnonzero(new)
    return (lo[starts], np.maximum.reduceat(hi, starts),
            np.logical_and.reduceat(cont, starts))


def _zranges_arrays(
    boxes: Sequence[Sequence[Tuple[int, int]]],
    bits: int,
    dims: int,
    max_ranges: int,
    max_levels: int,
):
    """Generic D-dimensional Morton cover → merged (lo, hi, contained)
    inclusive z-interval arrays covering the union of boxes.

    boxes: per-box, per-dim inclusive int bounds [(lo, hi), ...] in
    normalized int space. The native C++ pass (gm_zranges) runs when
    available (~50us — the cover sits on the cold-query planning path);
    the fallback is a level-synchronous vectorized numpy BFS. Budget rule
    mirrors sfcurve's maxRanges stop: when expanding the next level would
    exceed the budget, remaining overlapping cells flush as coarse
    (uncontained) ranges.
    """
    if not boxes:
        return _EMPTY_COVER
    interleave = {2: zorder.z2_encode, 3: zorder.z3_encode}[dims]
    max_levels = min(max_levels, bits)

    blo = np.array([[d[0] for d in b] for b in boxes], dtype=np.int64)  # (B,D)
    bhi = np.array([[d[1] for d in b] for b in boxes], dtype=np.int64)

    from geomesa_tpu import native
    res = native.zranges(blo, bhi, dims, bits, max_ranges, max_levels)
    if res is not None:
        return res

    child_bits = np.array(
        [[(c >> d) & 1 for d in range(dims)] for c in range(1 << dims)],
        dtype=np.int64)  # (fan, D)

    out_lo: List[np.ndarray] = []
    out_hi: List[np.ndarray] = []
    out_cont: List[np.ndarray] = []

    def emit(cells: np.ndarray, level: int, contained: np.ndarray) -> None:
        if len(cells) == 0:
            return
        shift = bits - level
        lo_coords = cells << shift
        zlo = interleave(*(lo_coords[:, d] for d in range(dims))).astype(np.int64)
        out_lo.append(zlo)
        out_hi.append(zlo + ((1 << (dims * shift)) - 1))
        out_cont.append(np.broadcast_to(contained, (len(cells),)).copy()
                        if contained.ndim == 0 else contained)

    cells = np.zeros((1, dims), dtype=np.int64)
    level = 0
    emitted = 0
    while len(cells):
        shift = bits - level
        clo = (cells << shift)[:, None, :]                 # (C,1,D)
        chi = (((cells + 1) << shift) - 1)[:, None, :]
        inside = ((blo[None] <= clo) & (chi <= bhi[None])).all(-1).any(-1)
        touches = ((chi >= blo[None]) & (clo <= bhi[None])).all(-1).any(-1)
        overlap = touches & ~inside

        emit(cells[inside], level, np.True_)
        emitted += int(inside.sum())
        live = cells[overlap]
        n_live = len(live)
        if n_live == 0:
            break
        if level >= max_levels or emitted + n_live * (1 << dims) > max_ranges:
            emit(live, level, np.False_)  # budget/depth stop: coarse cover
            break
        cells = ((live[:, None, :] << 1) | child_bits[None]).reshape(-1, dims)
        level += 1

    if not out_lo:
        return _EMPTY_COVER
    return merge_range_arrays(np.concatenate(out_lo), np.concatenate(out_hi),
                              np.concatenate(out_cont))


def to_ranges(arrays) -> List[IndexRange]:
    """(lo, hi, contained) arrays → IndexRange list (the object-form API)."""
    lo, hi, cont = arrays
    return [IndexRange(int(l), int(h), bool(c))
            for l, h, c in zip(lo, hi, cont)]


def _reshape_2d(boxes):
    return [((xlo, xhi), (ylo, yhi)) for xlo, ylo, xhi, yhi in boxes]


def _reshape_3d(boxes):
    return [((xlo, xhi), (ylo, yhi), (tlo, thi))
            for xlo, ylo, tlo, xhi, yhi, thi in boxes]


def zranges_2d(
    boxes: Sequence[Tuple[int, int, int, int]],
    bits: int = 31,
    max_ranges: int = 2000,
    max_levels: int = 64,
) -> List[IndexRange]:
    """2-D cover. boxes = (xlo, ylo, xhi, yhi) inclusive normalized ints."""
    return to_ranges(zranges_2d_arrays(boxes, bits, max_ranges, max_levels))


def zranges_3d(
    boxes: Sequence[Tuple[int, int, int, int, int, int]],
    bits: int = 21,
    max_ranges: int = 2000,
    max_levels: int = 64,
) -> List[IndexRange]:
    """3-D cover. boxes = (xlo, ylo, tlo, xhi, yhi, thi) inclusive ints."""
    return to_ranges(zranges_3d_arrays(boxes, bits, max_ranges, max_levels))


def zranges_2d_arrays(boxes, bits: int = 31, max_ranges: int = 2000,
                      max_levels: int = 64):
    """Array-form 2-D cover: merged (lo, hi, contained) — the hot-path form
    consumed directly by prune.ranges_to_slices."""
    return _zranges_arrays(_reshape_2d(boxes), bits, 2, max_ranges, max_levels)


def zranges_3d_arrays(boxes, bits: int = 21, max_ranges: int = 2000,
                      max_levels: int = 64):
    """Array-form 3-D cover: merged (lo, hi, contained)."""
    return _zranges_arrays(_reshape_3d(boxes), bits, 3, max_ranges, max_levels)
