"""Host-side z-range cover: decompose integer query boxes into Morton ranges.

The reference gets this from the external sfcurve library (``Z2.zranges`` /
``Z3.zranges``, used at /root/reference/geomesa-z3/.../Z2SFC.scala:52 and
Z3SFC.scala:61). This is a from-scratch implementation of the same idea: a
breadth-first quad/octree traversal that emits a z-interval for each tree cell
fully contained in (or, at the recursion budget, overlapping) any query box,
then sort-merges adjacent intervals.

This code is branchy and recursive by nature, so it stays on the host (plain
Python/numpy) — it produces at most ``max_ranges`` ranges (default mirrors the
reference's ``geomesa.scan.ranges.target`` = 2000, QueryProperties.scala:22),
which then parameterize the device scan kernels.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import List, Sequence, Tuple

from geomesa_tpu.curves import zorder


@dataclass(frozen=True)
class IndexRange:
    """Inclusive z-interval [lower, upper]; ``contained`` means every z in the
    interval satisfies the query box (no further filtering needed)."""

    lower: int
    upper: int
    contained: bool = False


def merge_ranges(ranges: List[IndexRange]) -> List[IndexRange]:
    """Sort and merge adjacent/overlapping ranges (sfcurve/XZ2SFC merge rule:
    merge when lower <= current.upper + 1; merged range is contained only if
    both inputs were)."""
    if not ranges:
        return []
    ranges = sorted(ranges, key=lambda r: (r.lower, r.upper))
    out: List[IndexRange] = []
    cur = ranges[0]
    for r in ranges[1:]:
        if r.lower <= cur.upper + 1:
            cur = IndexRange(cur.lower, max(cur.upper, r.upper), cur.contained and r.contained)
        else:
            out.append(cur)
            cur = r
    out.append(cur)
    return out


def _zranges(
    boxes: Sequence[Sequence[Tuple[int, int]]],
    bits: int,
    dims: int,
    max_ranges: int,
    max_levels: int,
) -> List[IndexRange]:
    """Generic D-dimensional Morton cover.

    boxes: per-box, per-dim inclusive int bounds [(lo, hi), ...] in normalized
    int space. Returns merged inclusive z ranges covering the union of boxes.
    """
    if not boxes:
        return []
    interleave = {2: lambda c: int(zorder.z2_encode(c[0], c[1])),
                  3: lambda c: int(zorder.z3_encode(c[0], c[1], c[2]))}[dims]

    max_levels = min(max_levels, bits)
    out: List[IndexRange] = []

    def emit(prefix: Tuple[int, ...], level: int, contained: bool) -> None:
        shift = bits - level
        lo = tuple(p << shift for p in prefix)
        zlo = interleave(lo)
        zhi = zlo + (1 << (dims * shift)) - 1
        out.append(IndexRange(zlo, zhi, contained))

    def classify(prefix: Tuple[int, ...], level: int) -> int:
        """2 = contained in some box, 1 = overlaps some box, 0 = disjoint."""
        shift = bits - level
        cell = [(p << shift, ((p + 1) << shift) - 1) for p in prefix]
        overlapped = False
        for box in boxes:
            inside = True
            touches = True
            for (clo, chi), (blo, bhi) in zip(cell, box):
                if not (blo <= clo and chi <= bhi):
                    inside = False
                if chi < blo or bhi < clo:
                    touches = False
                    break
            if inside:
                return 2
            if touches:
                overlapped = True
        return 1 if overlapped else 0

    # BFS, level by level; when the budget is hit, flush remaining cells as
    # overlapping (coarse) ranges — same spirit as sfcurve's maxRanges stop.
    queue: deque = deque([(tuple([0] * dims), 0)])
    while queue:
        prefix, level = queue.popleft()
        status = classify(prefix, level)
        if status == 0:
            continue
        if status == 2 or level >= max_levels or (len(out) + len(queue)) >= max_ranges:
            emit(prefix, level, status == 2)
            continue
        for child in range(1 << dims):
            child_prefix = tuple((p << 1) | ((child >> d) & 1) for d, p in enumerate(prefix))
            queue.append((child_prefix, level + 1))

    return merge_ranges(out)


def zranges_2d(
    boxes: Sequence[Tuple[int, int, int, int]],
    bits: int = 31,
    max_ranges: int = 2000,
    max_levels: int = 64,
) -> List[IndexRange]:
    """2-D cover. boxes = (xlo, ylo, xhi, yhi) inclusive normalized ints."""
    reshaped = [((xlo, xhi), (ylo, yhi)) for xlo, ylo, xhi, yhi in boxes]
    return _zranges(reshaped, bits, 2, max_ranges, max_levels)


def zranges_3d(
    boxes: Sequence[Tuple[int, int, int, int, int, int]],
    bits: int = 21,
    max_ranges: int = 2000,
    max_levels: int = 64,
) -> List[IndexRange]:
    """3-D cover. boxes = (xlo, ylo, tlo, xhi, yhi, thi) inclusive ints."""
    reshaped = [((xlo, xhi), (ylo, yhi), (tlo, thi)) for xlo, ylo, tlo, xhi, yhi, thi in boxes]
    return _zranges(reshaped, bits, 3, max_ranges, max_levels)
