"""XZ-ordering curves for geometries with extent (lines/polygons).

Re-implementation of 'XZ-Ordering: A Space-Filling Curve for Objects with
Spatial Extension' (Böhm, Klump, Kriegel), matching the reference semantics at
/root/reference/geomesa-z3/.../XZ2SFC.scala and XZ3SFC.scala:

  - a bbox is indexed by the sequence code of the *enlarged* tree cell
    (cell doubled in each dim) that contains it; the code-length l is derived
    from the bbox's max extent (l1 or l1+1 via the two-cell predicate)
  - query decomposition is a BFS over tree cells: cells whose enlarged bounds
    are contained in a query window emit a "contained" code interval (lemma 3
    of the paper); overlapping cells emit their single code and recurse
  - ranges are sort-merged (adjacent codes coalesce)

One generic implementation covers both the 2-D quadtree (XZ2) and the 3-D
octree (XZ3, spatial + binned-time). ``index`` is vectorized over numpy bbox
arrays (the write path encodes millions of geometries at once); ``ranges``
stays scalar host code, as in the reference.
"""

from __future__ import annotations

import math
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset
from geomesa_tpu.curves.ranges import IndexRange, merge_ranges


class XZSFC:
    """Generic D-dimensional XZ curve over user-space bounds per dim."""

    def __init__(self, g: int, bounds: Sequence[Tuple[float, float]]):
        self.g = int(g)
        self.dims = len(bounds)
        self.bounds = [(float(lo), float(hi)) for lo, hi in bounds]
        self._los = np.array([b[0] for b in self.bounds])
        self._sizes = np.array([b[1] - b[0] for b in self.bounds])
        self.fan = 1 << self.dims  # children per cell: 4 (quad) or 8 (oct)

    # -- indexing ----------------------------------------------------------

    def _normalize(self, mins: np.ndarray, maxs: np.ndarray, lenient: bool):
        """User-space (N, D) bbox corners → [0,1] normalized."""
        if np.any(mins > maxs):
            raise ValueError("Bounds must be ordered (min <= max per dim)")
        oob = (mins < self._los) | (maxs > self._los + self._sizes)
        if np.any(oob):
            if not lenient:
                raise ValueError("Values out of bounds for xz index")
            mins = np.clip(mins, self._los, self._los + self._sizes)
            maxs = np.clip(maxs, self._los, self._los + self._sizes)
        return (mins - self._los) / self._sizes, (maxs - self._los) / self._sizes

    def _seq_term(self, i) -> "int | np.ndarray":
        """Number of descendants-plus-self below one quadrant at level i:
        (fan^(g-i) - 1) / (fan - 1). Exact in int64 for g <= 21 (2D) / 14 (3D);
        we use Python/object ints via numpy int64 — g defaults keep it safe."""
        return (self.fan ** (self.g - i) - 1) // (self.fan - 1)

    def index(self, mins, maxs, lenient: bool = False) -> np.ndarray:
        """Vectorized: (N, D) bbox min/max corners → (N,) int64 codes."""
        mins = np.atleast_2d(np.asarray(mins, dtype=np.float64))
        maxs = np.atleast_2d(np.asarray(maxs, dtype=np.float64))
        nmins, nmaxs = self._normalize(mins, maxs, lenient)
        n = nmins.shape[0]

        # code length: l1 = floor(log(maxDim)/log(0.5)); maxDim == 0 → g
        ext = np.max(nmaxs - nmins, axis=1)
        with np.errstate(divide="ignore"):
            l1 = np.floor(np.log(ext) / math.log(0.5))
        l1 = np.where(np.isfinite(l1), l1, self.g).astype(np.int64)
        l1 = np.minimum(l1, self.g)

        # two-cell predicate: bump to l1+1 when the bbox spans at most two
        # cells of the finer resolution in every dim (XZ2SFC.scala:66-74)
        w2 = np.power(0.5, (l1 + 1).astype(np.float64))[:, None]
        fits = nmaxs <= np.floor(nmins / w2) * w2 + 2 * w2
        length = np.where((l1 < self.g) & np.all(fits, axis=1), l1 + 1, l1)

        # sequence code: walk the tree `length` levels toward the bbox's min
        # corner (XZ2SFC.sequenceCode, :264-286), all features in lockstep
        cs = np.zeros(n, dtype=np.int64)
        lo = np.zeros((n, self.dims))
        hi = np.ones((n, self.dims))
        pos = nmins
        for i in range(self.g):
            active = i < length
            center = (lo + hi) / 2.0
            upper = pos >= center  # per-dim quadrant bit
            quadrant = np.zeros(n, dtype=np.int64)
            for d in range(self.dims):
                quadrant |= upper[:, d].astype(np.int64) << d
            cs = np.where(active, cs + 1 + quadrant * self._seq_term(i), cs)
            sel = active[:, None] & upper
            lo = np.where(sel, center, lo)
            hi = np.where(active[:, None] & ~upper, center, hi)
        return cs

    # -- query decomposition ----------------------------------------------

    def ranges(
        self,
        queries: Sequence[Sequence[float]],
        max_ranges: Optional[int] = None,
    ) -> List[IndexRange]:
        """Cover query windows with code ranges.

        queries: each (min_0..min_D-1, max_0..max_D-1) in user space.
        """
        max_ranges = max_ranges or (1 << 62)
        windows = []
        for q in queries:
            mins = np.asarray(q[: self.dims], dtype=np.float64)
            maxs = np.asarray(q[self.dims:], dtype=np.float64)
            nmins, nmaxs = self._normalize(mins[None, :], maxs[None, :], lenient=False)
            windows.append((nmins[0], nmaxs[0]))

        out: List[IndexRange] = []

        def seq_code(point: np.ndarray, length: int) -> int:
            cs = 0
            lo = np.zeros(self.dims)
            hi = np.ones(self.dims)
            for i in range(length):
                center = (lo + hi) / 2.0
                quadrant = 0
                for d in range(self.dims):
                    if point[d] >= center[d]:
                        quadrant |= 1 << d
                        lo[d] = center[d]
                    else:
                        hi[d] = center[d]
                cs += 1 + quadrant * self._seq_term(i)
            return cs

        def emit(cell_lo: np.ndarray, level: int, contained: bool) -> None:
            lo_code = seq_code(cell_lo, level)
            if contained:
                # lemma 3: all codes prefixed by this cell's code. NB the
                # reference adds the full subtree size with no -1
                # (XZ2SFC.scala:297-306) — over-inclusive by one code, which
                # the fine filter removes; we match it for parity.
                hi_code = lo_code + self._seq_term(level - 1)
            else:
                hi_code = lo_code
            out.append(IndexRange(lo_code, hi_code, contained))

        # BFS over cells; a cell at `level` has side 0.5^level, and its
        # *enlarged* element doubles that side (XElement semantics)
        queue: deque = deque()
        root_children = [
            (np.array([(c >> d) & 1 for d in range(self.dims)]) * 0.5, 1)
            for c in range(self.fan)
        ]
        queue.extend(root_children)

        while queue:
            cell_lo, level = queue.popleft()
            side = 0.5 ** level
            ext_hi = cell_lo + 2 * side  # enlarged element upper corner
            cell_hi = cell_lo + side
            contained = overlapped = False
            for wmin, wmax in windows:
                if np.all(wmin <= cell_lo) and np.all(wmax >= ext_hi):
                    contained = True
                    break
                if np.all(wmax >= cell_lo) and np.all(wmin <= ext_hi):
                    overlapped = True
            if contained:
                emit(cell_lo, level, True)
            elif overlapped:
                emit(cell_lo, level, False)
                if level < self.g and len(out) < max_ranges:
                    half = side / 2.0
                    for c in range(self.fan):
                        child = cell_lo + np.array(
                            [((c >> d) & 1) * half for d in range(self.dims)])
                        queue.append((child, level + 1))
                elif level < self.g:
                    # budget exhausted: cover the whole subtree coarsely
                    lo_code = seq_code(cell_lo, level)
                    out.append(IndexRange(lo_code, lo_code + self._seq_term(level - 1), False))

        return merge_ranges(out)


class XZ2SFC(XZSFC):
    """2-D XZ curve over lon/lat (reference XZ2SFC.scala; default g=12)."""

    _cache: dict = {}

    def __init__(self, g: int = 12, x_bounds=(-180.0, 180.0), y_bounds=(-90.0, 90.0)):
        super().__init__(g, [x_bounds, y_bounds])

    @classmethod
    def apply(cls, g: int = 12) -> "XZ2SFC":
        if g not in cls._cache:
            cls._cache[g] = cls(g)
        return cls._cache[g]

    def index_bbox(self, xmin, ymin, xmax, ymax, lenient: bool = False) -> np.ndarray:
        mins = np.stack([np.asarray(xmin, dtype=np.float64), np.asarray(ymin, dtype=np.float64)], axis=-1)
        maxs = np.stack([np.asarray(xmax, dtype=np.float64), np.asarray(ymax, dtype=np.float64)], axis=-1)
        return self.index(mins, maxs, lenient)

    def ranges_bbox(self, queries: Sequence[Tuple[float, float, float, float]],
                    max_ranges: Optional[int] = None) -> List[IndexRange]:
        return self.ranges([(xmin, ymin, xmax, ymax) for xmin, ymin, xmax, ymax in queries], max_ranges)


class XZ3SFC(XZSFC):
    """3-D XZ curve over lon/lat/binned-time (reference XZ3SFC.scala).

    The time dim spans one period bin, [0, max_offset(period)]; callers
    decompose multi-bin intervals per bin as with Z3. Default g=36 exceeds
    what int64 codes can hold for an octree; the reference uses g=36 for XZ3?
    No — the reference XZ3 uses the same g resolution as XZ2 (12) by default
    at the index layer; we keep g configurable and default to 12.
    """

    _cache: dict = {}

    def __init__(self, g: int = 12, period: TimePeriod = TimePeriod.WEEK,
                 x_bounds=(-180.0, 180.0), y_bounds=(-90.0, 90.0)):
        period = TimePeriod.parse(period)
        super().__init__(g, [x_bounds, y_bounds, (0.0, float(max_offset(period)))])
        self.period = period

    @classmethod
    def apply(cls, g: int = 12, period: TimePeriod = TimePeriod.WEEK) -> "XZ3SFC":
        period = TimePeriod.parse(period)
        key = (g, period)
        if key not in cls._cache:
            cls._cache[key] = cls(g, period)
        return cls._cache[key]
