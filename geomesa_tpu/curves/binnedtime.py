"""Epoch-binned time: millis → (bin: int16, offset: int64).

Semantics match the reference's BinnedTime
(/root/reference/geomesa-z3/.../BinnedTime.scala):

  period  bin unit            offset unit   max offset
  day     days since epoch    millis        86_400_000
  week    weeks since epoch   seconds       604_800
  month   months since epoch  seconds       86_400 * 31
  year    years since epoch   minutes       1440 * 366 + 10

Bins are computed against the UTC java epoch; month/year bins are *calendar*
months/years (via numpy datetime64[M]/[Y] truncation, which agrees with
ChronoUnit.MONTHS/YEARS.between from a midnight-of-jan-1 epoch). All functions
are vectorized over int64 epoch-millis arrays.
"""

from __future__ import annotations

import enum

import numpy as np


class TimePeriod(enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @classmethod
    def parse(cls, s: "str | TimePeriod") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return cls(s.lower())


class BinnedTime:
    """Namespace mirroring the reference object; prefer the module functions."""

    MAX_BIN = 32767  # Short.MaxValue — bins are conceptually int16


_DAY_MS = 86_400_000
_WEEK_MS = 7 * _DAY_MS


def max_offset(period: TimePeriod) -> int:
    """Max offset value (exclusive upper bound for normalization) per period.

    Mirrors BinnedTime.maxOffset (BinnedTime.scala:148-156), including the
    year fudge factor for leap seconds.
    """
    period = TimePeriod.parse(period)
    if period is TimePeriod.DAY:
        return _DAY_MS
    if period is TimePeriod.WEEK:
        return _WEEK_MS // 1000
    if period is TimePeriod.MONTH:
        return (_DAY_MS // 1000) * 31
    return 1440 * 366 + 10  # minutes in a leap year + leap-second fudge


def time_to_binned_time(millis, period: TimePeriod):
    """Vectorized millis → (bin int64, offset int64).

    Negative (pre-epoch) times are a caller error, mirroring the reference's
    require(); we do not raise here — the lenient/strict decision lives in the
    SFC layer — but results for negative inputs are unspecified.
    """
    period = TimePeriod.parse(period)
    millis = np.asarray(millis, dtype=np.int64)
    if period is TimePeriod.DAY:
        bins = millis // _DAY_MS
        offsets = millis - bins * _DAY_MS
    elif period is TimePeriod.WEEK:
        bins = millis // _WEEK_MS
        offsets = (millis - bins * _WEEK_MS) // 1000
    else:
        dt = millis.astype("datetime64[ms]")
        unit = "M" if period is TimePeriod.MONTH else "Y"
        bins = dt.astype(f"datetime64[{unit}]").astype(np.int64)
        start_ms = bins.astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)
        if period is TimePeriod.MONTH:
            offsets = (millis - start_ms) // 1000
        else:
            offsets = (millis - start_ms) // 60_000
    return bins, offsets


def time_to_bin(millis, period: TimePeriod):
    return time_to_binned_time(millis, period)[0]


def binned_time_to_millis(bins, offsets, period: TimePeriod):
    """Inverse of :func:`time_to_binned_time` (up to offset-unit truncation)."""
    period = TimePeriod.parse(period)
    bins = np.asarray(bins, dtype=np.int64)
    offsets = np.asarray(offsets, dtype=np.int64)
    if period is TimePeriod.DAY:
        return bins * _DAY_MS + offsets
    if period is TimePeriod.WEEK:
        return bins * _WEEK_MS + offsets * 1000
    unit = "M" if period is TimePeriod.MONTH else "Y"
    start_ms = bins.astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)
    if period is TimePeriod.MONTH:
        return start_ms + offsets * 1000
    return start_ms + offsets * 60_000


def bin_to_millis_bounds(b: int, period: TimePeriod) -> "tuple[int, int]":
    """[start, end) epoch-millis of bin ``b``."""
    period = TimePeriod.parse(period)
    if period is TimePeriod.DAY:
        return b * _DAY_MS, (b + 1) * _DAY_MS
    if period is TimePeriod.WEEK:
        return b * _WEEK_MS, (b + 1) * _WEEK_MS
    unit = "M" if period is TimePeriod.MONTH else "Y"
    lo = np.int64(b).astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)
    hi = np.int64(b + 1).astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)
    return int(lo), int(hi)
