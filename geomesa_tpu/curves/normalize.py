"""Dimension normalization: double in [min,max] → int in [0, 2^precision).

Semantics match the reference's ``BitNormalizedDimension``
(/root/reference/geomesa-z3/.../NormalizedDimension.scala:56-72):
  - normalize: floor((x - min) * bins/(max-min)), with x >= max clamping to
    maxIndex (so the upper bound is inclusive and lands in the last bin)
  - denormalize: bin centers, min + (i + 0.5) * (max-min)/bins, with
    i >= maxIndex clamped to maxIndex first

Vectorized over numpy arrays; pure float64 host math (curve encoding happens
on the host / in f64 islands — device kernels consume the resulting ints).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class BitNormalizedDimension:
    min: float
    max: float
    precision: int

    def __post_init__(self):
        if not (0 < self.precision < 32):
            raise ValueError("Precision (bits) must be in [1,31]")

    @property
    def bins(self) -> int:
        return 1 << self.precision

    @property
    def max_index(self) -> int:
        return self.bins - 1

    def normalize(self, x):
        x = np.asarray(x, dtype=np.float64)
        normalizer = self.bins / (self.max - self.min)
        res = np.floor((x - self.min) * normalizer).astype(np.int64)
        return np.where(x >= self.max, np.int64(self.max_index), res)

    def denormalize(self, i):
        i = np.minimum(np.asarray(i, dtype=np.int64), self.max_index)
        denormalizer = (self.max - self.min) / self.bins
        return self.min + (i.astype(np.float64) + 0.5) * denormalizer

    def clamp(self, x):
        """Lenient bounds standardization (reference lenientIndex semantics)."""
        return np.clip(np.asarray(x, dtype=np.float64), self.min, self.max)


def NormalizedLat(precision: int) -> BitNormalizedDimension:
    return BitNormalizedDimension(-90.0, 90.0, precision)


def NormalizedLon(precision: int) -> BitNormalizedDimension:
    return BitNormalizedDimension(-180.0, 180.0, precision)


def NormalizedTime(precision: int, max: float) -> BitNormalizedDimension:
    return BitNormalizedDimension(0.0, max, precision)
