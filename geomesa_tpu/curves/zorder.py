"""Vectorized Morton (z-order) bit interleaving.

The reference delegates this to the external ``sfcurve-zorder`` library
(imported at /root/reference/geomesa-z3/.../Z3SFC.scala:13-14); here it is
implemented directly with the standard magic-mask spread, vectorized over
numpy arrays (host ingest path) and mirrored in jax (device path).

Two layouts are supported, matching the sfcurve ones the reference uses:
  - Z2: two dims × 31 bits  → 62-bit keys. Bit i of dim0 ("x") lands at
    position 2i (x is the *least*-significant of each pair).
  - Z3: three dims × 21 bits → 63-bit keys, x least significant of each triple.

All functions are pure and shape-polymorphic (scalars or arrays).
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# 2-D spread: 31-bit int -> every-other-bit in a 62-bit word
# ---------------------------------------------------------------------------

_M2 = [
    np.uint64(0x00000000FFFFFFFF),
    np.uint64(0x0000FFFF0000FFFF),
    np.uint64(0x00FF00FF00FF00FF),
    np.uint64(0x0F0F0F0F0F0F0F0F),
    np.uint64(0x3333333333333333),
    np.uint64(0x5555555555555555),
]

_S2 = [np.uint64(32), np.uint64(16), np.uint64(8), np.uint64(4), np.uint64(2), np.uint64(1)]


def spread2(x):
    """Spread the low 32 bits of ``x`` so bit i moves to bit 2i."""
    x = np.asarray(x).astype(np.uint64) & _M2[0]
    for s, m in zip(_S2[1:], _M2[1:]):
        x = (x | (x << s)) & m
    return x


def squash2(x):
    """Inverse of :func:`spread2`: collect even-position bits back together."""
    x = np.asarray(x).astype(np.uint64) & _M2[-1]
    for s, m in zip(reversed(_S2[1:]), reversed([_M2[0]] + _M2[1:-1])):
        x = (x | (x >> s)) & m
    return x


def z2_encode(x, y):
    """Interleave two ≤31-bit non-negative ints into a z2 key (int64)."""
    return (spread2(x) | (spread2(y) << np.uint64(1))).astype(np.int64)


def z2_decode(z):
    """Inverse of :func:`z2_encode` → (x, y) int64 arrays."""
    z = np.asarray(z).astype(np.uint64)
    return squash2(z).astype(np.int64), squash2(z >> np.uint64(1)).astype(np.int64)


# ---------------------------------------------------------------------------
# 3-D spread: 21-bit int -> every-third-bit in a 63-bit word
# ---------------------------------------------------------------------------

_M3 = [
    np.uint64(0x00000000001FFFFF),
    np.uint64(0x001F00000000FFFF),
    np.uint64(0x001F0000FF0000FF),
    np.uint64(0x100F00F00F00F00F),
    np.uint64(0x10C30C30C30C30C3),
    np.uint64(0x1249249249249249),
]

_S3 = [np.uint64(0), np.uint64(32), np.uint64(16), np.uint64(8), np.uint64(4), np.uint64(2)]


def spread3(x):
    """Spread the low 21 bits of ``x`` so bit i moves to bit 3i."""
    x = np.asarray(x).astype(np.uint64) & _M3[0]
    for s, m in zip(_S3[1:], _M3[1:]):
        x = (x | (x << s)) & m
    return x


def squash3(x):
    """Inverse of :func:`spread3`."""
    x = np.asarray(x).astype(np.uint64) & _M3[-1]
    x = (x | (x >> np.uint64(2))) & _M3[4]
    x = (x | (x >> np.uint64(4))) & _M3[3]
    x = (x | (x >> np.uint64(8))) & _M3[2]
    x = (x | (x >> np.uint64(16))) & _M3[1]
    x = (x | (x >> np.uint64(32))) & _M3[0]
    return x


def z3_encode(x, y, t):
    """Interleave three ≤21-bit non-negative ints into a z3 key (int64)."""
    return (spread3(x) | (spread3(y) << np.uint64(1)) | (spread3(t) << np.uint64(2))).astype(np.int64)


def z3_decode(z):
    """Inverse of :func:`z3_encode` → (x, y, t) int64 arrays."""
    z = np.asarray(z).astype(np.uint64)
    return (
        squash3(z).astype(np.int64),
        squash3(z >> np.uint64(1)).astype(np.int64),
        squash3(z >> np.uint64(2)).astype(np.int64),
    )
