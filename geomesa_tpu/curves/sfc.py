"""Z2 / Z3 space-filling curves (≙ reference Z2SFC.scala / Z3SFC.scala).

Vectorized over numpy arrays; strict bounds checking with a ``lenient`` clamp
escape hatch, matching the reference's index()/lenientIndex() pair
(Z2SFC.scala:27-41, Z3SFC.scala:32-47).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset
from geomesa_tpu.curves.normalize import NormalizedLat, NormalizedLon, NormalizedTime
from geomesa_tpu.curves.ranges import (IndexRange, to_ranges,
                                       zranges_2d_arrays, zranges_3d_arrays)


class Z2SFC:
    """2-D Morton curve over lon/lat, 31 bits/dim by default."""

    def __init__(self, precision: int = 31):
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)

    def _check(self, x, y, lenient: bool):
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        oob = (x < self.lon.min) | (x > self.lon.max) | (y < self.lat.min) | (y > self.lat.max)
        if np.any(oob):
            if not lenient:
                raise ValueError(
                    f"Value(s) out of bounds ([{self.lon.min},{self.lon.max}], "
                    f"[{self.lat.min},{self.lat.max}])")
            x, y = self.lon.clamp(x), self.lat.clamp(y)
        return x, y

    def normalize(self, x, y, lenient: bool = False):
        """(lon, lat) → per-dim normalized ints (the device-resident coords)."""
        x, y = self._check(x, y, lenient)
        return self.lon.normalize(x), self.lat.normalize(y)

    def index(self, x, y, lenient: bool = False):
        xi, yi = self.normalize(x, y, lenient)
        return zorder.z2_encode(xi, yi)

    def invert(self, z):
        xi, yi = zorder.z2_decode(z)
        return self.lon.denormalize(xi), self.lat.denormalize(yi)

    def ranges(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        max_ranges: Optional[int] = None,
        max_levels: int = 64,
    ) -> List[IndexRange]:
        """Cover (xmin, ymin, xmax, ymax) user-space boxes with z ranges."""
        return to_ranges(self.ranges_arrays(xy, max_ranges, max_levels))

    def ranges_arrays(self, xy, max_ranges: Optional[int] = None,
                      max_levels: int = 64):
        """Array-form cover (lo, hi, contained) — the query-planning hot
        path (feeds prune.ranges_to_slices without per-range objects)."""
        boxes = []
        for xmin, ymin, xmax, ymax in xy:
            xlo, ylo = self.normalize(xmin, ymin)
            xhi, yhi = self.normalize(xmax, ymax)
            boxes.append((int(xlo), int(ylo), int(xhi), int(yhi)))
        return zranges_2d_arrays(boxes, self.precision, max_ranges or 2000,
                                 max_levels)


class Z3SFC:
    """3-D Morton curve over (lon, lat, binned time offset), 21 bits/dim.

    One instance per TimePeriod, as in the reference (Z3SFC.scala:65-77);
    time normalization runs over [0, max_offset(period)].
    """

    _cache: dict = {}

    def __init__(self, period: TimePeriod, precision: int = 21):
        if not (0 < precision < 22):
            raise ValueError("Precision (bits) per dimension must be in [1,21]")
        self.period = TimePeriod.parse(period)
        self.precision = precision
        self.lon = NormalizedLon(precision)
        self.lat = NormalizedLat(precision)
        self.time = NormalizedTime(precision, float(max_offset(self.period)))

    @classmethod
    def apply(cls, period: TimePeriod) -> "Z3SFC":
        period = TimePeriod.parse(period)
        if period not in cls._cache:
            cls._cache[period] = cls(period)
        return cls._cache[period]

    @property
    def whole_period(self) -> Tuple[int, int]:
        return (int(self.time.min), int(self.time.max))

    def _check(self, x, y, t, lenient: bool):
        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        t = np.asarray(t, dtype=np.float64)
        oob = (
            (x < self.lon.min) | (x > self.lon.max)
            | (y < self.lat.min) | (y > self.lat.max)
            | (t < self.time.min) | (t > self.time.max)
        )
        if np.any(oob):
            if not lenient:
                raise ValueError("Value(s) out of bounds for z3 index")
            x, y, t = self.lon.clamp(x), self.lat.clamp(y), self.time.clamp(t)
        return x, y, t

    def normalize(self, x, y, t, lenient: bool = False):
        x, y, t = self._check(x, y, t, lenient)
        return self.lon.normalize(x), self.lat.normalize(y), self.time.normalize(t)

    def index(self, x, y, t, lenient: bool = False):
        """x/y in degrees, t = offset *within the time bin* (period units)."""
        xi, yi, ti = self.normalize(x, y, t, lenient)
        return zorder.z3_encode(xi, yi, ti)

    def invert(self, z):
        xi, yi, ti = zorder.z3_decode(z)
        return (
            self.lon.denormalize(xi),
            self.lat.denormalize(yi),
            self.time.denormalize(ti).astype(np.int64),
        )

    def ranges(
        self,
        xy: Sequence[Tuple[float, float, float, float]],
        t: Sequence[Tuple[int, int]],
        max_ranges: Optional[int] = None,
        max_levels: int = 64,
    ) -> List[IndexRange]:
        """Cover the cross product of lon/lat boxes and in-bin time windows."""
        return to_ranges(self.ranges_arrays(xy, t, max_ranges, max_levels))

    def ranges_arrays(self, xy, t, max_ranges: Optional[int] = None,
                      max_levels: int = 64):
        """Array-form cover (lo, hi, contained) — the query-planning hot
        path (feeds prune.ranges_to_slices without per-range objects)."""
        boxes = []
        for xmin, ymin, xmax, ymax in xy:
            xlo, ylo = self.lon.normalize(xmin), self.lat.normalize(ymin)
            xhi, yhi = self.lon.normalize(xmax), self.lat.normalize(ymax)
            for tmin, tmax in t:
                tlo, thi = self.time.normalize(tmin), self.time.normalize(tmax)
                boxes.append((int(xlo), int(ylo), int(tlo),
                              int(xhi), int(yhi), int(thi)))
        return zranges_3d_arrays(boxes, self.precision, max_ranges or 2000,
                                 max_levels)
