"""Space-filling-curve layer (≙ reference geomesa-z3 + external sfcurve-zorder).

Unlike the reference, which delegates the Morton bit-interleave and the
quad/octree range-cover to the external ``sfcurve`` library
(/root/reference/geomesa-z3/pom.xml:21-22), everything here is self-contained:

  - ``zorder``     — vectorized Morton spread/interleave/deinterleave (numpy + jax)
  - ``normalize``  — BitNormalizedDimension semantics (floor-normalize, +0.5 denormalize)
  - ``binnedtime`` — TimePeriod / BinnedTime epoch binning
  - ``sfc``        — Z2SFC / Z3SFC index/invert/ranges
  - ``ranges``     — host-side z-range cover (BFS quad/octree decomposition + merge)
  - ``xz``         — XZ2SFC / XZ3SFC for geometries with extent (Böhm et al. XZ-ordering)
"""

from geomesa_tpu.curves.normalize import BitNormalizedDimension, NormalizedLat, NormalizedLon, NormalizedTime
from geomesa_tpu.curves.binnedtime import TimePeriod, BinnedTime, max_offset, time_to_binned_time, binned_time_to_millis
from geomesa_tpu.curves.sfc import Z2SFC, Z3SFC
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curves.ranges import IndexRange, zranges_2d, zranges_3d, merge_ranges

__all__ = [
    "BitNormalizedDimension", "NormalizedLat", "NormalizedLon", "NormalizedTime",
    "TimePeriod", "BinnedTime", "max_offset", "time_to_binned_time", "binned_time_to_millis",
    "Z2SFC", "Z3SFC", "XZ2SFC", "XZ3SFC",
    "IndexRange", "zranges_2d", "zranges_3d", "merge_ranges",
]
