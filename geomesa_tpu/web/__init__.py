"""HTTP/GeoJSON API surface (≙ geomesa-web + geomesa-geojson)."""

from geomesa_tpu.web.server import GeoJsonApi, serve

__all__ = ["GeoJsonApi", "serve"]
