"""REST / GeoJSON API over a TpuDataStore.

≙ the reference's web surface: the Scalatra data servlets + stats endpoint
(geomesa-web, /root/reference/geomesa-web/geomesa-web-stats/.../
GeoMesaStatsEndpoint.scala) and the pure-JSON API of geomesa-geojson
(geojson-api/.../GeoJsonGtIndex.scala). Stdlib http.server — no framework
dependency; the handler core (`GeoJsonApi.handle`) is transport-agnostic so
it can mount under any WSGI/ASGI shim.

Resilience envelope (serve/resilience/): every request may carry
``?deadline_ms=``/``X-Deadline-Ms`` (default/cap from
GEOMESA_TPU_DEADLINE_*) and ``?priority=``/``X-Priority``
(interactive | batch). Errors come back as a structured JSON envelope
``{"error": ..., "kind": ...}`` with a correct status: deadline-exceeded →
504, admission shed → 429 (+ Retry-After), breaker open → 503
(+ Retry-After), guard veto / bad input → 400, unexpected → 500 — and a
handler thread can no longer die (resetting the client connection) on an
exception anywhere in routing. Degraded counts are flagged:
``{"count": n, "approximate": true, "reason": ...}``.

Routes:
  GET  /types                          → type names
  GET  /types/{t}                      → schema + row count
  GET  /types/{t}/features?cql=&limit=&sort=&crs=   → GeoJSON FeatureCollection
  GET  /types/{t}/features?cql=&select=st_centroid(geom) AS c,val
                                       → projected columns (geometry terms
                                         as WKT, st_* scalars as floats)
  GET  /types/{t}/count?cql=           → {"count": n}  (concurrent requests
                                         coalesce through the micro-batching
                                         scheduler, serve/scheduler.py)
  GET  /types/{t}/explain?cql=&analyze=1 → query plan JSON (+ dry-run trace
                                         tree; analyze=1 EXECUTES the plan
                                         and annotates spans with device ms
                                         and cache provenance)
  GET  /types/{t}/stats?stat=<dsl>     → stat sketch JSON
  POST /types/{t}/features             → ingest a GeoJSON FeatureCollection
  POST /types/{t}/reindex              → background build-then-swap reindex
                                         (GET polls its status)
  GET  /metrics                        → metrics snapshot (JSON)
  GET  /metrics?format=prometheus      → Prometheus text exposition (native
                                         _bucket lines carry exemplar trace
                                         ids where a retained trace exists)
  GET  /traces?limit=N                 → recent query traces, newest first
  GET  /traces?retained=1              → the tail-sampled ring (errors, slow
                                         outliers, sampled rest)
  GET  /events?slow_ms=&error=1&kind=&type=&limit=
                                       → flight-recorder wide events (one
                                         per query/count/batch), filtered
  GET  /slo                            → SLO burn-rate evaluation (5m/30m/
                                         1h/6h windows, page/ticket state)
  GET  /alerts                         → fleet-doctor detector firings
                                         (evaluated on read)
  GET  /incidents?active=1             → doctor incidents with correlated
                                         timelines + resolution records
  GET  /progress                       → live + recent long-running phases
                                         (index-build encode/upload/sort
                                         with row throughput)
  GET  /scheduler                      → scheduler state (queue depth, batch
                                         histogram, cache hit rates)
  GET  /durability                     → WAL/snapshot status (policy, seq,
                                         unsynced bytes, last-snapshot age)
  GET  /replication                    → fleet role + fencing epoch +
                                         follower acked/lag state
  POST /replication/drain?off=         → admission drain (rolling restart /
                                         pre-failover quiesce)
  POST /replication/promote?port=      → promote this replica to primary
                                         under a fresh fencing epoch
  GET  /healthz                        → liveness + device count + durability,
                                         recovery/replay, replication and
                                         cluster-shard state
  GET  /cluster                        → partition plane: process count,
                                         per-process rows, Morton key-range
                                         ownership, mesh topology, psum
                                         round counters
  GET  /cluster/balance                → shard balance observatory: per-shard
                                         load shares (hot cells x key-range
                                         ownership), imbalance score,
                                         projected split points
  GET  /fleet/balance                  → the same ledger over fleet-merged
                                         shardwatch + workload states
  GET  /config                         → system-property listing

Mutating routes on a read-only replica (or a fenced ex-primary) return 403
with ``{"kind": "fenced"}``.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np


class GeoJsonApi:
    """Transport-agnostic request handler core. ``store`` may be a
    TpuDataStore OR a replication Follower — a replica node serves the
    same read API over whatever store the follower currently holds (it
    swaps stores across a snapshot catch-up)."""

    def __init__(self, store):
        self._target = store

    @property
    def store(self):
        return getattr(self._target, "store", self._target)

    def _node_meta(self) -> dict:
        """This node's fleet identity: stable node id + live role (the
        replication role when one is active, the process-stamped role
        otherwise) — the attribution /healthz, /metrics?format=state and
        federated scrapes carry."""
        from geomesa_tpu import trace as _t
        repl = getattr(self.store, "replication", None)
        role = _t.node_role()
        if repl is not None:
            try:
                role = repl.stats().get("role", role)
            except Exception:
                pass
        return {"id": _t.node_id(), "role": role}

    @staticmethod
    def _request_deadline(query: dict, headers) -> Optional[object]:
        """Per-request Deadline from ?deadline_ms= / X-Deadline-Ms, falling
        back to the configured default, capped at the configured max.
        None when unconstrained."""
        from geomesa_tpu import config
        from geomesa_tpu.serve.resilience.deadline import Deadline
        raw = query.get("deadline_ms", [None])[0]
        if raw is None and headers is not None:
            raw = headers.get("X-Deadline-Ms")
        try:
            ms = float(raw) if raw is not None else 0.0
        except (TypeError, ValueError):
            ms = 0.0
        if ms <= 0:
            ms = float(config.DEADLINE_DEFAULT_MS.get())
        if ms <= 0:
            return None
        return Deadline.after_ms(min(ms, float(config.DEADLINE_MAX_MS.get())))

    @staticmethod
    def _request_priority(query: dict, headers) -> str:
        from geomesa_tpu.serve.resilience.admission import normalize_priority
        raw = query.get("priority", [None])[0]
        if raw is None and headers is not None:
            raw = headers.get("X-Priority")
        return normalize_priority(raw)

    @staticmethod
    def _request_tenant(query: dict, headers) -> Optional[str]:
        """Caller-declared tenant from ?tenant= / X-Tenant. None falls back
        to the auth-derived label inside the scheduler (workload metering
        never trusts this for access control — auths stay authoritative)."""
        raw = query.get("tenant", [None])[0]
        if raw is None and headers is not None:
            raw = headers.get("X-Tenant")
        if raw is None:
            return None
        raw = str(raw).strip()
        return raw or None

    # returns (status, payload) — dict for JSON, str for raw text bodies.
    # A 429/503 payload carries retry_after_s; the transport turns it into
    # a Retry-After header.
    def handle(self, method: str, path: str, query: dict,
               body: Optional[bytes] = None,
               headers=None) -> Tuple[int, object]:
        from geomesa_tpu import trace as _trace
        from geomesa_tpu.cluster.cells import NotOwnedError
        from geomesa_tpu.index.guards import QueryGuardError, QueryTimeout
        from geomesa_tpu.replication.fence import FencedError
        from geomesa_tpu.serve.resilience import deadline as _rdl
        from geomesa_tpu.serve.resilience.breaker import CircuitOpenError
        from geomesa_tpu.serve.resilience.admission import ShedError
        try:
            # cross-process trace context: a request carrying X-Trace-Id
            # (the router's proxy hop) opens its root trace as a CHILD of
            # the remote parent — one global id, one stitched fleet tree
            with _trace.remote_parent(_trace.extract_headers(headers)), \
                    _rdl.use(self._request_deadline(query, headers)):
                return self._route(method, path, query, body,
                                   headers=headers)
        except ShedError as e:        # admission control shed this request
            if _trace.enabled():
                _trace.record("shed", "shed", 0.0)
            return 429, {"error": str(e), "kind": "shed",
                         "priority": e.priority,
                         "retry_after_s": e.retry_after_s}
        except CircuitOpenError as e:  # failing fast on a sick device path
            return 503, {"error": str(e), "kind": "breaker_open",
                         "retry_after_s": e.retry_after_s}
        except QueryTimeout as e:     # deadline exceeded / planner timeout
            return 504, {"error": str(e), "kind": "deadline"}
        except FencedError as e:      # read-only replica / fenced ex-primary
            return 403, {"error": str(e), "kind": "fenced"}
        except NotOwnedError as e:    # write routed to the wrong cell
            return 409, {"error": str(e), "kind": "not_owner",
                         "cell": e.cell, "owner": e.owner,
                         "key": e.key}
        except QueryGuardError as e:  # an interceptor vetoed the query
            return 400, {"error": str(e), "kind": "guard"}
        except (KeyError, ValueError, TypeError, IndexError,
                json.JSONDecodeError) as e:
            # planner/parser/data errors stay 400s (client-fixable input)
            return 400, {"error": str(e), "kind": "bad_request"}
        except Exception as e:        # anything else is OUR fault: 500,
            return 500, {"error": str(e), "kind": "internal",
                         "type": type(e).__name__}

    def _route(self, method, path, query, body, headers=None):
        parts = [p for p in path.split("/") if p]
        if parts == ["types"]:
            return 200, {"types": self.store.get_type_names()}
        if parts == ["metrics"]:
            from geomesa_tpu.metrics import REGISTRY
            fmt = query.get("format", [None])[0]
            if fmt == "prometheus":
                # str payload → text/plain exposition body
                return 200, REGISTRY.to_prometheus()
            if fmt == "state":
                # bucket-exact registry state for the metrics federator
                # (lossless cross-node histogram merge), tagged with this
                # node's fleet identity; workload rollup/sketch state rides
                # the same payload so one scrape carries both
                from geomesa_tpu.obs.history import HISTORY
                from geomesa_tpu.obs.shardwatch import WATCH
                from geomesa_tpu.obs.workload import WORKLOAD
                state = REGISTRY.export_state()
                state["workload"] = WORKLOAD.export_state()
                state["shardwatch"] = WATCH.export_state()
                state["history"] = HISTORY.export_state()
                return 200, {"node": self._node_meta(), "state": state}
            return 200, REGISTRY.snapshot()
        if parts == ["traces"]:
            from geomesa_tpu.trace import RING
            limit = int(query.get("limit", [50])[0])
            gid = query.get("id", [None])[0]
            if gid is not None:
                # this node's halves of one (global) trace id — what the
                # router-side stitcher / `debug trace --fleet` fetch
                from geomesa_tpu.obs.federation import local_traces_by_id
                return 200, {"id": gid, "traces": local_traces_by_id(gid)}
            if query.get("retained", [None])[0] not in (None, "0", "false"):
                # the tail-sampled ring: errors/cancel/shed/degrade always,
                # slow outliers past the adaptive threshold, plus the
                # probabilistic sample — what /metrics exemplars link to
                from geomesa_tpu.obs.sampling import SAMPLER
                return 200, {"traces": SAMPLER.recent(limit),
                             "sampler": SAMPLER.stats()}
            return 200, {"traces": RING.recent(limit)}
        if parts == ["events"]:
            # flight recorder: wide events filtered by the shared predicate
            from geomesa_tpu.obs.flight import RECORDER
            slow = query.get("slow_ms", [None])[0]
            since = query.get("since_ms", [None])[0]
            return 200, {"events": RECORDER.recent(
                limit=int(query.get("limit", [100])[0]),
                slow_ms=float(slow) if slow is not None else None,
                errors=query.get("error", [None])[0]
                not in (None, "0", "false"),
                kind=query.get("kind", [None])[0],
                type_name=query.get("type", [None])[0],
                since_ms=float(since) if since is not None else None),
                "recorder": RECORDER.stats()}
        if parts == ["slo"]:
            from geomesa_tpu.obs.slo import ENGINE
            return 200, {"slo": ENGINE.evaluate()}
        if parts == ["alerts"]:
            # the doctor's current firings — reading IS detecting (the
            # evaluation runs here, never on the query hot path)
            from geomesa_tpu.obs.doctor import DOCTOR
            return 200, DOCTOR.alerts()
        if parts == ["incidents"]:
            from geomesa_tpu.obs.doctor import DOCTOR
            active = query.get("active", [None])[0] \
                not in (None, "0", "false")
            return 200, DOCTOR.incidents(active_only=active)
        if len(parts) == 3 and parts[0] == "incidents" \
                and parts[2] == "bundle":
            # the forensic bundle frozen when the doctor opened this
            # incident: history slices around the firing, matching flight
            # events, trace gids, replication/cell state, workload hot_set
            from geomesa_tpu.obs.forensics import FORENSICS
            bundle = FORENSICS.get(parts[1])
            if bundle is None:
                return 404, {"error": f"no forensic bundle for "
                                      f"{parts[1]}"}
            return 200, bundle
        if parts == ["history"]:
            # retained metric timelines: ?name=series&since_ms=&tier= for
            # a range; without ?name=, the sampler summary + series index
            from geomesa_tpu.obs.history import HISTORY
            name = query.get("name", [None])[0]
            if name is None:
                return 200, {"history": HISTORY.summary()}
            since = float(query.get("since_ms", [0])[0])
            tier = query.get("tier", [None])[0]
            return 200, {"name": name, "since_ms": since,
                         "samples": HISTORY.range(
                             name, since_ms=since,
                             tier=int(tier) if tier is not None
                             else None)}
        if parts == ["workload"]:
            # streaming workload analytics: windowed rollups, heavy-hitter
            # plan hashes / tenants, hot spatial cells (query LOAD, not data)
            from geomesa_tpu.obs.workload import WORKLOAD
            return 200, {"workload": WORKLOAD.summary()}
        if parts == ["progress"]:
            # long-running operation phases (index builds): live phases
            # with running row throughput + the recent history
            from geomesa_tpu.obs.profiling import PROGRESS
            return 200, {"progress": PROGRESS.snapshot()}
        if parts == ["scheduler"]:
            return 200, self.store.scheduler().stats()
        if parts == ["cache"]:
            # the hot-result cache surface: counters + per-cell warmth, so
            # the doctor's hot_skew suspects can be cross-checked against
            # what is actually cached on this node
            return 200, {"result_cache":
                         self.store.scheduler().results.stats()}
        if parts == ["durability"]:
            d = getattr(self.store, "durability", None)
            if d is None:
                return 200, {"enabled": False}
            return 200, d.status()
        if parts and parts[0] == "replication":
            return self._route_replication(parts[1:], method, query)
        if parts == ["debug", "fault"] and method == "POST":
            # deterministic chaos for subprocess drills: the fleet soak
            # arms mid-run faults (e.g. a repl.apply delay = lag spike)
            # in a child it cannot reach in-process. Hard-gated off by
            # default — the env flag is only set by drill spawners.
            import os as _os
            if _os.environ.get("GEOMESA_TPU_FAULT_API", "").lower() \
                    not in ("1", "true", "on"):
                return 403, {"error": "fault API disabled (spawn with "
                                      "GEOMESA_TPU_FAULT_API=1)",
                             "kind": "forbidden"}
            from geomesa_tpu.durability import faults as _faults
            if query.get("reset", [None])[0]:
                _faults.reset()
                return 200, {"reset": True}
            point = query.get("point", [None])[0]
            if not point:
                return 400, {"error": "missing ?point=",
                             "kind": "bad_request"}
            delay_s = float(query.get("delay_s", [0.0])[0])
            n = int(query.get("n", [1])[0])
            _faults.arm_serve_delay(point, seconds=delay_s, n=n)
            return 200, {"armed": point, "delay_s": delay_s, "n": n}
        if parts == ["fleet", "soak"]:
            # last fleet-soak scoreboard: readable WITHOUT a federator
            # (the orchestrator runs out-of-process; any node can serve
            # the summary it wrote to disk)
            from geomesa_tpu.obs import soakfleet as _soak
            board = _soak.last_run()
            if board is None:
                return 404, {"error": "no soak run recorded "
                                      "(geomesa-tpu soak)"}
            return 200, board
        if parts and parts[0] == "fleet":
            # the single pane of glass — served by whichever node carries
            # a configured federator (the router/primary, typically)
            from geomesa_tpu.obs import federation as _fed
            fed = _fed.federator()
            if fed is None:
                return 404, {"error": "no federator configured on this "
                                      "node (obs.federation.configure)"}
            if parts == ["fleet"]:
                return 200, fed.fleet()
            if parts == ["fleet", "metrics"]:
                return 200, fed.to_prometheus()  # str → text exposition
            if parts == ["fleet", "slo"]:
                return 200, {"slo": fed.slo()}
            if parts == ["fleet", "workload"]:
                # fleet-wide workload intelligence: per-node window states
                # and sketches merged into one hot-set / rollup view
                return 200, fed.fleet_workload()
            if parts == ["fleet", "incidents"]:
                # every node's doctor verdicts with node attribution
                return 200, fed.fleet_incidents()
            if parts == ["fleet", "balance"]:
                # fleet-wide shard balance: merged shardwatch + workload
                # states joined through the same ledger a node runs
                return 200, fed.fleet_balance()
            if parts == ["fleet", "history"]:
                # fleet timelines: equal-tier rings merged at aligned
                # slots with honest per-node gap markers
                return 200, fed.fleet_history()
            return 404, {"error": f"no route {method} {path}"}
        if parts == ["cluster", "balance"]:
            # the shard balance observatory: per-shard load shares joined
            # from hot cells x key-range ownership, imbalance score, and
            # projected split points for the hottest shard
            from geomesa_tpu.obs.shardwatch import WATCH
            return 200, WATCH.balance()
        if parts == ["cluster"]:
            # the partition plane: process count, per-process rows, Morton
            # key-range ownership, mesh topology, psum round counters.
            # (/fleet is the REPLICATION plane: full-copy nodes behind the
            # router. A cluster shard can still have read replicas.)
            from geomesa_tpu.cluster.runtime import runtime as _cluster_rt
            return 200, _cluster_rt(init=False).state()
        if parts == ["cells"]:
            # the shard-cell plane: which cell this node serves (key
            # range, fencing epoch, ingest-gate counters) + the fleet
            # topology when one was configured
            from geomesa_tpu.cluster import cells as _cells
            return 200, _cells.CELLS.state()
        if parts == ["healthz"]:
            import jax
            report = getattr(self.store, "recovery_report", None)
            d = getattr(self.store, "durability", None)
            # overload state reads the LIVE scheduler only — a health probe
            # must never be the thing that spins one up
            sched = getattr(self.store, "_scheduler", None)
            if sched is None:
                overload = {"scheduler": "idle"}
            else:
                overload = {"scheduler": "ok" if sched.healthy()
                            else "unhealthy",
                            "queue_depth": sched._queue.qsize(),
                            "admission": sched.admission.stats(),
                            "breaker": sched.breaker.stats()}
            from geomesa_tpu.obs.slo import ENGINE as _slo_engine
            try:
                slo = _slo_engine.summary()
            except Exception:
                slo = {"status": "unknown"}
            repl = getattr(self.store, "replication", None)
            from geomesa_tpu.cluster.runtime import runtime as _cluster_rt
            c = _cluster_rt(init=False)
            cluster = {"active": c.active()}
            if c.active():
                cluster.update({
                    "process_id": c.process_id,
                    "num_processes": c.num_processes,
                    "psum_rounds": c.psum_rounds,
                    "shard_rows": {
                        t: s.get("proc_rows", [None] * (c.process_id + 1))
                        [c.process_id] for t, s in c.tables.items()}})
            from geomesa_tpu.index import compiled as _fused
            return 200, {"status": "ok",
                         "node": self._node_meta(),
                         "cluster": cluster,
                         "devices": len(jax.local_devices()),
                         "types": len(self.store.get_type_names()),
                         "overload": overload,
                         "slo": slo,
                         "fused_query": _fused.stats_snapshot(),
                         "replication": repl.stats() if repl is not None
                         else {"role": "standalone"},
                         "durability": {
                             "enabled": d is not None,
                             "wal_policy": d.wal.policy if d else None,
                             "wal_seq": d.wal.last_seq if d else None,
                             "synced_seq": d.wal.synced_seq if d else None,
                             "unsynced_bytes": d.wal.unsynced_bytes
                             if d else None},
                         "recovery": report.to_dict() if report is not None
                         else {"recovered": False}}
        if parts == ["config"]:
            from geomesa_tpu import config
            return 200, config.describe()
        if len(parts) >= 2 and parts[0] == "types":
            t = parts[1]
            if t not in self.store.get_type_names():
                return 404, {"error": f"no such type {t!r}"}
            rest = parts[2:]
            cql = query.get("cql", ["INCLUDE"])[0]
            if "q" in query:
                # MongoDB-style JSON query (≙ the geojson API's GeoJsonQuery
                # language) — takes precedence over ?cql=
                from geomesa_tpu.web.jsonquery import parse_json_query
                cql = parse_json_query(query["q"][0], self.store.get_schema(t))
            auths = query["auths"][0].split(",") if "auths" in query else None
            if not rest:
                sft = self.store.get_schema(t)
                # one consistent (planner, delta) snapshot — two unlocked
                # reads could straddle a flush and under-count by the delta
                if self.store.tables.get(t) is None:
                    count = 0
                else:
                    planner, delta = self.store._snapshot(t)
                    count = len(planner.table) + (len(delta) if delta is not None else 0)
                return 200, {"name": t, "spec": sft.to_spec(),
                             "attributes": [
                                 {"name": a.name, "type": a.type_name,
                                  "default": a.default}
                                 for a in sft.attributes],
                             "count": count}
            if rest == ["count"]:
                # a freshly provisioned type (schema, zero rows) counts
                # as 0 — a 4xx here would read as node death to the
                # shard router and mark a healthy empty cell dark
                d = self.store.deltas.get(t)
                if self.store.tables.get(t) is None and \
                        (d is None or len(d) == 0):
                    return 200, {"count": 0}
                # coalesced: concurrent counts micro-batch into shared
                # fused device dispatches (serve/scheduler.py); the ambient
                # request deadline propagates through the scheduler and an
                # overload/breaker condition may degrade the answer to the
                # flagged stats estimate
                n = self.store.count_coalesced(
                    t, cql, auths=auths,
                    priority=self._request_priority(query, headers),
                    tenant=self._request_tenant(query, headers))
                out = {"count": int(n)}
                if getattr(n, "approximate", False):
                    out["approximate"] = True
                    out["reason"] = n.reason
                return 200, out
            if rest == ["explain"]:
                analyze = query.get("analyze", [None])[0] \
                    not in (None, "0", "false")
                out = self.store.explain(t, cql, analyze=analyze,
                                         auths=auths)
                return 200, json.loads(json.dumps(out, default=str))
            if rest == ["stats"]:
                stat = query.get("stat", [None])[0]
                if not stat:
                    return 400, {"error": "missing ?stat= DSL expression"}
                res = self.store.stats(t).run_stat(stat, cql, auths=auths)
                return 200, {"stat": stat, "result": res.to_dict()
                             if hasattr(res, "to_dict") else str(res)}
            if rest == ["features"] and method == "GET":
                hints = {}
                if "limit" in query:
                    hints["limit"] = int(query["limit"][0])
                if "sort" in query:
                    hints["sort"] = query["sort"][0]
                if "crs" in query:
                    hints["crs"] = query["crs"][0]
                res = self.store.query(t, cql, hints=hints or None,
                                       auths=auths)
                if "select" in query:
                    # geometry-catalog projections: st_* terms evaluate
                    # through the vmapped kernels (GEOM_KERNELS knob),
                    # geometry results serialize as WKT
                    from geomesa_tpu.geom.functions import \
                        projection_columns
                    cols = projection_columns(res.table, None,
                                              query["select"][0])
                    return 200, {"type": t, "count": len(res.table),
                                 "columns": cols}
                from geomesa_tpu.io.export import export
                return 200, json.loads(export(res.table, "geojson"))
            if rest == ["features"] and method == "POST":
                fc = json.loads(body or b"{}")
                n = self._ingest_geojson(t, fc)
                return 200, {"ingested": n}
            if rest == ["flush"] and method == "POST":
                # force the delta tier into main — lets operators (and the
                # soak orchestrator) provoke the table swap that reindex
                # builds race against
                self.store.flush(t)
                return 200, {"flushed": t}
            if rest == ["reindex"]:
                # POST kicks a background build-then-swap reindex (serving
                # continues against the old generation until the atomic
                # install); GET polls its status
                if method == "POST":
                    return 200, self.store.reindex(t, background=True)
                return 200, self.store.reindex_status(t)
        return 404, {"error": f"no route {method} {path}"}

    def _route_replication(self, rest, method, query):
        """Fleet control surface.

          GET  /replication          role + epoch + follower/lag state
          POST /replication/drain    admission drain (rolling restart /
                                     pre-failover quiesce); ?off=1 undoes
          POST /replication/promote  promote THIS node (a Follower-backed
                                     replica) to primary under a fresh
                                     fencing epoch; ?port= picks the new
                                     shipper port (0 = ephemeral)
          POST /replication/fence    durably fence THIS node under
                                     ?epoch= (ownership handoff: the old
                                     owner refuses every write until
                                     re-promoted; survives restart via
                                     the persisted epoch file)
        """
        repl = getattr(self.store, "replication", None)
        if not rest:
            if repl is None:
                return 200, {"role": "standalone"}
            return 200, repl.stats()
        if rest == ["drain"] and method == "POST":
            off = query.get("off", [None])[0] not in (None, "0", "false")
            self.store.scheduler().admission.drain(not off)
            return 200, {"draining": not off}
        if rest == ["promote"] and method == "POST":
            target = self._target
            if not hasattr(target, "promote"):
                return 400, {"error": "this node is not a promotable "
                                      "replica", "kind": "bad_request"}
            port = int(query.get("port", [0])[0])
            shipper = target.promote(port=port)
            return 200, {"role": "primary", "epoch": shipper.epoch,
                         "address": shipper.address}
        if rest == ["fence"] and method == "POST":
            from geomesa_tpu.cluster import cells as _cells
            from geomesa_tpu.replication import fence as _f
            epoch = int(query.get("epoch", [0])[0])
            store = self.store
            if repl is not None and hasattr(repl, "_fence_self"):
                repl._fence_self(epoch)
            else:
                _f.save_epoch(store.durability.path, epoch)
                store.durability.read_only = True
            if _cells.CELLS.fence is not None:
                _cells.CELLS.fence.epoch = max(
                    _cells.CELLS.fence.epoch, epoch)
            return 200, {"fenced": True, "epoch": epoch}
        return 404, {"error": f"no route {method} /replication/"
                              f"{'/'.join(rest)}"}

    def _ingest_geojson(self, t: str, fc: dict) -> int:
        feats = fc.get("features", [])
        if not feats:
            return 0
        from geomesa_tpu.cluster import cells as _cells
        if _cells.CELLS.active():
            # shard-cell ownership gate: every point's routing key must
            # fall in this node's cell range BEFORE anything is written
            # (atomic refusal — a misrouted batch lands zero rows)
            pts = [f.get("geometry", {}).get("coordinates")
                   for f in feats
                   if (f.get("geometry", {}).get("type") or
                       "Point").upper() == "POINT"]
            if pts:
                _cells.CELLS.ensure_owned([p[0] for p in pts],
                                          [p[1] for p in pts])
        sft = self.store.get_schema(t)
        with self.store.get_writer(t) as w:
            for f in feats:
                props = dict(f.get("properties", {}))
                geom = f.get("geometry") or {}
                coords = geom.get("coordinates")
                gtype = (geom.get("type") or "Point").upper()
                gattr = sft.geometry_attribute.name
                if gtype == "POINT":
                    props[gattr] = f"POINT ({coords[0]} {coords[1]})"
                else:
                    from geomesa_tpu.features.geometry import (NAME_TYPES,
                                                               write_wkt)
                    code = NAME_TYPES[geom.get("type")]
                    props[gattr] = write_wkt(code, coords)
                for a in sft.attributes:
                    if a.type_name == "Date" and a.name in props:
                        props[a.name] = np.datetime64(props[a.name], "ms") \
                            .astype(np.int64)
                w.write(fid=f.get("id"), **props)
        return len(feats)


class _Handler(BaseHTTPRequestHandler):
    api: GeoJsonApi = None  # set by serve()

    def _respond(self, status: int, payload) -> None:
        # str payloads are raw text bodies (the Prometheus exposition);
        # everything else serializes as JSON
        if isinstance(payload, str):
            data = payload.encode()
            ctype = "text/plain; version=0.0.4"
        else:
            data = json.dumps(payload).encode()
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        if isinstance(payload, dict) and "retry_after_s" in payload:
            # shed (429) / breaker-open (503) backpressure: the standard
            # header clients and proxies honor
            self.send_header("Retry-After",
                             str(max(1, int(-(-payload["retry_after_s"]
                                             // 1)))))
        self.end_headers()
        self.wfile.write(data)

    def _serve(self, method: str) -> None:
        """Route + respond inside a last-resort guard: NOTHING a route
        raises may kill the handler thread and reset the client connection
        — an unexpected error becomes a structured 500 envelope (the
        kind/status mapping itself lives in GeoJsonApi.handle)."""
        try:
            u = urlparse(self.path)
            body = None
            if method == "POST":
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
            status, payload = self.api.handle(method, u.path,
                                              parse_qs(u.query), body,
                                              headers=self.headers)
        except Exception as e:  # handle() failed outside its own guards
            status, payload = 500, {"error": str(e), "kind": "internal",
                                    "type": type(e).__name__}
        try:
            self._respond(status, payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the server thread must survive it

    def do_GET(self):
        self._serve("GET")

    def do_POST(self):
        self._serve("POST")

    def log_message(self, *a):  # quiet by default
        pass


def serve(store, host: str = "127.0.0.1", port: int = 8765,
          background: bool = False):
    """Start the REST server. ``background=True`` returns the server after
    starting a daemon thread (tests / embedded use)."""
    handler = type("BoundHandler", (_Handler,), {"api": GeoJsonApi(store)})
    httpd = ThreadingHTTPServer((host, port), handler)
    if background:
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd
    httpd.serve_forever()
