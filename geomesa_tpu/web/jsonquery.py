"""MongoDB-style JSON query DSL → filter IR.

≙ reference ``GeoJsonQuery`` (geomesa-geojson-api/.../query/GeoJsonQuery.
scala:30-60), the JSON query language of the GeoJSON REST API:

    {}                                        → INCLUDE
    { "foo" : "bar" }                         → foo = 'bar'
    { "foo" : { "$lt" : 10 } }                → foo < 10   ($lte/$gt/$gte/
                                                 $ne/$in analogous)
    { "geometry" : { "$bbox" : [x0,y0,x1,y1] } }
    { "geometry" : { "$intersects" : { "$geometry" : <geojson> } } }
    { "geometry" : { "$within" | "$contains" : { "$geometry" : ... } } }
    { "geometry" : { "$dwithin" : { "$geometry" : ..., "$dist" : 100,
                                    "$unit" : "meters" } } }
    { "$or" : [ q1, q2 ] }                    → q1 OR q2
    multiple keys in one object               → AND

Geometry-catalog function operators (st_* kernels, geom/):

    { "geometry" : { "$stContains"   : { "$geometry" : <geojson> } } }
                                              → st_contains(<lit>, geometry)
    { "geometry" : { "$stIntersects" : { "$geometry" : ... } } }
                                              → st_intersects(geometry, <lit>)
    { "geometry" : { "$stDistance" : { "$geometry" : <point>,
                                       "$lt" : 0.5 } } }
                                              → st_distance(geometry, <lit>) < 0.5
    { "geometry" : { "$stArea" | "$stLength" : { "$gt" : 10 } } }
                                              → st_area(geometry) > 10

Property names starting with ``$.`` (JSON-path style) strip the prefix —
attributes here are real SFT columns, not nested documents. ``geometry``
maps to the type's default geometry attribute.
"""

from __future__ import annotations

import json
from typing import Union

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import ir

# $dwithin unit → degrees at the equator (the exact-refine predicates work
# in degree space, matching the ECQL DWITHIN path)
_UNIT_TO_DEG = {
    "degrees": 1.0,
    "meters": 1.0 / 111_320.0,
    "kilometers": 1.0 / 111.32,
    "feet": 0.3048 / 111_320.0,
    "miles": 1609.344 / 111_320.0,
}

_CMP_OPS = {"$lt": "<", "$lte": "<=", "$gt": ">", "$gte": ">=", "$ne": "<>"}

# geometry-catalog operators (lower-cased lookup: the DSL is camelCase)
_FUNC_BOOL_OPS = {"$stcontains": "st_contains",
                  "$stintersects": "st_intersects"}
_FUNC_CMP_OPS = {"$stdistance": "st_distance", "$starea": "st_area",
                 "$stlength": "st_length"}
_FUNC_CMP_BOUNDS = {**_CMP_OPS, "$eq": "="}


def parse_json_query(q: Union[str, dict, None], sft) -> ir.Filter:
    """JSON query (text or parsed) → filter IR against ``sft``."""
    if q is None:
        return ir.Include()
    if isinstance(q, (str, bytes)):
        q = json.loads(q or "{}")
    if not isinstance(q, dict):
        raise ValueError("JSON query must be an object")
    return _evaluate(q, sft)


def _evaluate(obj: dict, sft) -> ir.Filter:
    if not obj:
        return ir.Include()
    preds = []
    for prop, v in obj.items():
        if prop == "$or":
            if not isinstance(v, list):
                raise ValueError("$or expects an array of query objects")
            preds.append(ir.or_filters([_evaluate(o, sft) for o in v]))
        elif prop == "$and":
            if not isinstance(v, list):
                raise ValueError("$and expects an array of query objects")
            preds.append(ir.and_filters([_evaluate(o, sft) for o in v]))
        elif isinstance(v, dict):
            preds.append(_predicate(_attr(prop, sft), v))
        else:
            preds.append(ir.Cmp("=", _attr(prop, sft), v))
    return ir.and_filters(preds)


def _attr(prop: str, sft) -> str:
    if prop.startswith("$."):
        prop = prop[2:]
    if prop == "geometry" and sft.geometry_attribute is not None:
        return sft.geometry_attribute.name
    return prop


def _predicate(attr: str, obj: dict) -> ir.Filter:
    """All operators on one field AND together ({"$gte": 1, "$lt": 10} is a
    range, not just its first bound)."""
    if not obj:
        raise ValueError(f"Empty predicate for {attr!r}")
    return ir.and_filters([_one_op(attr, op, v) for op, v in obj.items()])


def _one_op(attr: str, op: str, v) -> ir.Filter:
    low = op.lower()
    if low in _FUNC_BOOL_OPS:
        name = _FUNC_BOOL_OPS[low]
        lit = _geometry(v)
        # st_contains(lit, geom): the literal contains the feature (the
        # useful direction for a constant query geometry); st_intersects
        # is symmetric — keep the attr-first spelling the parser produces
        args = (lit, attr) if name == "st_contains" else (attr, lit)
        return ir.Func(name, args)
    if low in _FUNC_CMP_OPS:
        name = _FUNC_CMP_OPS[low]
        if not isinstance(v, dict):
            raise ValueError(f"{op} expects an object with a comparison "
                             "bound")
        bounds = [(b, bv) for b, bv in v.items() if b in _FUNC_CMP_BOUNDS]
        if len(bounds) != 1:
            raise ValueError(f"{op} needs exactly one comparison bound "
                             f"({sorted(_FUNC_CMP_BOUNDS)})")
        args = (attr, _geometry(v)) if name == "st_distance" else (attr,)
        bop, bval = bounds[0]
        return ir.FuncCmp(_FUNC_CMP_BOUNDS[bop], name, args, float(bval))
    if op in _CMP_OPS:
        return ir.Cmp(_CMP_OPS[op], attr, v)
    if op == "$in":
        if not isinstance(v, list):
            raise ValueError("$in expects an array")
        return ir.In(attr, tuple(v))
    if op == "$bbox":
        if not (isinstance(v, list) and len(v) == 4):
            raise ValueError("$bbox expects [xmin, ymin, xmax, ymax]")
        return ir.BBox(attr, float(v[0]), float(v[1]), float(v[2]),
                       float(v[3]))
    if op in ("$intersects", "$within", "$contains"):
        cls = {"$intersects": ir.Intersects, "$within": ir.Within,
               "$contains": ir.Contains}[op]
        return cls(attr, _geometry(v))
    if op == "$dwithin":
        dist = v.get("$dist") if isinstance(v, dict) else None
        if dist is None:
            raise ValueError("$dwithin needs a $dist")
        unit = str(v.get("$unit", "degrees")).lower()
        if unit not in _UNIT_TO_DEG:
            raise ValueError(f"Unknown $unit {unit!r} "
                             f"(have {sorted(_UNIT_TO_DEG)})")
        return ir.Dwithin(attr, _geometry(v),
                          float(dist) * _UNIT_TO_DEG[unit])
    raise ValueError(f"Unknown operator {op!r} for {attr!r}")


def _geometry(obj) -> tuple:
    """``{"$geometry": {"type": ..., "coordinates": ...}}`` → IR geometry
    tuple (type_code, nested coordinate lists)."""
    if not isinstance(obj, dict) or "$geometry" not in obj:
        raise ValueError("Expected an object with a $geometry key")
    g = obj["$geometry"]
    name = str(g.get("type", ""))
    if name not in geo.NAME_TYPES:
        raise ValueError(f"Unknown geometry type {name!r}")
    return (geo.NAME_TYPES[name], g.get("coordinates"))
