"""N-process CPU-backend distributed dryrun + single-process oracle.

The acceptance surface for the cluster runtime (and the engine behind
bench cfg12 and the CI ``cluster`` job): spawn N real worker processes
(``JAX_PLATFORMS=cpu``, gloo collectives), have each

  1. deal itself a round-robin slice of a deterministic shared-seed
     corpus (so no process ever materializes the full table),
  2. repartition by Morton key range (cluster/build.py) so it owns one
     contiguous, sorted shard,
  3. build a real local store + index over the shard, assemble the
     ClusterShardedTable global arrays, and run the query battery:
     psum'd bbox+time counts, a psum'd density grid, and ordered-merge
     selects,
  4. start a web surface and auto-register the cluster in the Federator
     (both processes must appear in /fleet with no manual --addr list),

while the parent runs the IDENTICAL battery single-process (the oracle
is the same code path with an inactive runtime — one code path, two
cardinalities). The orchestrator then asserts byte-equality: every
rank's psum count == oracle count, density grids sha-identical, merged
select fids list-identical, and every rank holds strictly less than the
full corpus.

The corpus deliberately contains duplicated (point, time) rows so the
tie-break discipline (original-gid plane through the partition, local
row order in the index) is exercised, not just probable.

The default (non-drill) run additionally exercises the cluster knn
radius exchange against a brute-force oracle and the distributed WRITE
path: a fresh extra corpus routed by Morton key ownership, each process
ingesting only its owned rows, and the post-ingest cluster table proven
byte-equal to the oracle that ingested everything single-process.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.cluster.runtime import ClusterRuntime, runtime

SPEC = "name:String,val:Int,dtg:Date,*geom:Point"
TYPE = "pts"

COUNT_QUERIES = [
    "BBOX(geom, -10, -10, 10, 10)",
    "BBOX(geom, -10, -10, 10, 10) AND dtg DURING "
    "2020-01-05T00:00:00Z/2020-01-20T00:00:00Z",
    "val > 50",
    "INCLUDE",
]
SELECT_QUERIES = [
    "BBOX(geom, -6, -6, 6, 6)",
    "BBOX(geom, 20, 20, 60, 60) AND dtg DURING "
    "2020-01-02T00:00:00Z/2020-01-25T00:00:00Z",
]
DENSITY_QUERY = "BBOX(geom, -90, -45, 90, 45)"
DENSITY_BBOX = (-90.0, -45.0, 90.0, 45.0)
DENSITY_WH = (64, 32)

# geometry-catalog battery: st_* function queries (banded kernels +
# host refine per shard, psum-reduced — ClusterScan.count is device-only
# and cannot host-refine Func residuals) and the point-in-polygon join
FUNC_COUNT_QUERIES = [
    "st_distance(geom, POINT(0 0)) < 25",
    "st_contains(POLYGON((-30 -15, 30 -15, 30 15, -30 15, -30 -15)), geom)",
    "st_intersects(geom, POLYGON((60 10, 120 10, 90 60, 60 10)))",
]
JOIN_POLYGONS = [
    "POLYGON((-20 -20, 20 -20, 20 20, -20 20, -20 -20))",
    "POLYGON((0 0, 40 0, 20 35, 0 0))",
    "POLYGON((100 -30, 160 -30, 160 40, 130 5, 100 40, 100 -30))",
]
JOIN_MAX_PAIRS = 200

# cluster knn battery: (cql, x, y, k) — device-exact plans only (knn
# rejects host residuals). k=7 overlaps the duplicated corpus tail so
# the (distance, gid) tie-break is exercised, not just probable.
KNN_QUERIES = [
    ("INCLUDE", 0.0, 0.0, 5),
    ("BBOX(geom, -60, -60, 60, 60)", 10.0, -5.0, 7),
    ("BBOX(geom, -10, -10, 10, 10) AND dtg DURING "
     "2020-01-05T00:00:00Z/2020-01-20T00:00:00Z", -3.0, 4.0, 6),
]

# write-path stage: the extra corpus is this fraction of the base one
WRITE_EXTRA_DIV = 8


# balance-drill corpus window: a 2-hour dtg span starting on an
# epoch-week boundary keeps every row in ONE z3 time bin, so the
# (bin << 48 | z) partition keys become spatial-major and coarse Morton
# cells map cleanly onto contiguous shard key ranges (the default
# 30-day corpus interleaves time bins and spatial cells straddle shards)
DRILL_START = "2020-01-06T00:00:00"
DRILL_SPAN_MS = 2 * 3600 * 1000


def make_corpus(n: int, seed: int, span_ms: Optional[int] = None,
                start: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Deterministic shared corpus; the tail duplicates head rows
    (same point, same timestamp) to force key ties across processes.
    ``span_ms``/``start`` narrow the dtg window (the balance drill needs
    a single z3 time bin); defaults reproduce the historical corpus."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    base = np.datetime64(start or "2020-01-01T00:00:00",
                         "ms").astype(np.int64)
    span = int(span_ms) if span_ms else 30 * 86400000
    dtg = base + rng.integers(0, span, n)
    name = rng.choice(["a", "b", "c"], n)
    val = rng.integers(0, 100, n).astype(np.int32)
    dup = max(1, n // 64)
    x[-dup:], y[-dup:], dtg[-dup:] = x[:dup], y[:dup], dtg[:dup]
    return {"x": x, "y": y, "dtg": dtg, "name": name, "val": val}


def _partition_keys(sft, table) -> np.ndarray:
    """Morton partition key per row: a MONOTONE coarsening of the z3
    index sort order (bin major, z high bits minor) — rows with equal
    full keys share a partition key, so no key range ever straddles a
    process boundary and the within-shard index sort restores the exact
    global order."""
    from geomesa_tpu.curves.binnedtime import TimePeriod
    from geomesa_tpu.index.spatial import Z3Index, _DeltaKeyShim

    shim = _DeltaKeyShim(sft, table, sft.geometry_attribute.name,
                         sft.dtg_attribute.name,
                         TimePeriod.parse(sft.z3_interval))
    Z3Index._sort_keys(shim)
    bins = np.asarray(shim._bins, dtype=np.int64)
    z = np.asarray(shim._z, dtype=np.int64)
    return (bins << 48) | (z >> 15)


def inactive_runtime() -> ClusterRuntime:
    """A single-process runtime for the oracle path (never touches the
    process-global singleton or jax.distributed)."""
    rt = ClusterRuntime()
    rt.initialized = True
    rt.topology = "flat"
    return rt


def build_local(rt: ClusterRuntime, n: int, seed: int,
                stages: Optional[dict] = None,
                span_ms: Optional[int] = None,
                start: Optional[str] = None):
    """Slice → partition → store/index → global table. Collective when
    the runtime is active; the complete single-process pipeline when
    not (the oracle)."""
    from geomesa_tpu import DataStoreFinder, config
    from geomesa_tpu.cluster.build import cluster_partition
    from geomesa_tpu.cluster.exec import ClusterScan
    from geomesa_tpu.cluster.table import ClusterShardedTable
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.features.table import FeatureTable

    if stages is None:
        stages = {}
    t0 = time.perf_counter()
    corpus = make_corpus(n, seed, span_ms=span_ms, start=start)
    if rt.active():
        ids = np.arange(rt.process_id, n, rt.num_processes, dtype=np.int64)
    else:
        ids = np.arange(n, dtype=np.int64)
    mine = {k: v[ids] for k, v in corpus.items()}
    stages["corpus_s"] = round(time.perf_counter() - t0, 3)

    sft = SimpleFeatureType.from_spec(TYPE, SPEC)
    t0 = time.perf_counter()
    key_table = FeatureTable.build(sft, {
        "name": mine["name"], "val": mine["val"], "dtg": mine["dtg"],
        "geom": (mine["x"], mine["y"])})
    keys = _partition_keys(sft, key_table)
    stages["keys_s"] = round(time.perf_counter() - t0, 3)

    keys_l, part, bounds, stages = cluster_partition(
        rt, keys, {**mine, "gid": ids}, gids=ids, stages=stages)

    t0 = time.perf_counter()
    fids = ["f%09d" % g for g in part["gid"]]
    ds = DataStoreFinder.get_data_store(backend="tpu")
    ds.create_schema(TYPE, SPEC)
    ds.load(TYPE, FeatureTable.build(ds.get_schema(TYPE), {
        "name": part["name"], "val": part["val"].astype(np.int32),
        "dtg": part["dtg"].astype(np.int64),
        "geom": (part["x"], part["y"])}, fids=fids))
    planner = ds.planner(TYPE)
    idx = next(i for i in planner.indexes if i.name == "z3")
    stages["index_build_s"] = round(time.perf_counter() - t0, 3)

    t0 = time.perf_counter()
    host_cols = {k: np.asarray(v) for k, v in idx.device.columns.items()}
    st = ClusterShardedTable.from_local_columns(rt, host_cols,
                                                key_bounds=bounds)
    stages["global_table_s"] = round(time.perf_counter() - t0, 3)
    rt.register_table(TYPE, st.layout.summary())
    if config.SHARDWATCH_ENABLED.get():
        # shard balance observatory: exchange the empirical cell -> shard
        # occupancy map (collective — symmetric because the knob is env-
        # driven and identical across ranks) and install it in the ledger
        from geomesa_tpu.cluster.table import shard_cell_map
        from geomesa_tpu.obs import shardwatch as _shardwatch
        t0 = time.perf_counter()
        cells, key_ranges, shard_rows = shard_cell_map(
            rt, part["x"], part["y"], keys_l)
        _shardwatch.WATCH.set_shard_map(TYPE, cells, key_ranges,
                                        shard_rows)
        stages["shard_map_s"] = round(time.perf_counter() - t0, 3)
    fids_sorted = np.asarray(planner.table.fids)[np.asarray(idx.perm)]
    return ds, planner, ClusterScan(st), fids_sorted, stages


def run_battery(planner, scan, fids_sorted) -> dict:
    """Counts + density + ordered-merge selects; identical output shape
    on every rank AND on the oracle (which is how equality is judged)."""
    out = {"counts": {}, "count_warm_ms": {}, "selects": {}}
    for q in COUNT_QUERIES:
        plan = planner.plan(q)
        c = scan.count(plan)                       # compile + collective
        t0 = time.perf_counter()
        c2 = scan.count(plan)
        out["count_warm_ms"][q] = round(
            (time.perf_counter() - t0) * 1000.0, 3)
        assert c == c2
        out["counts"][q] = int(c)
    plan = planner.plan(DENSITY_QUERY)
    grid = scan.density(plan, DENSITY_BBOX, *DENSITY_WH)
    t0 = time.perf_counter()
    grid = scan.density(plan, DENSITY_BBOX, *DENSITY_WH)
    out["density_warm_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
    g32 = np.ascontiguousarray(np.asarray(grid, dtype=np.float32))
    out["density_sha"] = hashlib.sha256(g32.tobytes()).hexdigest()
    out["density_sum"] = float(g32.sum())
    for q in SELECT_QUERIES:
        plan = planner.plan(q)
        t0 = time.perf_counter()
        merged = scan.select_merged(plan, {"fid": fids_sorted})
        out.setdefault("select_ms", {})[q] = round(
            (time.perf_counter() - t0) * 1000.0, 3)
        out["selects"][q] = merged["fid"]

    # geometry catalog: st_* function counts + the sharded spatial join
    # (same code path on the oracle — inactive runtime collapses the
    # psum/merge, so equality judges the distribution, not the kernels)
    from geomesa_tpu.geom.join import func_counts, join_battery
    rt = getattr(scan, "runtime", None)
    t0 = time.perf_counter()
    out["func_counts"] = func_counts(planner, FUNC_COUNT_QUERIES,
                                     runtime=rt)
    jb = join_battery(planner, JOIN_POLYGONS, runtime=rt,
                      fids=fids_sorted, max_pairs=JOIN_MAX_PAIRS)
    out["join"] = jb["stable"]
    out["join_meta"] = jb["meta"]
    out["geom_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
    return out


# -- cluster knn + the distributed write path ---------------------------------


def _knn_key(q: str, x: float, y: float, k: int) -> str:
    return f"{q}|{x},{y},k={k}"


def run_knn(planner, scan) -> dict:
    """The bounded-radius-exchange battery: every query's (ids, dists)
    plus the number of collective rounds it took (the dryrun asserts
    rounds are counted and stay under the cap)."""
    from geomesa_tpu.cluster.exec import KNN_STATS
    out: dict = {"results": {}, "rounds": {}}
    for q, x, y, k in KNN_QUERIES:
        plan = planner.plan(q)
        before = KNN_STATS["rounds_total"]
        ids, d = scan.knn(plan, x, y, k)
        out["results"][_knn_key(q, x, y, k)] = {
            "ids": [int(i) for i in ids],
            "d": [float(v) for v in np.asarray(d, dtype=np.float32)]}
        out["rounds"][_knn_key(q, x, y, k)] = \
            KNN_STATS["rounds_total"] - before
    out["stats"] = dict(KNN_STATS)
    return out


def oracle_knn(planner, scan) -> dict:
    """The brute-force oracle: no top-k machinery at all — f64 haversine
    over EVERY masked row, (distance, gid) lexsort, take k. What the
    radius exchange must match byte-for-byte."""
    from geomesa_tpu.process.geo import haversine_m
    gx, gy = scan.sharded.host_xy
    out = {}
    for q, x, y, k in KNN_QUERIES:
        idx = np.flatnonzero(scan.mask(planner.plan(q)))
        d = haversine_m(np.asarray(gx)[idx].astype(np.float64),
                        np.asarray(gy)[idx].astype(np.float64),
                        float(x), float(y))
        top = np.lexsort((idx, d))[:k]
        out[_knn_key(q, x, y, k)] = {
            "ids": [int(i) for i in idx[top]],
            "d": [float(v) for v in d[top].astype(np.float32)]}
    return out


def _extra_table(sft, extra: Dict[str, np.ndarray], ids: np.ndarray):
    from geomesa_tpu.features.table import FeatureTable
    return FeatureTable.build(sft, {
        "name": extra["name"][ids],
        "val": extra["val"][ids].astype(np.int32),
        "dtg": extra["dtg"][ids].astype(np.int64),
        "geom": (extra["x"][ids], extra["y"][ids])},
        fids=["e%09d" % g for g in ids])


def run_post_battery(planner, scan, fids_sorted) -> dict:
    """Post-ingest exactness battery (counts + density sha + merged
    selects): byte-equality against the oracle's post-ingest run IS the
    'writes landed on the owning cell' proof — a row on the wrong shard
    breaks rank-order merge, a lost row breaks every count."""
    out: dict = {"counts": {}, "selects": {}}
    for q in COUNT_QUERIES:
        out["counts"][q] = int(scan.count(planner.plan(q)))
    grid = scan.density(planner.plan(DENSITY_QUERY), DENSITY_BBOX,
                        *DENSITY_WH)
    g32 = np.ascontiguousarray(np.asarray(grid, dtype=np.float32))
    out["density_sha"] = hashlib.sha256(g32.tobytes()).hexdigest()
    for q in SELECT_QUERIES:
        out["selects"][q] = scan.select_merged(
            planner.plan(q), {"fid": fids_sorted})["fid"]
    return out


def run_write_path(rt: ClusterRuntime, ds, scan, n: int, seed: int,
                   span_ms: Optional[int] = None,
                   start: Optional[str] = None) -> dict:
    """The distributed durable write path: a fresh extra corpus routes
    by Morton key ownership (ShardCells over the layout's key ranges),
    each process ingests ONLY its owned rows, the cluster table
    reassembles, and the post-ingest battery must be byte-equal to the
    oracle that ingested everything single-process."""
    from geomesa_tpu.cluster.cells import ShardCells
    from geomesa_tpu.cluster.exec import ClusterScan
    from geomesa_tpu.cluster.table import ClusterShardedTable
    from geomesa_tpu.features.table import FeatureTable

    t0 = time.perf_counter()
    n_extra = max(64, n // WRITE_EXTRA_DIV)
    extra = make_corpus(n_extra, seed + 1, span_ms=span_ms, start=start)
    sft = ds.get_schema(TYPE)
    keys = _partition_keys(sft, FeatureTable.build(sft, {
        "name": extra["name"], "val": extra["val"].astype(np.int32),
        "dtg": extra["dtg"].astype(np.int64),
        "geom": (extra["x"], extra["y"])}))
    if rt.active() and scan.layout.key_ranges:
        owners = ShardCells.from_key_ranges(
            scan.layout.key_ranges).route(keys)
        mine = np.flatnonzero(owners == rt.process_id)
    else:
        mine = np.arange(n_extra, dtype=np.int64)
    if len(mine):
        ds.load(TYPE, _extra_table(sft, extra, mine))

    planner = ds.planner(TYPE)        # flush: extras merge into the index
    idx = next(i for i in planner.indexes if i.name == "z3")
    host_cols = {k: np.asarray(v) for k, v in idx.device.columns.items()}
    post_keys = _partition_keys(sft, planner.table)
    st = ClusterShardedTable.from_local_columns(
        rt, host_cols,
        key_bounds=(int(post_keys.min()), int(post_keys.max())))
    scan2 = ClusterScan(st)
    fids_sorted = np.asarray(planner.table.fids)[np.asarray(idx.perm)]
    post = run_post_battery(planner, scan2, fids_sorted)
    return {
        "n_extra": int(n_extra),
        "ingested": int(len(mine)),
        "owned_sha": hashlib.sha256(
            np.asarray(mine, dtype=np.int64).tobytes()).hexdigest(),
        "post": post,
        "key_range": st.layout.key_ranges[rt.process_id]
            if st.layout.key_ranges else None,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _expected_routing(key_ranges, n: int, seed: int) -> List[dict]:
    """What ownership routing SHOULD do, recomputed independently by the
    orchestrator from each rank's reported key range."""
    from geomesa_tpu.cluster.cells import ShardCells
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.features.table import FeatureTable

    n_extra = max(64, n // WRITE_EXTRA_DIV)
    extra = make_corpus(n_extra, seed + 1)
    sft = SimpleFeatureType.from_spec(TYPE, SPEC)
    keys = _partition_keys(sft, FeatureTable.build(sft, {
        "name": extra["name"], "val": extra["val"].astype(np.int32),
        "dtg": extra["dtg"].astype(np.int64),
        "geom": (extra["x"], extra["y"])}))
    owners = ShardCells.from_key_ranges(key_ranges).route(keys)
    out = []
    for p in range(len(key_ranges)):
        mine = np.flatnonzero(owners == p)
        out.append({"ingested": int(len(mine)),
                    "owned_sha": hashlib.sha256(
                        np.asarray(mine, dtype=np.int64).tobytes())
                        .hexdigest()})
    return out


# -- the balance drill --------------------------------------------------------


def _drill_cells(cells: Dict[str, dict], shard: str, k: int = 16,
                 min_rows: int = 8, min_share: float = 0.9) -> List[str]:
    """Cells owned (>= ``min_share`` of their rows) by ``shard`` with
    enough rows to be meaningful, densest first — the drill's target
    set (clean ownership keeps the expected attribution unambiguous)."""
    owned = []
    for cell, owners in cells.items():
        rows = {s: int(o["rows"]) for s, o in owners.items()}
        tot = sum(rows.values())
        if tot >= min_rows and rows.get(shard, 0) / tot >= min_share:
            owned.append((cell, tot))
    owned.sort(key=lambda t: (-t[1], t[0]))
    return [c for c, _ in owned[:k]]


def run_drill(rt: ClusterRuntime, mode: str, seed: int,
              n_events: Optional[int] = None) -> dict:
    """The balance drill: rank 0 synthesizes a query-event storm through
    the observability plane's own input surface (flight record → workload
    tee → shardwatch ledger), then every rank reports its ledger verdict.

    ``skew`` is a Zipf storm (s=1.3) over cells owned by the LAST shard
    — rank 0 emits the events, so the ledger must attribute load across
    a rank boundary to name the victim. ``uniform`` spreads the same
    event count evenly over every shard's cells (the two-sided control:
    balance ≈ 1.0, zero incidents)."""
    from geomesa_tpu.obs import flight as _flight
    from geomesa_tpu.obs import shardwatch as _shardwatch
    from geomesa_tpu.obs.doctor import DOCTOR

    n_events = int(n_events if n_events is not None else os.environ.get(
        "GEOMESA_TPU_DRYRUN_DRILL_EVENTS", "600"))
    smap = (_shardwatch.WATCH.export_state()["maps"] or {}).get(TYPE) \
        or {}
    cells = smap.get("cells") or {}
    shards = sorted(smap.get("key_ranges") or {})
    victim = shards[-1] if shards else "0"
    out: dict = {"mode": mode, "victim": victim, "events": 0}
    if rt.process_id == 0 and cells:
        rng = np.random.default_rng(seed + 1000)
        now_ms = int(time.time() * 1000)
        if mode == "skew":
            pool = _drill_cells(cells, victim)
            w = 1.0 / np.arange(1, len(pool) + 1, dtype=np.float64) ** 1.3
        else:
            # equal weight PER SHARD (not per cell) so the control stays
            # balanced even when shards differ in qualifying-cell count
            pool, wl = [], []
            for s in shards:
                owned = _drill_cells(cells, s)
                pool.extend(owned)
                wl.extend([1.0 / max(1, len(owned))] * len(owned))
            w = np.asarray(wl, dtype=np.float64)
        if len(pool):
            w = w / w.sum()
            picks = rng.choice(len(pool), size=n_events, p=w)
            for j, i in enumerate(picks):
                cell = pool[int(i)]
                rows = sum(int(o["rows"]) for o in cells[cell].values())
                _flight.RECORDER.record({
                    "ts_ms": now_ms, "kind": "query", "type": TYPE,
                    "plan_hash": f"drill:{cell}", "cell": cell,
                    "priority": "interactive",
                    "tenant": f"drill{j % 3}",
                    "duration_ms": 2.0, "rows_scanned": rows,
                    "rows_matched": rows, "device_ms": 0.4})
            out["events"] = int(n_events)
            out["pool_cells"] = len(pool)
    out["balance"] = _shardwatch.WATCH.balance()
    res = DOCTOR.evaluate()
    out["alerts"] = [a for a in res.get("alerts", [])
                     if a["rule"] in ("shard_imbalance",
                                      "collective_straggler")]
    out["imbalance_incidents"] = [
        {"rule": i.get("rule"), "cause": i.get("cause"),
         "suspect": i.get("suspect"), "status": i.get("status")}
        for i in res.get("incidents", [])
        if i.get("rule") == "shard_imbalance"]
    return out


# -- worker entry (one process of the cluster) --------------------------------


def worker_main(out_path: str) -> int:
    n = int(os.environ.get("GEOMESA_TPU_DRYRUN_N", "20000"))
    seed = int(os.environ.get("GEOMESA_TPU_DRYRUN_SEED", "7"))
    with_web = os.environ.get("GEOMESA_TPU_DRYRUN_WEB", "1") != "0"
    drill = os.environ.get("GEOMESA_TPU_DRYRUN_DRILL", "").strip().lower()
    span_ms = os.environ.get("GEOMESA_TPU_DRYRUN_SPAN_MS")
    start = os.environ.get("GEOMESA_TPU_DRYRUN_START") or None
    t_start = time.perf_counter()
    rt = runtime()
    stages: dict = {}
    ds, planner, scan, fids_sorted, stages = build_local(
        rt, n, seed, stages,
        span_ms=int(span_ms) if span_ms else None, start=start)
    battery = run_battery(planner, scan, fids_sorted)
    drill_report = run_drill(rt, drill, seed) if drill else None
    # knn + the distributed write path ride the default dryrun; the
    # drill variant keeps its historical (cfg13-scored) shape
    knn_report = run_knn(planner, scan) if not drill else None
    write_report = run_write_path(rt, ds, scan, n, seed) \
        if not drill else None

    fleet = None
    balance_http = None
    if with_web:
        from geomesa_tpu.web import serve
        httpd = serve(ds, port=0, background=True)
        port = httpd.server_address[1]
        nodes = rt.register_web(port)            # collective: all bound
        if nodes:
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleet", timeout=30) as r:
                fleet = json.loads(r.read().decode())
            if drill:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/cluster/balance",
                        timeout=30) as r:
                    balance_http = json.loads(r.read().decode())
                if rt.process_id == 0 and drill_report is not None:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/fleet/balance",
                            timeout=30) as r:
                        drill_report["fleet_balance"] = json.loads(
                            r.read().decode())

    report = {
        "process_id": rt.process_id,
        "num_processes": rt.num_processes,
        "local_rows": scan.sharded.local_rows(),
        "n_global": scan.sharded.n,
        "key_range": scan.layout.key_ranges[rt.process_id]
            if scan.layout.key_ranges else None,
        "psum_rounds": rt.psum_rounds,
        "cluster": rt.state(),
        "battery": battery,
        "stages": stages,
        "fleet": fleet,
        "knn": knn_report,
        "write": write_report,
        "drill": drill_report,
        "balance_http": balance_http,
        "wall_s": round(time.perf_counter() - t_start, 3),
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    rt.barrier("dryrun-done")
    return 0


# -- orchestrator -------------------------------------------------------------


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_dryrun(num_processes: int = 2, n: int = 20000, seed: int = 7,
               timeout_s: float = 420.0, local_devices: int = 2,
               out_dir: Optional[str] = None, web: bool = True,
               drill: Optional[str] = None) -> dict:
    """Spawn the N-process dryrun, compute the oracle in-process, and
    return the merged report with exactness checks + timings. ``drill``
    ("skew" | "uniform") additionally runs the shard-balance drill on the
    single-z3-bin corpus window (see ``DRILL_START``)."""
    if drill and drill not in ("skew", "uniform"):
        raise ValueError(f"unknown drill mode: {drill!r}")
    t_start = time.perf_counter()
    work = out_dir or tempfile.mkdtemp(prefix="geomesa_cluster_dryrun_")
    os.makedirs(work, exist_ok=True)
    span_ms = DRILL_SPAN_MS if drill else None
    start = DRILL_START if drill else None

    coord = f"127.0.0.1:{_free_port()}"
    procs: List[subprocess.Popen] = []
    outs = []
    for p in range(num_processes):
        out_path = os.path.join(work, f"rank{p}.json")
        outs.append(out_path)
        env = os.environ.copy()
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={local_devices}",
            "GEOMESA_TPU_CLUSTER": "1",
            "GEOMESA_TPU_CLUSTER_COORDINATOR": coord,
            "GEOMESA_TPU_CLUSTER_NUM_PROCESSES": str(num_processes),
            "GEOMESA_TPU_CLUSTER_PROCESS_ID": str(p),
            "GEOMESA_TPU_NODE_ID": f"proc{p}",
            "GEOMESA_TPU_DRYRUN_N": str(n),
            "GEOMESA_TPU_DRYRUN_SEED": str(seed),
            "GEOMESA_TPU_DRYRUN_WEB": "1" if web else "0",
        })
        if drill:
            env.update({
                "GEOMESA_TPU_DRYRUN_DRILL": drill,
                "GEOMESA_TPU_DRYRUN_START": DRILL_START,
                "GEOMESA_TPU_DRYRUN_SPAN_MS": str(DRILL_SPAN_MS),
            })
        with open(os.path.join(work, f"rank{p}.log"), "w") as log:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "geomesa_tpu.cluster.dryrun",
                 "--worker", "--out", out_path],
                stdout=log, stderr=subprocess.STDOUT, env=env))

    # oracle while the workers run: same battery, inactive runtime
    # (same corpus window as the workers so equality still holds)
    rt0 = inactive_runtime()
    ds0, planner, scan, fids_sorted, ostages = build_local(
        rt0, n, seed, span_ms=span_ms, start=start)
    oracle = run_battery(planner, scan, fids_sorted)
    if not drill:
        oracle["knn_brute"] = oracle_knn(planner, scan)
        oracle["write"] = run_write_path(rt0, ds0, scan, n, seed)

    deadline = time.monotonic() + timeout_s
    rcs = [None] * num_processes
    while time.monotonic() < deadline and any(r is None for r in rcs):
        for i, pr in enumerate(procs):
            if rcs[i] is None:
                rcs[i] = pr.poll()
        time.sleep(0.2)
    for pr in procs:
        if pr.poll() is None:
            pr.kill()
    rcs = [pr.poll() for pr in procs]

    ranks = []
    for path in outs:
        try:
            with open(path) as f:
                ranks.append(json.load(f))
        except Exception:
            ranks.append(None)

    checks = _check(oracle, ranks, n, num_processes, web, drill,
                    seed=seed)
    report = {
        "ok": all(checks.values()) and all(rc == 0 for rc in rcs),
        "num_processes": num_processes,
        "n": n,
        "drill": drill,
        "exit_codes": rcs,
        "checks": checks,
        "oracle": {k: oracle[k] for k in
                   ("counts", "density_sha", "density_sum")},
        "ranks": ranks,
        "oracle_stages": ostages,
        "work_dir": work,
        "wall_s": round(time.perf_counter() - t_start, 3),
    }
    with open(os.path.join(work, "dryrun_report.json"), "w") as f:
        json.dump(report, f, indent=2)
    return report


def _check(oracle: dict, ranks: List[Optional[dict]], n: int,
           num_processes: int, web: bool,
           drill: Optional[str] = None,
           seed: int = 7) -> Dict[str, bool]:
    live = [r for r in ranks if r is not None]
    checks = {"all_ranks_reported": len(live) == num_processes}
    if not checks["all_ranks_reported"]:
        return checks
    checks["counts_equal"] = all(
        r["battery"]["counts"] == oracle["counts"] for r in live)
    checks["density_equal"] = all(
        r["battery"]["density_sha"] == oracle["density_sha"] for r in live)
    checks["selects_equal"] = all(
        r["battery"]["selects"] == oracle["selects"] for r in live)
    checks["func_counts_equal"] = all(
        r["battery"].get("func_counts") == oracle["func_counts"]
        for r in live)
    checks["join_equal"] = all(
        r["battery"].get("join") == oracle["join"] for r in live)
    checks["shards_strict_subset"] = all(
        0 < r["local_rows"] < n for r in live) and \
        sum(r["local_rows"] for r in live) == n
    kr = [r["key_range"] for r in sorted(live,
                                         key=lambda r: r["process_id"])]
    checks["key_ranges_ordered"] = (
        all(k is not None for k in kr)
        and all(kr[i][1] <= kr[i + 1][0] for i in range(len(kr) - 1)))
    checks["psum_rounds_counted"] = all(
        r["psum_rounds"] > 0 for r in live)
    if web:
        def _fleet_ok(r):
            nodes = (r["fleet"] or {}).get("nodes") or {}
            return (len(nodes) == num_processes
                    and all(v.get("ok") for v in nodes.values()))
        checks["fleet_registered"] = all(_fleet_ok(r) for r in live)
    if drill:
        # every rank ran the drill and rank 0's ledger was active
        # (scoring against the pinned bars lives in bench cfg13)
        checks["drill_reported"] = all(
            (r.get("drill") or {}).get("mode") == drill for r in live)
        r0 = next((r for r in live if r["process_id"] == 0), None)
        checks["drill_ledger_active"] = bool(
            r0 and ((r0.get("drill") or {}).get("balance")
                    or {}).get("active"))
    else:
        from geomesa_tpu import config
        # cluster knn: every rank's radius exchange byte-equals the
        # brute-force oracle, with the collective rounds counted and
        # under the cap (exactly 2 per exact query)
        brute = oracle.get("knn_brute")
        checks["knn_exact"] = all(
            (r.get("knn") or {}).get("results") == brute for r in live)
        cap = max(2, int(config.CELL_KNN_MAX_ROUNDS.get()))
        checks["knn_rounds_bounded"] = all(
            (r.get("knn") or {}).get("rounds")
            and all(0 < v <= cap
                    for v in r["knn"]["rounds"].values())
            for r in live)
        # write path: each rank ingested EXACTLY the rows ownership
        # routing assigns it (recomputed independently here), and the
        # post-ingest cluster table byte-equals the oracle that
        # ingested everything single-process
        expected = _expected_routing(kr, n, seed) \
            if checks["key_ranges_ordered"] else None
        by_pid = {r["process_id"]: (r.get("write") or {}) for r in live}
        checks["write_landed_on_owner"] = bool(expected) and all(
            by_pid.get(p, {}).get("ingested") == e["ingested"]
            and by_pid.get(p, {}).get("owned_sha") == e["owned_sha"]
            for p, e in enumerate(expected))
        n_extra = max(64, n // WRITE_EXTRA_DIV)
        checks["write_strict_subset"] = (
            sum(w.get("ingested", 0) for w in by_pid.values()) == n_extra
            and all(w.get("ingested", 0) < n_extra
                    for w in by_pid.values()))
        checks["write_post_equal"] = all(
            (r.get("write") or {}).get("post") == oracle["write"]["post"]
            for r in live)
    return checks


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="N-process CPU cluster dryrun vs single-process oracle")
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as one spawned cluster process")
    ap.add_argument("--out", default="dryrun_report.json")
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--n", type=int, default=20000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--timeout-s", type=float, default=420.0)
    ap.add_argument("--no-web", action="store_true")
    ap.add_argument("--drill", choices=["skew", "uniform"], default=None,
                    help="run the shard-balance drill (Zipf storm on one "
                         "shard's key range, or the uniform control)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args.out)
    report = run_dryrun(args.procs, args.n, args.seed,
                        timeout_s=args.timeout_s, web=not args.no_web,
                        drill=args.drill)
    print(json.dumps({k: report[k] for k in
                      ("ok", "checks", "wall_s", "work_dir")}, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
