"""Process-partitioned sharded table: the global-array construction path.

``ShardedTable.from_host_columns`` (parallel/mesh.py) device_puts full
host columns — correct single-process, impossible multi-process (no host
holds the whole table). Here each process supplies ONLY its local shard
(a contiguous Morton key range, sorted locally; cluster/build.py makes
that true for arbitrary input) and the shards assemble into one global
``jax.Array`` with ``jax.make_array_from_process_local_data`` over the
cluster mesh:

  - the per-DEVICE row chunk is the unit: every device gets the same
    chunk (max over processes of ceil(local_n / local_devices)), so the
    row axis divides evenly however many devices each process brings;
  - local shards pad at the END of the process block with the same
    out-of-domain ``_pad_value`` + ``__valid__=False`` discipline as the
    single-process table, so pad rows can never match a predicate;
  - process blocks are contiguous because the mesh device order is
    sorted by (process_index, id) — global row id of local row i is
    simply ``block_start(p) + i``, and rank-order concatenation of
    per-process results IS the global key order.

``split_points`` generalize to ``key_ranges``: per-process [lo, hi]
Morton key ownership boundaries, exchanged at construction and surfaced
on /cluster for ops parity with the reference's tablet split points.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.cluster.runtime import ClusterRuntime
from geomesa_tpu.parallel.mesh import ShardedTable, _pad_value


def shard_cell_map(rt: ClusterRuntime, xs, ys, keys, bits=None):
    """Empirical cell -> shard occupancy: which shard holds how many
    rows of each coarse Morton cell, plus the per-shard key span of
    those rows (obs/sketches.cell_key geometry, so the workload plane's
    hot cells join directly against it).

    Collective when the cluster is active (one small allgather of the
    per-shard cell tallies); solo it degrades to a one-shard map. Feeds
    ``obs.shardwatch.WATCH.set_shard_map`` — the ledger's ownership
    side. Returns ``(cells, key_ranges, shard_rows)`` keyed by shard id
    strings."""
    from geomesa_tpu.obs.sketches import z_interleave

    if bits is None:
        bits = int(config.WORKLOAD_CELL_BITS.get())
    bits = max(1, min(16, int(bits)))
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    keys = np.asarray(keys, dtype=np.int64)
    n = 1 << bits
    # same center-quantization as sketches.cell_key (point rows ARE
    # their own bbox center), truncation and clamping included
    gx = np.clip(((xs + 180.0) / 360.0 * n).astype(np.int64), 0, n - 1)
    gy = np.clip(((ys + 90.0) / 180.0 * n).astype(np.int64), 0, n - 1)
    local = {}
    if len(keys):
        gid = gx * n + gy
        uniq, inv = np.unique(gid, return_inverse=True)
        counts = np.bincount(inv, minlength=len(uniq))
        klo = np.full(len(uniq), np.iinfo(np.int64).max, dtype=np.int64)
        khi = np.full(len(uniq), np.iinfo(np.int64).min, dtype=np.int64)
        np.minimum.at(klo, inv, keys)
        np.maximum.at(khi, inv, keys)
        width = max(1, (2 * bits + 3) // 4)
        for u, c, lo, hi in zip(uniq.tolist(), counts.tolist(),
                                klo.tolist(), khi.tolist()):
            z = z_interleave(int(u) // n, int(u) % n)
            local[f"b{bits}:{z:0{width}x}"] = {
                "rows": int(c), "key_lo": int(lo), "key_hi": int(hi)}
    me = {"proc": rt.process_id if rt.active() else 0,
          "rows": int(len(keys)),
          "key_range": [int(keys.min()), int(keys.max())]
          if len(keys) else [0, -1],
          "cells": local}
    peers = rt.exchange(me, op="shard_map")
    cells, key_ranges, shard_rows = {}, {}, {}
    for p in peers:
        s = str(p["proc"])
        key_ranges[s] = [int(p["key_range"][0]), int(p["key_range"][1])]
        shard_rows[s] = int(p["rows"])
        for cell, o in (p["cells"] or {}).items():
            cells.setdefault(cell, {})[s] = o
    return cells, key_ranges, shard_rows


@dataclass
class ClusterLayout:
    """Who owns which rows: the cross-process ownership map."""

    process_id: int
    num_processes: int
    per_dev_rows: int            # rows per device (the even-split unit)
    proc_rows: List[int]         # true (unpadded) rows per process
    proc_padded: List[int]       # padded block size per process
    key_ranges: Optional[List[List[int]]] = None   # per-process [lo, hi]
    local_devices: List[int] = field(default_factory=list)

    @property
    def n_global(self) -> int:
        return int(sum(self.proc_rows))

    @property
    def n_padded_global(self) -> int:
        return int(sum(self.proc_padded))

    def block_start(self, p: Optional[int] = None) -> int:
        """Global (padded) row offset of process p's block."""
        p = self.process_id if p is None else p
        return int(sum(self.proc_padded[:p]))

    def summary(self) -> dict:
        """The /cluster ownership table (JSON-safe)."""
        return {
            "n_global": self.n_global,
            "per_dev_rows": self.per_dev_rows,
            "proc_rows": [int(r) for r in self.proc_rows],
            "proc_padded": [int(r) for r in self.proc_padded],
            "key_ranges": None if self.key_ranges is None else
                [[int(a), int(b)] for a, b in self.key_ranges],
        }


class ClusterShardedTable(ShardedTable):
    """A ShardedTable whose columns are process-spanning global arrays.

    Drop-in for DistributedScan's column access; ``replicated`` switches
    to the callback constructor (device_put of a host array cannot
    target a multi-process sharding)."""

    layout: ClusterLayout = None
    runtime: ClusterRuntime = None

    @classmethod
    def from_local_columns(cls, rt: ClusterRuntime,
                           local_cols: Dict[str, np.ndarray],
                           key_bounds: Optional[tuple] = None,
                           axis: str = "rows") -> "ClusterShardedTable":
        """Assemble the global table from THIS process's shard.

        ``key_bounds`` is this process's (lo, hi) Morton ownership range
        (ints), exchanged into the layout for /cluster. Collective: every
        process must call this with its own shard."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not rt.active():
            # single-process degenerate: the ordinary path, plus a layout
            mesh = rt.mesh(axis)
            t = ShardedTable.from_host_columns(mesh, local_cols)
            self = cls(t.mesh, t.n, t.n_padded, t.columns, t.host_xy)
            self.runtime = rt
            ndev = int(mesh.devices.size)
            self.layout = ClusterLayout(
                0, 1, t.n_padded // ndev, [t.n], [t.n_padded],
                None if key_bounds is None else
                [[int(key_bounds[0]), int(key_bounds[1])]],
                [ndev])
            return self

        mesh = rt.mesh(axis)
        spec = P(rt.data_spec_axes(axis))
        n_local = int(len(next(iter(local_cols.values()))))
        me = {"rows": n_local, "local_devices": rt.local_device_count()}
        if key_bounds is not None:
            me["key_lo"] = int(key_bounds[0])
            me["key_hi"] = int(key_bounds[1])
        peers = rt.exchange(me)
        per_dev = max(
            -(-p["rows"] // max(1, p["local_devices"])) for p in peers)
        per_dev = max(1, per_dev)
        proc_rows = [p["rows"] for p in peers]
        proc_padded = [per_dev * p["local_devices"] for p in peers]
        key_ranges = None
        if all("key_lo" in p for p in peers):
            key_ranges = [[p["key_lo"], p["key_hi"]] for p in peers]
        layout = ClusterLayout(rt.process_id, rt.num_processes, per_dev,
                               proc_rows, proc_padded, key_ranges,
                               [p["local_devices"] for p in peers])

        my_padded = proc_padded[rt.process_id]
        n_global_padded = layout.n_padded_global
        cols = {}
        host_xy = None
        if "xf" in local_cols and "yf" in local_cols:
            host_xy = (np.asarray(local_cols["xf"]),
                       np.asarray(local_cols["yf"]))
        for name, arr in local_cols.items():
            arr = np.asarray(arr)
            if my_padded != n_local:
                pad_val = _pad_value(name, arr.dtype)
                pad = np.full((my_padded - n_local,) + arr.shape[1:],
                              pad_val, dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            cols[name] = jax.make_array_from_process_local_data(
                NamedSharding(mesh, spec), arr,
                (n_global_padded,) + arr.shape[1:])
        valid = np.zeros(my_padded, dtype=bool)
        valid[:n_local] = True
        cols["__valid__"] = jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), valid, (n_global_padded,))

        self = cls(mesh, layout.n_global, n_global_padded, cols, host_xy)
        self.layout = layout
        self.runtime = rt
        return self

    def replicated(self, arr: np.ndarray):
        """Query constants replicated on every device of every process."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        arr = np.asarray(arr)
        if self.runtime is None or not self.runtime.active():
            return super().replicated(arr)
        sharding = NamedSharding(self.mesh, P())
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def local_rows(self) -> int:
        """True rows this process holds (< n when the cluster is real —
        the 'strictly less than the full table' acceptance unit)."""
        return int(self.layout.proc_rows[self.layout.process_id])
