"""Cluster query execution: psum-reduced global kernels + ordered merge.

The single-process DistributedScan already shards rows across local
devices; across PROCESSES only two things change, and both live here:

  - count/density jit with ``out_shardings=NamedSharding(mesh, P())``:
    XLA inserts the cross-process psum, and EVERY process returns the
    exact global answer (the paper's "psum-reduced hit counts" across a
    pod). Each dispatch bumps the ``cluster.psum_rounds`` counter the
    /cluster surface and ``debug cluster`` report.
  - select/export cannot psum (ragged payloads): each process compacts
    its LOCAL matches — readable host-side because its block of the
    global array is addressable — and results stream through a
    host-side ordered merge. Rank order == Morton key order (the table
    is partitioned by contiguous key range), so concatenation in rank
    order IS the global sort order: no re-sort, no k-way heap.

  - knn runs a bounded radius exchange: each process ranks its LOCAL
    matches in f64 (its block's coordinates are host-addressable, so
    the exact re-rank needs nothing remote), round 1 exchanges every
    rank's local kth distance and takes the min — a proven upper bound
    on the global kth, since any rank holding k points within d has
    shown k global points within d — and round 2 exchanges only the
    ≤ k per-rank candidates inside that radius. Exactly two collective
    rounds for an exact answer, counted in ``KNN_STATS`` and capped by
    ``GEOMESA_TPU_CELL_KNN_MAX_ROUNDS``. Ties at the kth boundary break
    on (distance, global row id) so every process — and the
    single-process oracle — agrees byte-for-byte.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.cluster.runtime import note_collective
from geomesa_tpu.cluster.table import ClusterShardedTable
from geomesa_tpu.parallel.dist import DistributedScan, _build_mask


# radius-exchange accounting: the dryrun asserts rounds are counted and
# bounded (exactly 2 per exact query)
KNN_STATS = {"rounds_total": 0, "last_rounds": 0, "queries": 0}


class ClusterScan(DistributedScan):
    """DistributedScan over a process-partitioned ClusterShardedTable."""

    def __init__(self, sharded: ClusterShardedTable):
        super().__init__(sharded)
        self.runtime = sharded.runtime
        self.layout = sharded.layout

    def _active(self) -> bool:
        return self.runtime is not None and self.runtime.active()

    # -- psum-reduced global kernels ------------------------------------------

    def _jit(self, fn, replicated_out: bool = False):
        """The cluster side of DistributedScan's hook: replicated-out
        reductions compile with ``out_shardings=P()`` so XLA inserts the
        cross-process psum and every process holds the global answer."""
        import jax
        if not self._active() or not replicated_out:
            return jax.jit(fn)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.jit(fn,
                       out_shardings=NamedSharding(self.sharded.mesh, P()))

    def count(self, plan) -> int:
        if not self._active():
            return super().count(plan)
        import time as _time
        self.runtime.note_psum_round()
        t0 = _time.perf_counter()
        out = super().count(plan)
        note_collective("psum", _time.perf_counter() - t0)
        return out

    def density(self, plan, bbox, width: int, height: int,
                weight_attr: Optional[str] = None) -> np.ndarray:
        if not self._active():
            return super().density(plan, bbox, width, height, weight_attr)
        import time as _time
        self.runtime.note_psum_round()
        t0 = _time.perf_counter()
        out = super().density(plan, bbox, width, height, weight_attr)
        note_collective("psum", _time.perf_counter() - t0,
                        payload_bytes=out.nbytes)
        return out

    def knn(self, plan, x: float, y: float, k: int):
        """Exact cluster knn via bounded radius exchange (module
        docstring): (global row ids, distances_m f32), every process
        returning the identical answer. Falls back to the single-shard
        DistributedScan path when the cluster runtime is inactive."""
        if not self._active():
            return super().knn(plan, x, y, k)
        if plan.residual_host is not None \
                or plan.candidate_slices is not None:
            raise ValueError(
                "cluster knn needs a device-exact plan (host residuals "
                "cannot refine a k-limited result)")
        if self.sharded.host_xy is None:
            raise ValueError("cluster knn needs host coordinates "
                             "(ClusterShardedTable.host_xy)")
        import time as _time

        from geomesa_tpu import config
        from geomesa_tpu.process.geo import haversine_m

        k = int(k)
        max_rounds = max(2, int(config.CELL_KNN_MAX_ROUNDS.get()))
        rounds = 0

        def exchange(payload):
            nonlocal rounds
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError(
                    f"cluster knn exceeded {max_rounds} radius-exchange "
                    f"rounds (GEOMESA_TPU_CELL_KNN_MAX_ROUNDS)")
            self.runtime.note_psum_round()
            t0 = _time.perf_counter()
            out = self.runtime.exchange(payload, op="knn_radius")
            note_collective("knn_radius", _time.perf_counter() - t0)
            return out

        # local exact ranking: f64 re-rank of this process's matches
        idx = np.flatnonzero(self.local_mask(plan))
        gx, gy = self.sharded.host_xy
        d = haversine_m(np.asarray(gx)[idx].astype(np.float64),
                        np.asarray(gy)[idx].astype(np.float64),
                        float(x), float(y))
        order = np.argsort(d, kind="stable")
        idx, d = idx[order], d[order]
        row0 = int(sum(int(r) for r in
                       self.layout.proc_rows[: self.layout.process_id]))
        gids = row0 + idx.astype(np.int64)

        # round 1: min over every rank's local kth distance == a proven
        # upper bound on the global kth distance
        local_kth = float(d[k - 1]) if len(d) >= k else None
        kths = [p["kth"] for p in exchange({"kth": local_kth})]
        finite = [v for v in kths if v is not None]
        radius = min(finite) if finite else float("inf")

        # round 2: only candidates within the radius travel (≤ k/rank)
        n_send = min(k, int(np.searchsorted(d, radius, side="right"))
                     if np.isfinite(radius) else len(d))
        cand = [[int(g), float(v)]
                for g, v in zip(gids[:n_send], d[:n_send])]
        parts = exchange({"cand": cand})
        all_g = np.asarray([g for p in parts for g, _ in p["cand"]],
                           dtype=np.int64)
        all_d = np.asarray([v for p in parts for _, v in p["cand"]],
                           dtype=np.float64)
        KNN_STATS["last_rounds"] = rounds
        KNN_STATS["rounds_total"] += rounds
        KNN_STATS["queries"] += 1
        if not len(all_g):
            return (np.empty(0, dtype=np.int64),
                    np.empty(0, dtype=np.float32))
        # deterministic kth-boundary ties: (distance, global row id)
        top = np.lexsort((all_g, all_d))[:k]
        return all_g[top], all_d[top].astype(np.float32)

    # -- local compaction + ordered merge -------------------------------------

    def local_mask(self, plan) -> np.ndarray:
        """This process's boolean match mask over its TRUE local rows
        (host-readable: the local block of the global mask is
        addressable). Single-process falls back to the full mask."""
        if not self._active():
            return super().mask(plan)
        import jax
        import jax.numpy as jnp

        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("cluster_mask", plan.primary_kind,
               plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return _build_mask(cols, plan.primary_kind, boxes,
                                   windows, rfn, rparams)
            return jax.jit(step)

        fn = self._fn(key, build)
        out = fn(self.sharded.columns, boxes, windows, rparams)
        shards = sorted(out.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards])
        return local[: self.sharded.local_rows()]

    def mask(self, plan) -> np.ndarray:
        """Full global mask (hydration). On an active cluster this is an
        exchange of every process's local mask in rank order — used by
        oracle comparisons, not hot paths."""
        if not self._active():
            return super().mask(plan)
        local = self.local_mask(plan)
        parts = ordered_merge(self.runtime,
                              [int(i) for i in np.flatnonzero(local)])
        # rebuild global-row mask from per-process match offsets
        full = np.zeros(self.layout.n_global, dtype=bool)
        row0 = np.cumsum([0] + [int(r) for r in self.layout.proc_rows])
        for p, idxs in enumerate(parts):
            full[row0[p] + np.asarray(idxs, dtype=np.int64)] = True
        return full

    def select_local(self, plan,
                     values: Dict[str, np.ndarray]) -> Dict[str, list]:
        """Compact ``values`` (per-local-row payload columns, e.g. fids)
        down to this process's matches, in local key order."""
        m = self.local_mask(plan)
        idx = np.flatnonzero(m)
        return {k: [_json_safe(np.asarray(v)[i]) for i in idx]
                for k, v in values.items()}

    def select_merged(self, plan,
                      values: Dict[str, np.ndarray]) -> Dict[str, list]:
        """Global select: local compaction + host-side ordered merge.
        Every process returns the identical, globally key-ordered
        result (the client-side FeatureReducer step, collectivized)."""
        local = self.select_local(plan, values)
        if not self._active():
            return local
        parts = ordered_merge(self.runtime, local)
        merged: Dict[str, list] = {k: [] for k in values}
        for part in parts:
            for k in merged:
                merged[k].extend(part.get(k, []))
        return merged


def ordered_merge(rt, local_payload) -> List:
    """All-gather one JSON-safe payload per process, returned in RANK
    order — which is global key order for key-range-partitioned data.
    The host-side merge step of every cluster select/export (timed as
    the ``cluster.collective.row_exchange`` op)."""
    return [p["v"] for p in rt.exchange({"v": local_payload},
                                        op="row_exchange")]


def _json_safe(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, np.str_):
        return str(v)
    return v
