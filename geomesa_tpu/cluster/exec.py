"""Cluster query execution: psum-reduced global kernels + ordered merge.

The single-process DistributedScan already shards rows across local
devices; across PROCESSES only two things change, and both live here:

  - count/density jit with ``out_shardings=NamedSharding(mesh, P())``:
    XLA inserts the cross-process psum, and EVERY process returns the
    exact global answer (the paper's "psum-reduced hit counts" across a
    pod). Each dispatch bumps the ``cluster.psum_rounds`` counter the
    /cluster surface and ``debug cluster`` report.
  - select/export cannot psum (ragged payloads): each process compacts
    its LOCAL matches — readable host-side because its block of the
    global array is addressable — and results stream through a
    host-side ordered merge. Rank order == Morton key order (the table
    is partitioned by contiguous key range), so concatenation in rank
    order IS the global sort order: no re-sort, no k-way heap.

knn is explicitly rejected on an active cluster for now: the f64 host
re-rank needs candidate coordinates that live on other hosts, and a
silent f32-only answer would violate the documented contract.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.cluster.runtime import note_collective
from geomesa_tpu.cluster.table import ClusterShardedTable
from geomesa_tpu.parallel.dist import DistributedScan, _build_mask


class ClusterScan(DistributedScan):
    """DistributedScan over a process-partitioned ClusterShardedTable."""

    def __init__(self, sharded: ClusterShardedTable):
        super().__init__(sharded)
        self.runtime = sharded.runtime
        self.layout = sharded.layout

    def _active(self) -> bool:
        return self.runtime is not None and self.runtime.active()

    # -- psum-reduced global kernels ------------------------------------------

    def _jit(self, fn, replicated_out: bool = False):
        """The cluster side of DistributedScan's hook: replicated-out
        reductions compile with ``out_shardings=P()`` so XLA inserts the
        cross-process psum and every process holds the global answer."""
        import jax
        if not self._active() or not replicated_out:
            return jax.jit(fn)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.jit(fn,
                       out_shardings=NamedSharding(self.sharded.mesh, P()))

    def count(self, plan) -> int:
        if not self._active():
            return super().count(plan)
        import time as _time
        self.runtime.note_psum_round()
        t0 = _time.perf_counter()
        out = super().count(plan)
        note_collective("psum", _time.perf_counter() - t0)
        return out

    def density(self, plan, bbox, width: int, height: int,
                weight_attr: Optional[str] = None) -> np.ndarray:
        if not self._active():
            return super().density(plan, bbox, width, height, weight_attr)
        import time as _time
        self.runtime.note_psum_round()
        t0 = _time.perf_counter()
        out = super().density(plan, bbox, width, height, weight_attr)
        note_collective("psum", _time.perf_counter() - t0,
                        payload_bytes=out.nbytes)
        return out

    def knn(self, plan, x: float, y: float, k: int):
        if not self._active():
            return super().knn(plan, x, y, k)
        raise NotImplementedError(
            "cluster knn: the exact f64 re-rank needs remote candidate "
            "coordinates; run knn against a replicated table")

    # -- local compaction + ordered merge -------------------------------------

    def local_mask(self, plan) -> np.ndarray:
        """This process's boolean match mask over its TRUE local rows
        (host-readable: the local block of the global mask is
        addressable). Single-process falls back to the full mask."""
        if not self._active():
            return super().mask(plan)
        import jax
        import jax.numpy as jnp

        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("cluster_mask", plan.primary_kind,
               plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return _build_mask(cols, plan.primary_kind, boxes,
                                   windows, rfn, rparams)
            return jax.jit(step)

        fn = self._fn(key, build)
        out = fn(self.sharded.columns, boxes, windows, rparams)
        shards = sorted(out.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards])
        return local[: self.sharded.local_rows()]

    def mask(self, plan) -> np.ndarray:
        """Full global mask (hydration). On an active cluster this is an
        exchange of every process's local mask in rank order — used by
        oracle comparisons, not hot paths."""
        if not self._active():
            return super().mask(plan)
        local = self.local_mask(plan)
        parts = ordered_merge(self.runtime,
                              [int(i) for i in np.flatnonzero(local)])
        # rebuild global-row mask from per-process match offsets
        full = np.zeros(self.layout.n_global, dtype=bool)
        row0 = np.cumsum([0] + [int(r) for r in self.layout.proc_rows])
        for p, idxs in enumerate(parts):
            full[row0[p] + np.asarray(idxs, dtype=np.int64)] = True
        return full

    def select_local(self, plan,
                     values: Dict[str, np.ndarray]) -> Dict[str, list]:
        """Compact ``values`` (per-local-row payload columns, e.g. fids)
        down to this process's matches, in local key order."""
        m = self.local_mask(plan)
        idx = np.flatnonzero(m)
        return {k: [_json_safe(np.asarray(v)[i]) for i in idx]
                for k, v in values.items()}

    def select_merged(self, plan,
                      values: Dict[str, np.ndarray]) -> Dict[str, list]:
        """Global select: local compaction + host-side ordered merge.
        Every process returns the identical, globally key-ordered
        result (the client-side FeatureReducer step, collectivized)."""
        local = self.select_local(plan, values)
        if not self._active():
            return local
        parts = ordered_merge(self.runtime, local)
        merged: Dict[str, list] = {k: [] for k in values}
        for part in parts:
            for k in merged:
                merged[k].extend(part.get(k, []))
        return merged


def ordered_merge(rt, local_payload) -> List:
    """All-gather one JSON-safe payload per process, returned in RANK
    order — which is global key order for key-range-partitioned data.
    The host-side merge step of every cluster select/export (timed as
    the ``cluster.collective.row_exchange`` op)."""
    return [p["v"] for p in rt.exchange({"v": local_payload},
                                        op="row_exchange")]


def _json_safe(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, np.str_):
        return str(v)
    return v
