"""Multi-process cluster runtime: the feature table PARTITIONED by Morton
key range across process boundaries (ISSUE 15 / ROADMAP open item 1).

PR 7 made the fleet horizontal by REPLICATION — every node holds a full
copy, so the corpus is bounded by one host's HBM. This package adds the
missing axis: a jax.distributed runtime in which each process owns a
contiguous key-range shard of the sorted columnar table, assembled into
one global jax.Array with ``make_array_from_process_local_data`` +
``NamedSharding`` over a named ``rows`` axis (the SNIPPETS partitioner
pattern). Counts/density run as psum-reduced global kernels (every
process returns the exact global answer); selects stream per-process
local matches through a host-side ordered merge (rank order == key
order, so concatenation IS the global sort order).

Modules:
  runtime   jax.distributed bring-up (GEOMESA_TPU_CLUSTER_* knobs),
            mesh topology as first-class config (flat process-contiguous
            rows mesh / hybrid ICI x DCN), host exchange, federation
            auto-registration, /cluster state.
  table     ClusterShardedTable — global-array construction from
            process-local shards; cross-process ownership boundaries.
  exec      ClusterScan — psum'd count/density, ordered-merge select.
  build     cross-process splitter exchange: distributed partition of
            unsorted rows into per-process contiguous key ranges, so
            distributed index builds land sorted-by-construction.
  dryrun    spawned N-process CPU-backend dryrun + single-process
            oracle comparison (the CI acceptance surface and bench
            cfg12 engine).
  cells     shard cells (cluster v2): each Morton key-range shard as a
            replicated primary+follower group with its own fencing
            epoch — the ownership map the shard-aware router routes
            writes by, the per-cell admit matrix, graceful ownership
            handoff, and the node-local ingest ownership gate.
"""

from geomesa_tpu.cluster.runtime import (ClusterRuntime, runtime,
                                         cluster_active)
from geomesa_tpu.cluster.cells import (CellInfo, NotOwnedError,
                                       ShardCells)

__all__ = ["ClusterRuntime", "runtime", "cluster_active",
           "CellInfo", "NotOwnedError", "ShardCells"]
