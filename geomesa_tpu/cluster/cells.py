"""Shard cells: per-Morton-shard replicated primary+follower groups.

≙ the reference's tablet-server model: every tablet (contiguous key
range) is hosted by one server with write-ahead durability, and the
master moves ownership between servers under a fencing discipline.
Here the composition is explicit — `cluster/` contributes the
contiguous Morton key ranges (`ClusterLayout.key_ranges`) and
`replication/` contributes the WAL frame protocol, fencing epochs and
promote-by-highest-applied-seq — and this module is where they meet:

  ShardCells      the fleet-wide ownership map: shard id -> [key_lo,
                  key_hi] + member nodes, with O(log S) key routing
                  (`owner_of` / `route` / `route_points`). Ranges are
                  half-open on the NEXT shard's lo, so every int64 key
                  has exactly one owner (edge keys clamp to the edge
                  cells — growth never strands a write).
  CellFence       the per-cell fencing admit matrix composed over
                  `replication/fence.py`: a stale epoch from the SAME
                  cell is rejected and answered with a fence (split-
                  brain inside the cell stops here); a frame from a
                  DIFFERENT cell is rejected outright WITHOUT touching
                  the receiver's epoch — cross-cell traffic must never
                  fence a healthy owner.
  cell frames     `pack_cell_frame`/`unpack_cell_frame`: the (cell id,
                  epoch) envelope around a WAL frame that makes the
                  admit matrix checkable before the frame body is even
                  CRC-verified.
  hand_off        the graceful ownership handoff discipline: drain the
                  old owner, wait for the successor to reach the old
                  WAL head, then bump the successor's epoch so the old
                  owner is fenced BEFORE the successor accepts writes.
                  Epochs persist through `replication/fence.py`, so a
                  restart of either side cannot resurrect the old
                  owner.
  CELLS           the process-global registry: which cell (if any)
                  this node serves, surfaced on `/cells` and enforced
                  by the web ingest gate (`ensure_owned`).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.replication import fence as _fence

KEY_MIN = -(1 << 62)
KEY_MAX = (1 << 62) - 1


class NotOwnedError(ValueError):
    """A write's routing key falls outside the local cell's range."""

    def __init__(self, cell: str, key: int, owner: Optional[str]):
        super().__init__(
            f"key {key} is not owned by cell {cell}"
            + (f" (owner: {owner})" if owner else ""))
        self.cell = cell
        self.key = int(key)
        self.owner = owner


@dataclass
class CellInfo:
    """One shard cell: a contiguous key range + its member nodes."""

    shard: str
    key_lo: int
    key_hi: int
    members: List[str] = field(default_factory=list)

    def summary(self) -> dict:
        return {"shard": self.shard,
                "key_range": [int(self.key_lo), int(self.key_hi)],
                "members": list(self.members)}


class ShardCells:
    """The fleet ownership map: sorted, contiguous shard key ranges."""

    def __init__(self, cells: Sequence[CellInfo]):
        if not cells:
            raise ValueError("ShardCells needs at least one cell")
        self.cells: List[CellInfo] = sorted(cells,
                                            key=lambda c: int(c.key_lo))
        seen = set()
        for c in self.cells:
            if c.shard in seen:
                raise ValueError(f"duplicate shard id {c.shard!r}")
            seen.add(c.shard)
            if int(c.key_hi) < int(c.key_lo):
                raise ValueError(
                    f"cell {c.shard}: key_hi {c.key_hi} < key_lo "
                    f"{c.key_lo}")
        for a, b in zip(self.cells, self.cells[1:]):
            if int(b.key_lo) <= int(a.key_lo):
                raise ValueError(
                    f"cells {a.shard}/{b.shard} share key_lo "
                    f"{b.key_lo}")
        self._los = np.asarray([int(c.key_lo) for c in self.cells],
                               dtype=np.int64)

    def __len__(self) -> int:
        return len(self.cells)

    def cell(self, shard: str) -> CellInfo:
        for c in self.cells:
            if c.shard == shard:
                return c
        raise KeyError(f"no shard {shard!r}")

    def route(self, keys) -> np.ndarray:
        """Cell index per key. Half-open on the next cell's lo; keys
        below the first lo clamp to cell 0 (edge cells absorb growth at
        the boundaries, so every key has exactly one owner)."""
        keys = np.asarray(keys, dtype=np.int64)
        idx = np.searchsorted(self._los, keys, side="right") - 1
        return np.clip(idx, 0, len(self.cells) - 1)

    def owner_of(self, key: int) -> CellInfo:
        return self.cells[int(self.route([int(key)])[0])]

    def route_points(self, xs, ys,
                     bits: Optional[int] = None) -> np.ndarray:
        """Cell index per (lon, lat) point via the coarse Z2 routing
        key — the serving write path's geometry-only router (the table
        partition itself uses the exact z3-derived keys)."""
        return self.route(geo_key(xs, ys, bits=bits))

    def summary(self) -> dict:
        return {"shards": [c.summary() for c in self.cells]}

    @classmethod
    def from_key_ranges(cls, key_ranges: Sequence[Sequence[int]],
                        members: Optional[Dict[str, List[str]]] = None
                        ) -> "ShardCells":
        """Build from `ClusterLayout.key_ranges` order: shard i is the
        i-th contiguous range (the dryrun/table side of the map)."""
        members = members or {}
        return cls([CellInfo(str(i), int(lo), int(hi),
                             members.get(str(i), []))
                    for i, (lo, hi) in enumerate(key_ranges)])

    @classmethod
    def from_specs(cls, specs: Sequence[str]) -> "ShardCells":
        """Parse CLI cell specs ``SHARD=LO:HI[=MEMBER[,MEMBER...]]``
        (members name router endpoints, first member = seed primary)."""
        cells = []
        for spec in specs:
            parts = spec.split("=")
            if len(parts) not in (2, 3) or ":" not in parts[1]:
                raise ValueError(
                    f"bad shard spec {spec!r} "
                    "(want SHARD=LO:HI[=MEMBER,...])")
            lo, hi = parts[1].split(":", 1)
            mem = [m for m in parts[2].split(",") if m] \
                if len(parts) == 3 else []
            cells.append(CellInfo(parts[0], int(lo), int(hi), mem))
        return cls(cells)


def geo_key(xs, ys, bits: Optional[int] = None) -> np.ndarray:
    """Vectorized coarse Z2 routing key: interleave ``bits`` lon/lat
    grid bits (lon major, same orientation as obs/sketches.cell_key) —
    deterministic, monotone-in-space, and computable anywhere a
    feature's coordinates are known (a router has no store)."""
    if bits is None:
        bits = int(config.CELL_GEO_KEY_BITS.get())
    bits = max(1, min(16, int(bits)))
    n = 1 << bits
    xs = np.asarray(xs, dtype=np.float64)
    ys = np.asarray(ys, dtype=np.float64)
    gx = np.clip(((xs + 180.0) / 360.0 * n).astype(np.int64), 0, n - 1)
    gy = np.clip(((ys + 90.0) / 180.0 * n).astype(np.int64), 0, n - 1)
    z = np.zeros(gx.shape, dtype=np.int64)
    for b in range(bits):
        z |= ((gx >> b) & 1) << (2 * b + 1)
        z |= ((gy >> b) & 1) << (2 * b)
    return z


# -- the per-cell fencing admit matrix ----------------------------------------


ADMIT_OK = "ok"
ADMIT_ADOPT = "adopt"
REJECT_STALE = "reject_stale"          # same cell, lower epoch: fence it
REJECT_FOREIGN = "reject_foreign"      # different cell: drop, no fencing


class CellFence:
    """Fencing epochs scoped to ONE cell, persisted in the cell
    directory via replication/fence.py (so a handoff or restart can
    never resurrect a lower epoch).

    The admit matrix is the split-brain contract:

      same cell, epoch == mine   -> ok
      same cell, epoch >  mine   -> adopt (durably witness the higher
                                   epoch, then ok)
      same cell, epoch <  mine   -> reject_stale: refused AND answered
                                   with the higher epoch (the sender
                                   lost primaryship of THIS cell)
      different cell, any epoch  -> reject_foreign: refused WITHOUT
                                   touching the receiver's epoch — a
                                   stale frame leaking across cells
                                   must never fence a healthy owner.
    """

    def __init__(self, cell: str, directory: str):
        self.cell = str(cell)
        self.dir = str(directory)
        self.epoch = _fence.load_epoch(self.dir)
        self.stale_rejects = 0
        self.foreign_rejects = 0

    def bump(self, at_least: int = 0) -> int:
        self.epoch = _fence.bump_epoch(self.dir, at_least=max(
            int(at_least), self.epoch))
        return self.epoch

    def admit(self, cell: str, epoch: int) -> str:
        """Classify one (cell, epoch) envelope; adopts/refuses as the
        matrix says and returns the verdict string."""
        epoch = int(epoch)
        if str(cell) != self.cell:
            self.foreign_rejects += 1
            _metrics.inc("cells.foreign_frame_rejects")
            return REJECT_FOREIGN
        if epoch < self.epoch:
            self.stale_rejects += 1
            _metrics.inc("cells.stale_frame_rejects")
            return REJECT_STALE
        if epoch > self.epoch:
            self.epoch = _fence.save_epoch(self.dir, epoch)
            return ADMIT_ADOPT
        return ADMIT_OK

    def stats(self) -> dict:
        return {"cell": self.cell, "epoch": self.epoch,
                "stale_rejects": self.stale_rejects,
                "foreign_rejects": self.foreign_rejects}


# -- cell frame envelope ------------------------------------------------------

_CF_MAGIC = b"GMCF"


def pack_cell_frame(cell: str, epoch: int, frame: bytes) -> bytes:
    """Wrap one WAL frame in the (cell, epoch) envelope the admit
    matrix classifies — checked BEFORE the frame body is CRC-verified,
    so a foreign or stale frame costs one header parse, not an apply."""
    cid = str(cell).encode("utf-8")
    return (_CF_MAGIC + struct.pack(">HQ", len(cid), int(epoch))
            + cid + frame)


def unpack_cell_frame(data: bytes):
    """-> (cell, epoch, frame). Raises ValueError on a malformed
    envelope (same fail-loudly discipline as WAL frame CRC)."""
    if len(data) < 14 or data[:4] != _CF_MAGIC:
        raise ValueError("bad cell frame envelope (magic)")
    clen, epoch = struct.unpack(">HQ", data[4:14])
    if len(data) < 14 + clen:
        raise ValueError("bad cell frame envelope (truncated cell id)")
    cell = data[14:14 + clen].decode("utf-8")
    return cell, int(epoch), data[14 + clen:]


# -- ownership handoff --------------------------------------------------------


def hand_off(old, new, wait_s: Optional[float] = None,
             clock=None) -> dict:
    """Graceful ownership handoff inside one cell: drain the OLD owner,
    wait for the NEW owner to prove it reached the old WAL head, then
    fence the old owner under the bumped epoch BEFORE the new owner
    accepts writes — acked writes either land on the old owner (and are
    replicated) or are refused; none straddle the swap.

    ``old``/``new`` duck-type the router Endpoint surface: ``drain()``,
    ``probe()`` (applied_seq / last epoch), ``fence(epoch)`` on old,
    ``promote(port)`` on new. Returns the handoff report (durations +
    the fencing epoch)."""
    import time as _time
    clock = clock or _time.monotonic
    wait_s = float(wait_s if wait_s is not None
                   else config.CELL_HANDOFF_DRAIN_S.get())
    t0 = clock()
    try:
        old.drain()
    except Exception:
        pass  # an unreachable old owner is already not accepting writes
    old.last_probe_ts = 0.0
    op = old.probe() or {}
    head = int(op.get("applied_seq") or 0)
    old_epoch = int(op.get("epoch") or 0)
    deadline = t0 + wait_s
    caught_up = False
    while clock() < deadline:
        new.last_probe_ts = 0.0
        np_ = new.probe() or {}
        if int(np_.get("applied_seq") or 0) >= head:
            caught_up = True
            break
        _time.sleep(0.02)
    # fence FIRST: after this point the old owner refuses writes even
    # if the promote below fails — fail closed, never two owners
    epoch = old_epoch + 1
    try:
        old.fence(epoch)
    except Exception:
        pass  # dead old owner: the epoch bump below still wins
    result = new.promote(port=0)
    return {"caught_up": caught_up,
            "head_seq": head,
            "epoch": int(result.get("epoch") or epoch),
            "duration_ms": round((clock() - t0) * 1000.0, 1),
            "promoted": result}


# -- process-global cell registry ---------------------------------------------


class CellRegistry:
    """Which cell THIS node serves (one per process, like the
    Federator): the web `/cells` surface and the ingest ownership
    gate read it; the CLI `--cell` flag writes it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.topology: Optional[ShardCells] = None
        self.local: Optional[CellInfo] = None
        self.fence: Optional[CellFence] = None
        self.gate_refusals = 0
        self.gate_rows = 0

    def configure(self, topology: Optional[ShardCells] = None,
                  local: Optional[CellInfo] = None,
                  directory: Optional[str] = None) -> None:
        with self._lock:
            self.topology = topology
            self.local = local
            self.fence = (CellFence(local.shard, directory)
                          if local is not None and directory else None)

    def active(self) -> bool:
        return self.local is not None

    def ensure_owned(self, xs, ys) -> int:
        """The ingest ownership gate: every row's routing key must fall
        in the local cell's range. Raises NotOwnedError naming the
        owning shard (when the topology knows it); CELL_ENFORCE=0
        counts but accepts."""
        with self._lock:
            local, topo = self.local, self.topology
        if local is None:
            return 0
        keys = geo_key(xs, ys)
        self.gate_rows += int(len(keys))
        bad = (keys < int(local.key_lo)) | (keys > int(local.key_hi))
        n_bad = int(bad.sum())
        if n_bad == 0:
            return 0
        self.gate_refusals += n_bad
        _metrics.inc("cells.gate_refusals", n_bad)
        if not config.CELL_ENFORCE.get():
            return n_bad
        k = int(keys[np.flatnonzero(bad)[0]])
        owner = None
        if topo is not None:
            try:
                owner = topo.owner_of(k).shard
            except Exception:
                owner = None
        raise NotOwnedError(local.shard, k, owner)

    def state(self) -> dict:
        """The `/cells` payload."""
        with self._lock:
            local, topo, fence = self.local, self.topology, self.fence
        return {
            "active": local is not None,
            "local": local.summary() if local else None,
            "fence": fence.stats() if fence else None,
            "topology": topo.summary() if topo else None,
            "gate": {"rows": self.gate_rows,
                     "refusals": self.gate_refusals,
                     "enforce": bool(config.CELL_ENFORCE.get())},
        }


CELLS = CellRegistry()


def _reset_for_tests() -> None:
    global CELLS
    CELLS = CellRegistry()
