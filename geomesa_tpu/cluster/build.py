"""Cross-process partition: distributed builds land sorted-by-construction.

The PR-13 mesh-sharded sort (parallel/dist.py mesh_sort_perm) runs its
splitter exchange across LOCAL devices. This module extends exactly that
discipline across PROCESS boundaries, on the host side:

  1. local stable sort of the Morton keys (global-row-id tie-break, the
     same iota discipline as every sort path in the repo);
  2. sample exchange — each process contributes k evenly-spaced sorted
     samples, every process deterministically derives the SAME
     num_processes-1 global splitters from the merged sample set;
  3. partition by KEY ONLY with the strictly-less-than boundary rule
     (rows equal to a splitter all land in the splitter's right
     partition on every process — no key ever straddles an ownership
     boundary, ties ordered by the row-id plane);
  4. row exchange (allgather of the sorted columns + everyone slices
     out its own partition from each source) and a final local stable
     merge — each process ends holding one contiguous key range,
     sorted, which is precisely the ClusterShardedTable input contract.

Result: concatenating per-process shards in rank order is bitwise
identical to a single-process stable sort of the full corpus — the
distributed index build needs no post-hoc global sort.

Payload columns are exchanged as raw bytes (dtype-preserving), strings
as fixed-width byte matrices, so float columns roundtrip exactly.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.cluster.runtime import ClusterRuntime, note_collective


def _allgather_u8(rt: ClusterRuntime, arr: np.ndarray,
                  rows: List[int]) -> List[np.ndarray]:
    """All-gather a per-process (n_p, w) uint8 matrix; ``rows`` is every
    process's row count (already exchanged). Returns one matrix per
    process, unpadded. This is the bulk row-payload mover of the
    partition build — timed as ``cluster.collective.row_exchange`` with
    the padded wire size as its payload-bytes gauge."""
    import time as _time

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    t0 = _time.perf_counter()
    cap = max(1, max(rows))
    w = arr.shape[1] if arr.ndim == 2 else 1
    buf = np.zeros((cap, w), dtype=np.uint8)
    if len(arr):
        buf[:len(arr)] = arr.reshape(len(arr), w)
    out = np.asarray(multihost_utils.process_allgather(
        jnp.asarray(buf))).reshape(rt.num_processes, cap, w)
    note_collective("row_exchange", _time.perf_counter() - t0,
                    payload_bytes=cap * w * rt.num_processes)
    return [out[p, :rows[p]] for p in range(rt.num_processes)]


def _cols_to_u8(cols: Dict[str, np.ndarray]) -> Tuple[Dict[str, np.ndarray],
                                                      Dict[str, dict]]:
    """Encode 1-D columns into (n, itemsize) uint8 matrices + the specs
    to decode them (numeric: raw bytes; strings: fixed-width utf-8)."""
    enc, spec = {}, {}
    for name, arr in cols.items():
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            raw = [s.encode("utf-8") if isinstance(s, str)
                   else bytes(s) for s in arr.tolist()]
            w = max([len(r) for r in raw], default=0) + 1
            m = np.zeros((len(raw), w), dtype=np.uint8)
            for i, r in enumerate(raw):
                m[i, :len(r)] = np.frombuffer(r, dtype=np.uint8)
            enc[name] = m
            spec[name] = {"kind": "str", "width": w}
        else:
            m = np.frombuffer(arr.tobytes(), dtype=np.uint8)
            enc[name] = m.reshape(len(arr), arr.dtype.itemsize) \
                if len(arr) else m.reshape(0, arr.dtype.itemsize)
            spec[name] = {"kind": "num", "dtype": arr.dtype.str}
    return enc, spec


def _u8_to_col(mat: np.ndarray, sp: dict) -> np.ndarray:
    if sp["kind"] == "str":
        return np.asarray([bytes(r).rstrip(b"\x00").decode("utf-8")
                           for r in mat], dtype=object)
    return np.frombuffer(np.ascontiguousarray(mat).tobytes(),
                         dtype=np.dtype(sp["dtype"]))


def cluster_partition(rt: ClusterRuntime, keys: np.ndarray,
                      payload: Dict[str, np.ndarray],
                      gids: np.ndarray = None,
                      stages: dict = None):
    """Repartition (keys, payload) so each process holds one contiguous,
    locally-sorted Morton key range.

    Collective: every process calls with its own unsorted rows. ``gids``
    is the optional global tie-break id per row (the ORIGINAL corpus row
    id when rows were dealt out round-robin) — with it, rows with equal
    keys land in their original global order, so a downstream index
    build's local-row tie-break reproduces the single-process sort
    bitwise. Returns ``(keys_local, payload_local, (key_lo, key_hi),
    stages)`` — the sorted local shard, its ownership bounds, and phase
    timings."""
    import time as _time

    if stages is None:
        stages = {}
    keys = np.asarray(keys, dtype=np.int64)
    n_local = len(keys)
    if not rt.active():
        gid = np.arange(n_local, dtype=np.int64) if gids is None \
            else np.asarray(gids, dtype=np.int64)
        order = np.lexsort((gid, keys))
        keys = keys[order]
        payload = {k: np.asarray(v)[order] for k, v in payload.items()}
        lo = int(keys[0]) if n_local else 0
        hi = int(keys[-1]) if n_local else -1
        return keys, payload, (lo, hi), stages

    # phase 1: local stable sort with a global-row-id tie-break plane
    t0 = _time.perf_counter()
    counts = [p["n"] for p in rt.exchange({"n": n_local})]
    start = int(sum(counts[:rt.process_id]))
    gid = np.arange(start, start + n_local, dtype=np.int64) \
        if gids is None else np.asarray(gids, dtype=np.int64)
    order = np.lexsort((gid, keys))
    keys_s = keys[order]
    gid_s = gid[order]
    payload_s = {k: np.asarray(v)[order] for k, v in payload.items()}
    stages["partition_local_sort_s"] = round(_time.perf_counter() - t0, 3)

    # phase 2: sample exchange -> global splitters (deterministic on
    # every process: same merged samples, same quantile picks)
    t0 = _time.perf_counter()
    k_samples = max(2, config.SHARD_SORT_SAMPLES.get())
    if n_local:
        pos = np.unique(np.linspace(0, n_local - 1,
                                    num=min(k_samples, n_local))
                        .astype(np.int64))
        mine = [int(keys_s[i]) for i in pos]
    else:
        mine = []
    sample_sets = [p["s"] for p in rt.exchange({"s": mine})]
    samples = np.sort(np.asarray(
        [s for ss in sample_sets for s in ss], dtype=np.int64))
    total = len(samples)
    nproc = rt.num_processes
    splitters = np.asarray(
        [samples[(total * j) // nproc] for j in range(1, nproc)],
        dtype=np.int64) if total else np.zeros(nproc - 1, dtype=np.int64)
    # strictly-less-than boundaries: rows with key < splitter[j] belong
    # left of boundary j; equal keys all fall right (never straddle)
    bounds = [0] + [int(c) for c in
                    np.searchsorted(keys_s, splitters, side="left")] \
        + [n_local]
    stages["partition_splitters_s"] = round(_time.perf_counter() - t0, 3)

    # phase 3: row exchange — allgather sorted columns, every process
    # slices its own partition out of each source's bounds
    t0 = _time.perf_counter()
    all_bounds = [p["b"] for p in rt.exchange({"b": bounds})]
    enc, spec = _cols_to_u8({"__key__": keys_s, "__gid__": gid_s,
                             **payload_s})
    gathered = {name: _allgather_u8(rt, mat, counts)
                for name, mat in enc.items()}
    me = rt.process_id
    pieces = {name: [] for name in enc}
    for src in range(nproc):
        b0, b1 = all_bounds[src][me], all_bounds[src][me + 1]
        if b1 <= b0:
            continue
        for name in enc:
            pieces[name].append(gathered[name][src][b0:b1])
    moved = int(sum(len(p) for p in pieces["__key__"]))
    cols = {}
    for name in enc:
        if pieces[name]:
            mat = np.concatenate(pieces[name])
        else:
            mat = np.zeros((0, enc[name].shape[1]), dtype=np.uint8)
        cols[name] = _u8_to_col(mat, spec[name])
    stages["partition_exchange_s"] = round(_time.perf_counter() - t0, 3)

    # phase 4: final local stable merge (sources were sorted runs;
    # row-id plane keeps ties in original order)
    t0 = _time.perf_counter()
    keys_f = cols.pop("__key__")
    gid_f = cols.pop("__gid__")
    order = np.lexsort((gid_f, keys_f))
    keys_f = keys_f[order]
    out_payload = {k: v[order] for k, v in cols.items()}
    stages["partition_merge_s"] = round(_time.perf_counter() - t0, 3)
    stages["partition_rows"] = moved
    lo = int(keys_f[0]) if len(keys_f) else 0
    hi = int(keys_f[-1]) if len(keys_f) else -1
    return keys_f, out_payload, (lo, hi), stages
