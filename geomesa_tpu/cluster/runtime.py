"""Cluster bring-up + topology: the one home for jax.distributed state.

Bring-up order matters and is easy to get wrong, so it lives here once:

  1. On the CPU backend, cross-process collectives need the gloo
     implementation selected BEFORE ``jax.distributed.initialize`` —
     without it every multi-process jit fails with "Multiprocess
     computations aren't implemented on the CPU backend".
  2. ``jax.distributed.initialize(coordinator, num_processes, process_id)``
     with a bounded rendezvous timeout (a missing peer fails the
     bring-up instead of hanging the fleet).
  3. The mesh device order is ``sorted(devices, key=(process_index, id))``
     so process p's devices form one contiguous block of the ``rows``
     axis — process p owns rows [p*per_proc, (p+1)*per_proc) under
     ``NamedSharding(P("rows"))``, which is what makes rank order ==
     key order for the ordered select merge.

Topology is first-class config (GEOMESA_TPU_CLUSTER_TOPOLOGY):
``flat`` is one process-contiguous ``rows`` axis (CPU dryruns, single
slice); ``hybrid`` builds ``create_hybrid_device_mesh`` with a ``dcn``
axis across slices and ICI-contiguous ``rows`` within one; ``auto``
picks hybrid iff >1 slice is detected. ``hybrid`` without multiple
slices raises — a misconfigured mesh fails loudly (same discipline as
the create_mesh fix in parallel/mesh.py).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu import config


def note_collective(op: str, seconds: float,
                    payload_bytes: int = 0) -> None:
    """Record one collective round: a ``cluster.collective.<op>`` timer
    (a leaf span under an active trace, a registry histogram otherwise)
    plus a payload-bytes counter. Never raises into the collective."""
    try:
        from geomesa_tpu import trace as _trace
        _trace.record(f"cluster.collective.{op}", "collective", seconds)
        if payload_bytes:
            from geomesa_tpu.metrics import REGISTRY
            REGISTRY.inc(f"cluster.collective.{op}.bytes",
                         int(payload_bytes))
    except Exception:
        pass


class ClusterConfigError(ValueError):
    """A cluster knob combination that cannot work (fail loudly)."""


def _slice_index(dev) -> int:
    return int(getattr(dev, "slice_index", 0) or 0)


@dataclass
class ClusterRuntime:
    """Process-global cluster state (one per process, like the Federator)."""

    coordinator: str = ""
    num_processes: int = 1
    process_id: int = 0
    topology: str = "auto"
    initialized: bool = False
    psum_rounds: int = 0
    # type_name -> {"proc_rows": [...], "key_ranges": [...], ...}
    tables: Dict[str, dict] = field(default_factory=dict)
    _mesh_cache: Dict[str, object] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    # -- bring-up -------------------------------------------------------------

    def initialize(self) -> "ClusterRuntime":
        """Join the cluster (idempotent). Inactive (num_processes == 1,
        no coordinator) is a successful no-op: every cluster code path
        degrades to the single-process behavior."""
        import jax

        if self.initialized:
            return self
        self.coordinator = config.CLUSTER_COORDINATOR.get().strip()
        self.num_processes = max(1, config.CLUSTER_NUM_PROCESSES.get())
        self.process_id = config.CLUSTER_PROCESS_ID.get()
        self.topology = config.CLUSTER_TOPOLOGY.get().strip().lower()
        if self.topology not in ("auto", "flat", "hybrid"):
            raise ClusterConfigError(
                f"GEOMESA_TPU_CLUSTER_TOPOLOGY={self.topology!r} "
                "(want auto|flat|hybrid)")
        if self.num_processes <= 1 or not self.coordinator:
            if self.num_processes > 1 and not self.coordinator:
                raise ClusterConfigError(
                    "GEOMESA_TPU_CLUSTER_NUM_PROCESSES > 1 needs "
                    "GEOMESA_TPU_CLUSTER_COORDINATOR")
            self.initialized = True
            return self
        if not (0 <= self.process_id < self.num_processes):
            raise ClusterConfigError(
                f"process_id {self.process_id} outside "
                f"[0, {self.num_processes})")
        # CPU collectives: gloo must be selected before initialize (the
        # default CPU backend rejects multi-process programs outright).
        # Backend must NOT be initialized yet, so sniff the platform from
        # config/env instead of jax.default_backend().
        import os
        plats = (os.environ.get("JAX_PLATFORMS")
                 or getattr(jax.config, "jax_platforms", None) or "")
        if str(plats).split(",")[0].strip().lower() == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass  # older/newer jax without the knob: initialize decides
        kwargs = {"coordinator_address": self.coordinator,
                  "num_processes": self.num_processes,
                  "process_id": self.process_id}
        n_local = config.CLUSTER_LOCAL_DEVICES.get()
        if n_local and n_local > 0:
            kwargs["local_device_ids"] = list(range(n_local))
        try:
            jax.distributed.initialize(
                initialization_timeout=int(
                    config.CLUSTER_INIT_TIMEOUT_S.get()),
                **kwargs)
        except TypeError:
            # older jax without initialization_timeout
            jax.distributed.initialize(**kwargs)
        self.initialized = True
        return self

    def active(self) -> bool:
        return self.initialized and self.num_processes > 1

    # -- topology -------------------------------------------------------------

    def devices(self) -> List:
        """Global device list in process-contiguous order: sorted by
        (process_index, id) so each process's devices are one block."""
        import jax
        return sorted(jax.devices(),
                      key=lambda d: (d.process_index, d.id))

    def local_device_count(self) -> int:
        import jax
        return jax.local_device_count()

    def mesh(self, axis: str = "rows"):
        """The cluster mesh for ``axis``. Flat: one named axis over the
        process-contiguous device order. Hybrid (multi-slice): ``dcn``
        across slices x ``axis`` ICI-contiguous within a slice."""
        key = axis
        with self._lock:
            if key in self._mesh_cache:
                return self._mesh_cache[key]
        from jax.sharding import Mesh
        devs = self.devices()
        slices = sorted({_slice_index(d) for d in devs})
        want_hybrid = (self.topology == "hybrid"
                       or (self.topology == "auto" and len(slices) > 1))
        if self.topology == "hybrid" and len(slices) <= 1:
            raise ClusterConfigError(
                "topology=hybrid needs >1 slice "
                f"(detected {len(slices)}); use flat/auto")
        if want_hybrid and len(slices) > 1:
            from jax.experimental.mesh_utils import \
                create_hybrid_device_mesh
            per_slice = len(devs) // len(slices)
            mesh_devs = create_hybrid_device_mesh(
                (per_slice,), (len(slices),), devices=devs)
            m = Mesh(mesh_devs, ("dcn", axis))
        else:
            m = Mesh(np.array(devs), (axis,))
        with self._lock:
            self._mesh_cache[key] = m
        return m

    def data_spec_axes(self, axis: str = "rows"):
        """Axis name(s) the row dimension shards over in ``mesh(axis)``:
        a hybrid mesh shards rows over BOTH dcn and ici axes so shard
        order stays process-contiguous."""
        m = self.mesh(axis)
        return tuple(m.axis_names) if len(m.axis_names) > 1 else axis

    # -- host-side exchange ---------------------------------------------------

    def exchange(self, payload: dict, op: str = "allgather") -> List[dict]:
        """All-gather one small JSON payload per process (rank order).
        Inactive clusters return ``[payload]`` — callers never branch.
        Each active round records a ``cluster.collective.<op>`` timer
        with total payload bytes, and (shardwatch on) one extra tiny
        gather of per-rank round timings for straggler attribution."""
        if not self.active():
            return [payload]
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        t0 = time.perf_counter()
        raw = json.dumps(payload, sort_keys=True).encode("utf-8")
        n = np.asarray([len(raw)], dtype=np.int32)
        lens = np.asarray(multihost_utils.process_allgather(
            jnp.asarray(n))).reshape(self.num_processes)
        cap = int(lens.max())
        buf = np.zeros(cap, dtype=np.uint8)
        buf[:len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        blobs = np.asarray(multihost_utils.process_allgather(
            jnp.asarray(buf))).reshape(self.num_processes, cap)
        dt = time.perf_counter() - t0
        note_collective(op, dt, payload_bytes=int(lens.sum()))
        if config.SHARDWATCH_ENABLED.get():
            # symmetric on every rank (same env across the cluster):
            # gather each rank's round wall time; the LAST arriver made
            # everyone else wait, so it measured the SHORTEST round —
            # the slowest rank is the argmin
            durs = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(np.asarray([dt * 1000.0],
                                       dtype=np.float32)))
            ).reshape(self.num_processes)
            self._note_straggler(op, [float(d) for d in durs])
        return [json.loads(bytes(blobs[p, :int(lens[p])]).decode("utf-8"))
                for p in range(self.num_processes)]

    def _note_straggler(self, op: str, durs_ms: List[float]) -> None:
        """Per-round straggler attribution: name the slowest rank, count
        over-bar rounds against it (the doctor's collective_straggler
        feed), and flight-record the round with cluster dims."""
        try:
            from geomesa_tpu.metrics import REGISTRY
            spread = max(durs_ms) - min(durs_ms)
            slowest = int(min(range(len(durs_ms)),
                              key=lambda p: (durs_ms[p], p)))
            REGISTRY.inc("cluster.collective.rounds")
            if spread < float(config.DOCTOR_STRAGGLER_MS.get()):
                return
            REGISTRY.inc(f"cluster.collective.straggler.rank{slowest}")
            REGISTRY.observe("cluster.collective.straggler_spread",
                             spread / 1000.0)
            from geomesa_tpu.obs import flight as _flight
            _flight.RECORDER.record({
                "ts_ms": int(time.time() * 1000), "kind": "collective",
                "type": op, "duration_ms": round(spread, 3),
                "slowest_rank": slowest,
                "round_ms": [round(d, 3) for d in durs_ms],
                **event_dims()})
        except Exception:
            pass

    def barrier(self, name: str = "cluster") -> None:
        if not self.active():
            return
        from jax.experimental import multihost_utils
        t0 = time.perf_counter()
        multihost_utils.sync_global_devices(name)
        note_collective("barrier", time.perf_counter() - t0)

    # -- integration hooks ----------------------------------------------------

    def note_psum_round(self, n: int = 1) -> None:
        """Count one psum-reduced global dispatch (the /cluster and
        debug-cluster 'psum round' surface + a fleet metric)."""
        with self._lock:
            self.psum_rounds += n
        try:
            from geomesa_tpu.metrics import REGISTRY
            REGISTRY.inc("cluster.psum_rounds", n)
        except Exception:
            pass

    def register_table(self, type_name: str, summary: dict) -> None:
        with self._lock:
            self.tables[type_name] = summary

    def register_web(self, port: int, host: str = "127.0.0.1") -> Optional[dict]:
        """Exchange this process's web address across the cluster and
        install a Federator over ALL of them on every rank — cluster
        nodes auto-register in /fleet with no manual --addr lists."""
        if not config.CLUSTER_WEB_REGISTER.get():
            return None
        from geomesa_tpu import trace as _trace
        from geomesa_tpu.obs import federation
        me = {"proc": self.process_id, "addr": f"{host}:{port}",
              "node_id": _trace.node_id()}
        peers = self.exchange(me)
        nodes = {p.get("node_id") or f"proc{p['proc']}": p["addr"]
                 for p in peers}
        federation.configure(nodes)
        return nodes

    # -- state surfaces -------------------------------------------------------

    def state(self) -> dict:
        """The /cluster + ``debug cluster`` payload."""
        import jax
        out = {
            "active": self.active(),
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "coordinator": self.coordinator or None,
            "topology": self.topology,
            "psum_rounds": self.psum_rounds,
            "tables": dict(self.tables),
        }
        if self.initialized:
            try:
                devs = self.devices()
                slices = sorted({_slice_index(d) for d in devs})
                m = self.mesh()
                out["mesh"] = {
                    "axes": {k: int(v)
                             for k, v in zip(m.axis_names,
                                             m.devices.shape)},
                    "devices": len(devs),
                    "local_devices": jax.local_device_count(),
                    "slices": len(slices),
                    "ici_shape": [len(devs) // max(1, len(slices))],
                    "dcn_shape": [len(slices)],
                    "backend": jax.default_backend(),
                }
            except Exception as e:  # noqa: BLE001 - state must not raise
                out["mesh"] = {"error": str(e)}
        return out


_RUNTIME: Optional[ClusterRuntime] = None
_RT_LOCK = threading.Lock()


def runtime(init: bool = True) -> ClusterRuntime:
    """The process-global runtime; ``init=True`` joins the cluster on
    first use when the knobs say so."""
    global _RUNTIME
    with _RT_LOCK:
        if _RUNTIME is None:
            _RUNTIME = ClusterRuntime()
    if init and not _RUNTIME.initialized and _enabled():
        _RUNTIME.initialize()
    return _RUNTIME


def _enabled() -> bool:
    return bool(config.CLUSTER.get()
                or config.CLUSTER_COORDINATOR.get().strip())


def cluster_active() -> bool:
    """True iff this process is part of an initialized >1-process
    cluster. Cheap and safe to call from hot paths (no bring-up side
    effects unless the knobs ask for it)."""
    if _RUNTIME is not None:
        return _RUNTIME.active()
    if not _enabled():
        return False
    return runtime().active()


def event_dims() -> dict:
    """``process``/``shard`` dims for flight events and traces (empty
    outside a cluster, so single-process event shapes are unchanged)."""
    if _RUNTIME is None or not _RUNTIME.active():
        return {}
    return {"process": _RUNTIME.process_id,
            "shard": f"{_RUNTIME.process_id}/{_RUNTIME.num_processes}"}


def _reset_for_tests() -> None:
    global _RUNTIME
    with _RT_LOCK:
        _RUNTIME = None
