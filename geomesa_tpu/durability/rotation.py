"""Shared file rotation / fsync / atomic-install policy.

One tested home for the three disciplines every durable file in the system
uses (≙ the reference's WAL + RFile commit discipline — Accumulo WALs fsync
group-committed batches, and both stores install immutable files via
tmp+rename):

  rotate(path, keep)       keep-N numbered rotation (``path`` → ``path.1`` →
                           ``path.2`` …), the AuditWriter JSONL policy and the
                           WAL's bounded-history slot
  atomic_install(tmp, dst) tmp+rename installation with parent-dir fsync —
                           a reader never observes a half-written file/dir
  fsync_file(fh)           flush + fsync with fault-injection hooks
                           (durability/faults.py) threaded through

Everything here is host-side posix file plumbing; no jax."""

from __future__ import annotations

import os
from typing import Callable, List, Optional

from geomesa_tpu.durability import faults


def fsync_file(fh) -> None:
    """flush + os.fsync, honouring injected fsync failures (faults.py).
    Raises OSError when an injected (or real) fsync error fires — callers
    decide whether that fails the write (WAL ``always``) or is retried
    (WAL ``batch`` background syncer)."""
    fh.flush()
    faults.fsync_gate()
    os.fsync(fh.fileno())


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (posix requires
    the parent-dir fsync for the rename itself to survive power loss)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without dir-fd fsync: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_install(tmp_path: str, final_path: str) -> None:
    """Atomically install ``tmp_path`` at ``final_path`` (file or directory)
    via rename, then fsync the parent so the rename is durable. The unit of
    crash-atomicity for snapshots: a crash leaves either the old state or
    the complete new one, never a torn install."""
    faults.crash_point("snapshot.written")
    os.replace(tmp_path, final_path)
    fsync_dir(os.path.dirname(final_path) or ".")
    faults.crash_point("snapshot.installed")


def rotate(path: str, keep: int = 1,
           on_drop: Optional[Callable[[str], None]] = None) -> None:
    """Numbered keep-N rotation: ``path`` becomes ``path.1``, shifting
    ``path.k`` → ``path.k+1`` up to ``keep``; the former ``path.keep`` is
    dropped (``on_drop(dropped_path)`` runs first — the hook AuditWriter
    uses to account discarded events). Each step is an atomic os.replace."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    oldest = f"{path}.{keep}"
    if os.path.exists(oldest) and on_drop is not None:
        on_drop(oldest)
    for k in range(keep, 1, -1):
        src = f"{path}.{k - 1}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{k}")
    os.replace(path, f"{path}.1")


def keep_newest(paths: List[str], keep: int,
                on_drop: Optional[Callable[[str], None]] = None) -> List[str]:
    """Delete all but the ``keep`` newest entries of ``paths`` (assumed
    sorted oldest→newest; files or directories). Returns the dropped paths.
    The snapshot-GC and WAL-segment-GC share this so 'how many old
    generations survive' has one tested definition."""
    import shutil
    dropped = []
    excess = paths[:-keep] if keep > 0 else list(paths)
    for p in excess:
        if on_drop is not None:
            on_drop(p)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        else:
            try:
                os.remove(p)
            except OSError:
                continue
        dropped.append(p)
    return dropped
