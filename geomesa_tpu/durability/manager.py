"""DurabilityManager: the store-side owner of WAL + snapshot lifecycle.

One manager per durable TpuDataStore. Mutators (holding the store lock) log
their record BEFORE applying in memory (log-then-apply); after the public
mutator releases the lock it calls ``maybe_snapshot()``, which writes an
incremental snapshot once enough rows/bytes accumulated since the last one,
rotates the WAL, and garbage-collects fully-covered segments.

The ``replaying`` flag suppresses logging and snapshot triggers while
recovery replays records through the same mutation paths.

Layout under the durability directory::

    <dir>/wal/wal-<first_seq>.log     append-only CRC-framed segments
    <dir>/snapshot-<wal_seq>/         installed snapshots (catalog + npz)
    <dir>/.tmp-snapshot-*             in-flight snapshot writes (crash junk)
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from geomesa_tpu.durability.wal import WriteAheadLog


def attach(store, path: str, params: Optional[dict] = None) -> None:
    """Wire durability onto a fresh store: recover from an existing layout
    when one is present, then start logging. Called from
    ``TpuDataStore.__init__`` for ``params={"durability": path}`` /
    ``TpuDataStore.open(path)``."""
    from geomesa_tpu.durability import recovery as _recovery
    from geomesa_tpu.durability import snapshot as _snap
    from geomesa_tpu.durability import wal as _wal

    params = params or {}
    report = None
    has_layout = bool(_snap.snapshot_dirs(path)) or \
        bool(_wal.segments(os.path.join(path, "wal")))
    if has_layout:
        report = _recovery.recover_into(store, path)
    start_seq = (report.last_seq + 1) if report else 1
    store.durability = DurabilityManager(
        store, path,
        fsync=params.get("wal.fsync"),
        segment_bytes=params.get("wal.segment_bytes"),
        interval_ms=params.get("wal.interval_ms"),
        snapshot_rows=params.get("snapshot.rows"),
        snapshot_wal_bytes=params.get("snapshot.wal_bytes"),
        start_seq=start_seq,
        snapshot_seq=report.snapshot_seq if report else 0)
    store.recovery_report = report


class DurabilityManager:

    def __init__(self, store, path: str, fsync: Optional[str] = None,
                 segment_bytes: Optional[int] = None,
                 interval_ms: Optional[float] = None,
                 snapshot_rows: Optional[int] = None,
                 snapshot_wal_bytes: Optional[int] = None,
                 start_seq: int = 1, snapshot_seq: int = 0):
        from geomesa_tpu import config
        from geomesa_tpu.metrics import REGISTRY as _metrics
        self.store = store
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.wal = WriteAheadLog(os.path.join(path, "wal"), fsync=fsync,
                                 segment_bytes=segment_bytes,
                                 interval_ms=interval_ms,
                                 start_seq=start_seq)
        self.replaying = False
        # replication role fence: a read-only replica refuses direct
        # mutations (only the follower's apply loop, which flips
        # ``replaying``, may change state); a fenced ex-primary refuses
        # everything once a higher fencing epoch was witnessed
        self.read_only = False
        self.snapshot_seq = int(snapshot_seq)
        self._snapshot_rows = int(snapshot_rows
                                  or config.SNAPSHOT_ROWS.get())
        self._snapshot_wal_bytes = int(snapshot_wal_bytes
                                       or config.SNAPSHOT_WAL_BYTES.get())
        self._rows_since_snapshot = 0
        self._bytes_since_snapshot = 0
        self._last_snapshot_ts = time.time()
        self._snap_lock = threading.Lock()
        self.closed = False
        # process-level gauges (last attached durable store wins — the
        # one-store-per-process serving shape)
        _metrics.set_gauge("durability.unsynced_bytes",
                           lambda: self.wal.unsynced_bytes)
        _metrics.set_gauge("durability.wal_seq", lambda: self.wal.last_seq)
        _metrics.set_gauge(
            "durability.last_snapshot_age_s",
            lambda: round(time.time() - self._last_snapshot_ts, 1))

    # -- logging (called by datastore mutators, store lock held) -------------

    def log_json(self, kind: str, meta: dict, rows: int = 0) -> Optional[int]:
        if self.replaying or self.closed:
            return None
        self._fence_check()
        from geomesa_tpu.durability.wal import encode_json
        return self._log(kind, encode_json(meta), rows)

    def log_table(self, kind: str, meta: dict, table=None, arrays=None,
                  rows: int = 0) -> Optional[int]:
        if self.replaying or self.closed:
            return None
        self._fence_check()
        from geomesa_tpu.durability.wal import encode_table
        return self._log(kind, encode_table(meta, table, arrays), rows)

    def _fence_check(self) -> None:
        """Refuse the mutation BEFORE it reaches the log or memory: on a
        read-only replica, and on a primary whose fencing epoch was
        superseded (the split-brain loser) — mutators log-then-apply, so
        raising here vetoes the whole operation atomically."""
        from geomesa_tpu.replication.fence import FencedError
        if self.read_only:
            raise FencedError(
                "store is a read-only replica (mutations must go to the "
                "primary; promote() lifts the restriction)")
        repl = getattr(self.store, "replication", None)
        if repl is not None and getattr(repl, "fenced", False):
            raise FencedError(
                f"fencing epoch {repl.epoch} superseded by "
                f"{repl.fenced_by}: this node lost primaryship and can "
                f"no longer accept writes")

    def _log(self, kind: str, payload: bytes, rows: int) -> int:
        seq = self.wal.append(kind, payload)
        self._rows_since_snapshot += rows
        self._bytes_since_snapshot += len(payload)
        return seq

    # -- snapshots ------------------------------------------------------------

    def maybe_snapshot(self) -> bool:
        """Write a snapshot when the accumulation thresholds are crossed.
        Called by mutators AFTER releasing the store lock."""
        if self.replaying or self.closed:
            return False
        if (self._rows_since_snapshot < self._snapshot_rows
                and self._bytes_since_snapshot < self._snapshot_wal_bytes):
            return False
        return self.snapshot()

    def snapshot(self) -> bool:
        """Capture (briefly under the store lock), write + install, rotate
        the WAL, GC covered segments. Serialized; concurrent triggers
        coalesce into one snapshot."""
        from geomesa_tpu import trace as _trace
        from geomesa_tpu.durability import snapshot as _snap
        from geomesa_tpu.features.table import FeatureTable

        if not self._snap_lock.acquire(blocking=False):
            return False  # a snapshot is already in flight
        try:
            with _trace.span("durability.snapshot", kind="aggregate"):
                store = self.store
                with store._lock:
                    schemas = dict(store.schemas)
                    tables = {}
                    for name in schemas:
                        t = store.tables.get(name)
                        d = store.deltas.get(name)
                        if t is not None and d is not None:
                            t = FeatureTable.concat([t, d])
                        elif t is None:
                            t = d
                        tables[name] = t
                    counters = dict(store._counters)
                    generations = dict(store._generations)
                    wal_seq = self.wal.last_seq
                # everything captured is immutable (build-then-swap): the
                # write happens outside the lock; later mutations get
                # seq > wal_seq and stay in the replay suffix
                self.wal.sync()
                _snap.write_snapshot(self.path, schemas, tables, counters,
                                     generations, wal_seq)
                self.snapshot_seq = wal_seq
                self._rows_since_snapshot = 0
                self._bytes_since_snapshot = 0
                self._last_snapshot_ts = time.time()
                self.wal.rotate()
                # GC only records the OLDEST retained snapshot covers: if
                # the newest snapshot is later found corrupt, recovery can
                # still fall back one generation and replay forward from it
                retained = _snap.snapshot_dirs(self.path)
                self.wal.gc(retained[0][0] if retained else wal_seq)
            return True
        finally:
            self._snap_lock.release()

    # -- surfaces -------------------------------------------------------------

    def status(self) -> dict:
        from geomesa_tpu.durability import snapshot as _snap
        snaps = _snap.snapshot_dirs(self.path)
        return {
            "enabled": True,
            "dir": self.path,
            "wal": self.wal.stats(),
            "snapshot_seq": self.snapshot_seq,
            "snapshots": len(snaps),
            "last_snapshot_age_s": round(time.time()
                                         - self._last_snapshot_ts, 1),
            "rows_since_snapshot": self._rows_since_snapshot,
            "wal_bytes_since_snapshot": self._bytes_since_snapshot,
            "snapshot_rows_threshold": self._snapshot_rows,
            "snapshot_wal_bytes_threshold": self._snapshot_wal_bytes,
        }

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.wal.close()
