"""Incremental snapshots: periodic full-state images that let the WAL stay
short.

≙ the Lambda tier's ``DataStorePersistence`` flushing hot state to the cold
store plus the reference's metadata/stats persistence (SURVEY.md §2.6/§3.6):
rather than replaying an unbounded log on restart, the store periodically
writes its complete columnar state (reusing io/checkpoint's table codec,
compressed) tagged with the WAL sequence number it covers. Recovery loads
the newest valid snapshot and replays only the WAL suffix past it; the WAL
then rotates and fully-covered segments are garbage-collected — the
"incremental" part is that each snapshot resets the replay horizon.

Crash-atomicity: a snapshot directory is written under a dot-tmp name, every
file fsynced, then installed via one atomic rename (rotation.atomic_install).
A crash mid-write leaves a ``.tmp-`` dir recovery ignores (and cleans); a
crash between install and WAL GC just means the next recovery skips records
the snapshot already covers (replay starts strictly after ``wal_seq``)."""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

from geomesa_tpu.durability import faults, rotation

_PREFIX = "snapshot-"
_TMP_PREFIX = ".tmp-snapshot-"
_VERSION = 2


def snapshot_dirs(directory: str) -> List[Tuple[int, str]]:
    """(wal_seq, path) for every installed snapshot, oldest first."""
    if not os.path.isdir(directory):
        return []
    out = []
    for fn in os.listdir(directory):
        if fn.startswith(_PREFIX):
            try:
                out.append((int(fn[len(_PREFIX):]), os.path.join(directory, fn)))
            except ValueError:
                continue
    return sorted(out)


def clean_tmp(directory: str) -> int:
    """Remove torn ``.tmp-snapshot-*`` leftovers (a crash mid-write)."""
    n = 0
    if not os.path.isdir(directory):
        return 0
    for fn in os.listdir(directory):
        if fn.startswith(_TMP_PREFIX):
            shutil.rmtree(os.path.join(directory, fn), ignore_errors=True)
            n += 1
    return n


def write_snapshot(directory: str, schemas: Dict[str, object],
                   tables: Dict[str, object], counters: Dict[str, int],
                   generations: Dict[str, int], wal_seq: int,
                   keep: Optional[int] = None) -> str:
    """Write + atomically install one snapshot covering WAL records up to
    and including ``wal_seq``; prune to the newest ``keep`` snapshots.
    ``tables`` must be the fully-merged immutable view (main ∪ delta) —
    the caller captures it under the store lock; this function only reads.

    Stats sketches are deliberately NOT persisted here (unlike io/checkpoint):
    a snapshot's table may merge an unflushed delta the live battery has not
    observed, so recovery re-observes — exactness over restore speed."""
    from geomesa_tpu import config
    from geomesa_tpu.io.checkpoint import _save_table
    from geomesa_tpu.metrics import REGISTRY as _metrics

    faults.crash_point("snapshot.capture")
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f"{_TMP_PREFIX}{wal_seq:020d}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    catalog: dict = {"version": _VERSION, "wal_seq": int(wal_seq),
                     "ts_ms": int(time.time() * 1000), "types": {}}
    for name, sft in schemas.items():
        table = tables.get(name)
        catalog["types"][name] = {
            "spec": sft.to_spec(),
            "counter": int(counters.get(name, 0)),
            "generation": int(generations.get(name, 0)),
            "rows": 0 if table is None else len(table),
        }
        if table is not None and len(table):
            _save_table(table, os.path.join(tmp, f"{name}.npz"))
    with open(os.path.join(tmp, "catalog.json"), "w") as fh:
        json.dump(catalog, fh)
        rotation.fsync_file(fh)
    for fn in os.listdir(tmp):  # data files durable before the rename
        if fn.endswith(".npz"):
            with open(os.path.join(tmp, fn), "rb+") as fh:
                rotation.fsync_file(fh)
    rotation.fsync_dir(tmp)
    final = os.path.join(directory, f"{_PREFIX}{wal_seq:020d}")
    rotation.atomic_install(tmp, final)
    _metrics.inc("snapshot.writes")
    keep_n = int(keep if keep is not None else config.SNAPSHOT_KEEP.get())
    rotation.keep_newest([p for _, p in snapshot_dirs(directory)], keep_n)
    return final


def load_snapshot(path: str):
    """(wal_seq, {type: {"sft", "table", "counter", "generation"}}) from an
    installed snapshot. Raises on a corrupt catalog — recovery falls back
    to the next-older snapshot."""
    from geomesa_tpu.features.sft import SimpleFeatureType
    from geomesa_tpu.io.checkpoint import _load_table

    with open(os.path.join(path, "catalog.json")) as fh:
        catalog = json.load(fh)
    types = {}
    for name, entry in catalog["types"].items():
        sft = SimpleFeatureType.from_spec(name, entry["spec"])
        table = None
        if entry.get("rows", 0):
            npz = os.path.join(path, f"{name}.npz")
            if not os.path.exists(npz):
                raise ValueError(
                    f"corrupt snapshot: {entry['rows']} rows recorded for "
                    f"{name!r} but {npz} is missing")
            table = _load_table(sft, npz)
            if len(table) != entry["rows"]:
                raise ValueError(
                    f"corrupt snapshot: {name!r} has {len(table)} rows, "
                    f"catalog says {entry['rows']}")
        types[name] = {"sft": sft, "table": table,
                       "counter": int(entry.get("counter", 0)),
                       "generation": int(entry.get("generation", 0))}
    return int(catalog["wal_seq"]), types
