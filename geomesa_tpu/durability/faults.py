"""Deterministic fault injection for the durability + serving subsystems.

≙ the crash-consistency test harnesses real storage engines carry (e.g.
Accumulo's WAL recovery tests kill tablet servers at write boundaries): a
registry of named **crash points** threaded through every WAL/snapshot
boundary, plus torn-write / short-write / fsync-failure injection. Tests arm
a point, drive mutations until the injected crash fires, then assert that
``recover()`` reconstructs exactly the oracle state.

The serving path threads through the same registry (**serve points**,
``SERVE_POINTS``): tests inject slow device rounds (``arm_serve_delay``),
dispatch errors (``arm_serve_error``), queue saturation (a collector stall
is a delay at ``sched.collect``), and killed scheduler worker threads
(``arm_serve_crash``) — so every overload / breaker / worker-death behavior
in serve/resilience is exercised deterministically, never by racing real
load.

Design constraints:
  - zero overhead when disarmed (one module-global boolean check);
  - ``InjectedCrash`` derives from BaseException so production ``except
    Exception`` guards can never swallow a simulated process death;
  - deterministic: ``arm(point, at=n)`` fires on the n-th hit of that point,
    so "kill at every crash point" enumerates reproducibly.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, Optional

# every registered crash point, in rough mutation-lifecycle order. Tests
# iterate this to kill the store at each WAL/snapshot boundary.
CRASH_POINTS = (
    "wal.append.before",     # op never reached the log (op lost, never acked)
    "wal.append.torn",       # process died mid-frame-write (torn tail)
    "wal.append.after",      # frame written; died before the in-memory apply
    "wal.fsync",             # died inside the group-commit fsync
    "wal.rotate",            # died between segment close and successor open
    "snapshot.capture",      # died before the snapshot tmp dir was written
    "snapshot.written",      # tmp complete; died before the atomic install
    "snapshot.installed",    # installed; died before WAL rotate + GC
    "wal.gc",                # died before old segments were deleted
)


class InjectedCrash(BaseException):
    """Simulated process death (BaseException: nothing in the store may
    catch-and-continue past a crash)."""

    def __init__(self, point: str):
        super().__init__(f"injected crash at {point!r}")
        self.point = point


# serving-side injection points (scheduler worker loops + device boundary),
# in request-lifecycle order. Tests arm delays/errors/crashes at these.
SERVE_POINTS = (
    "sched.collect",       # top of a collector iteration (stall = queue
                           # saturation; crash = killed collector thread)
    "sched.dispatch",      # immediately before the fused device dispatch
                           # (error = failing device path, feeds the breaker)
    "sched.device_wait",   # before the batched readback blocks (delay =
                           # slow device round, the overload-burst shape)
    "sched.complete",      # top of a completer iteration (crash = killed
                           # completer thread)
    "sched.single",        # before a fallback single execution
)

# replication-pipeline injection points (replication/), in ship-lifecycle
# order. The fleet fault drills arm these: a delay at repl.apply is a lag
# spike (stalled follower apply), a crash at repl.apply is a killed replica
# mid-ship, an error at repl.ship.frame is a flaky replication link.
REPL_POINTS = (
    "repl.ship.frame",     # primary, immediately before sending one frame
    "repl.ship.snapshot",  # primary, before a snapshot-catchup transfer
    "repl.apply",          # follower, before appending+applying a frame
    "repl.ack",            # follower, before sending an ack
)


_lock = threading.Lock()
_active = False                      # fast-path gate (read without the lock)
_armed: Dict[str, int] = {}          # point -> remaining hits before firing
_torn_frac: float = 0.5              # fraction of the frame written when torn
_fsync_errors = 0                    # pending injected fsync failures
_hits: Dict[str, int] = {}           # observability: point -> times reached
_serve_errors: Dict[str, int] = {}   # point -> remaining injected errors
_serve_crash: Dict[str, int] = {}    # point -> hits until InjectedCrash
_serve_delay: Dict[str, list] = {}   # point -> [remaining, seconds]
_repl_corrupt = 0                    # pending shipped-frame corruptions


def reset() -> None:
    """Disarm everything (test teardown)."""
    global _active, _fsync_errors, _repl_corrupt
    with _lock:
        _armed.clear()
        _hits.clear()
        _serve_errors.clear()
        _serve_crash.clear()
        _serve_delay.clear()
        _fsync_errors = 0
        _repl_corrupt = 0
        _active = False


def arm(point: str, at: int = 1) -> None:
    """Fire an InjectedCrash on the ``at``-th hit of ``point``."""
    global _active
    if point not in CRASH_POINTS:
        raise ValueError(f"unknown crash point {point!r} "
                         f"(have {list(CRASH_POINTS)})")
    with _lock:
        _armed[point] = int(at)
        _active = True


def arm_torn(at: int = 1, frac: float = 0.5) -> None:
    """Arm a torn write: the ``at``-th WAL frame write persists only
    ``frac`` of its bytes before the injected crash — the short-write /
    power-loss-mid-sector shape recovery must truncate at."""
    global _torn_frac
    with _lock:
        _torn_frac = float(frac)
    arm("wal.append.torn", at=at)


def arm_fsync_errors(n: int = 1) -> None:
    """Make the next ``n`` fsyncs raise OSError (disk-full / EIO shape)."""
    global _active, _fsync_errors
    with _lock:
        _fsync_errors = int(n)
        _active = True


def crash_point(point: str) -> None:
    """Call site hook: dies here iff the point is armed and its countdown
    reaches zero. Disarmed cost: one global read + compare."""
    if not _active:
        return
    with _lock:
        _hits[point] = _hits.get(point, 0) + 1
        n = _armed.get(point)
        if n is None:
            return
        if n > 1:
            _armed[point] = n - 1
            return
        del _armed[point]
    raise InjectedCrash(point)


def torn_cut(size: int) -> Optional[int]:
    """If a torn write is armed (and due), return how many of ``size``
    frame bytes to persist before crashing; None = write normally. The cut
    is clamped to [0, size-1] so the frame is always incomplete."""
    if not _active:
        return None
    with _lock:
        _hits["wal.append.torn"] = _hits.get("wal.append.torn", 0) + 1
        n = _armed.get("wal.append.torn")
        if n is None:
            return None
        if n > 1:
            _armed["wal.append.torn"] = n - 1
            return None
        del _armed["wal.append.torn"]
        return max(0, min(size - 1, int(size * _torn_frac)))


def fsync_gate() -> None:
    """Raise an injected fsync failure if one is pending (rotation.fsync_file
    calls this before the real os.fsync)."""
    global _fsync_errors
    if not _active:
        return
    with _lock:
        if _fsync_errors <= 0:
            return
        _fsync_errors -= 1
    raise OSError("injected fsync failure")


def hits() -> Dict[str, int]:
    """Times each point was reached since the last reset (diagnostics)."""
    with _lock:
        return dict(_hits)


# -- serving-side injections --------------------------------------------------


def _check_serve_point(point: str) -> None:
    if point not in SERVE_POINTS and point not in REPL_POINTS:
        raise ValueError(f"unknown serve/repl point {point!r} "
                         f"(have {list(SERVE_POINTS + REPL_POINTS)})")


def arm_serve_error(point: str, n: int = 1) -> None:
    """Make the next ``n`` hits of ``point`` raise RuntimeError — the
    injected-dispatch-failure shape (retried by the retry wrapper, counted
    by the circuit breaker)."""
    global _active
    _check_serve_point(point)
    with _lock:
        _serve_errors[point] = int(n)
        _active = True


def arm_serve_crash(point: str, at: int = 1) -> None:
    """Raise InjectedCrash on the ``at``-th hit of ``point`` — a killed
    scheduler worker thread (BaseException: the worker's ``except
    Exception`` guards cannot swallow it; the thread-level handler must
    fail all outstanding futures)."""
    global _active
    _check_serve_point(point)
    with _lock:
        _serve_crash[point] = int(at)
        _active = True


def arm_serve_delay(point: str, seconds: float, n: int = 1) -> None:
    """Sleep ``seconds`` at the next ``n`` hits of ``point`` — slow device
    rounds (``sched.device_wait``) or queue saturation (a stalled
    collector, ``sched.collect``)."""
    global _active
    _check_serve_point(point)
    with _lock:
        _serve_delay[point] = [int(n), float(seconds)]
        _active = True


def serve_gate(point: str) -> None:
    """Call-site hook on the serving path: applies any armed delay, then
    any armed error or crash, in that order. Disarmed cost: one global
    read + compare (the same zero-overhead contract as crash_point)."""
    if not _active:
        return
    sleep_s = None
    exc: Optional[BaseException] = None
    with _lock:
        _hits[point] = _hits.get(point, 0) + 1
        d = _serve_delay.get(point)
        if d is not None and d[0] > 0:
            d[0] -= 1
            sleep_s = d[1]
        n = _serve_errors.get(point, 0)
        if n > 0:
            _serve_errors[point] = n - 1
            exc = RuntimeError(f"injected serve error at {point!r}")
        else:
            c = _serve_crash.get(point)
            if c is not None:
                if c > 1:
                    _serve_crash[point] = c - 1
                else:
                    del _serve_crash[point]
                    exc = InjectedCrash(point)
    if sleep_s:
        _time.sleep(sleep_s)
    if exc is not None:
        raise exc


def arm_repl_corrupt(n: int = 1) -> None:
    """Corrupt the next ``n`` shipped WAL frames in flight (one flipped
    byte mid-frame) — the torn-shipped-frame drill. The receiver must
    reject the frame on CRC and resynchronize from its acked seq."""
    global _active, _repl_corrupt
    with _lock:
        _repl_corrupt = int(n)
        _active = True


def repl_corrupt(frame: bytes) -> bytes:
    """Shipper-side hook: returns ``frame`` unchanged, or a copy with one
    byte flipped when a corruption is armed and due."""
    global _repl_corrupt
    if not _active:
        return frame
    with _lock:
        if _repl_corrupt <= 0:
            return frame
        _repl_corrupt -= 1
        _hits["repl.corrupt"] = _hits.get("repl.corrupt", 0) + 1
    b = bytearray(frame)
    b[len(b) // 2] ^= 0xFF
    return bytes(b)
