"""Durability subsystem: write-ahead log, incremental snapshots, crash
recovery, fault injection.

≙ the reference's storage-tier durability (Accumulo/HBase WALs + the Lambda
tier's DataStorePersistence, SURVEY.md §2.6/§3.6): every logical mutation is
crash-safe before it is acknowledged, restarts recover to exactly the logged
state, and the fault-injection harness proves it by killing the store at
every WAL/snapshot boundary.

    store = TpuDataStore.open("/data/mystore")      # recovers if needed
    store.durability.snapshot()                      # force a snapshot
    report = store.recovery_report                   # what recovery did

Modules: wal (CRC-framed segments + group-commit fsync), snapshot
(tmp+rename-installed incremental images), recovery (snapshot + WAL-suffix
replay with torn-tail truncation), faults (crash-point registry), rotation
(the shared fsync/rotate/atomic-install helpers), manager (store wiring)."""

from geomesa_tpu.durability import faults  # noqa: F401
from geomesa_tpu.durability.manager import DurabilityManager, attach  # noqa: F401
from geomesa_tpu.durability.recovery import (RecoveryReport,  # noqa: F401
                                             recover_into)
from geomesa_tpu.durability.wal import KINDS, WriteAheadLog  # noqa: F401
