"""Append-only, CRC-framed write-ahead log with group-commit fsync.

≙ the reference's storage-tier WALs (Accumulo/HBase write-ahead logs under
the GeoMesa index tables — every mutation is durable before it is
acknowledged) transplanted onto the in-process TPU store: each logical
mutation (append batch / upsert / delete / update / age-off / schema op /
hot-tier GeoMessage) is encoded as one compact framed record and appended to
a numbered segment file.

Framing (all little-endian)::

    segment header:  b"GTW1" + u64 first_seq                    (12 bytes)
    record frame:    u32 crc | u32 len | u64 seq | u8 kind | payload

``crc`` is crc32 over (len, seq, kind, payload), so a torn tail — a frame
cut short by a crash mid-write — fails verification and recovery truncates
the log at the last whole record (the reference's WAL recovery discipline).
Sequence numbers are global and contiguous across segments; a gap is treated
as corruption.

Fsync policy (``GEOMESA_TPU_WAL_FSYNC``):

  off      never fsync (OS page cache only; survives process death, not
           power loss) — the bulk-load setting
  batch    group commit: appends buffer and a background syncer fsyncs once
           per commit window (``GEOMESA_TPU_WAL_INTERVAL_MS``); bounded
           data-at-risk, near-zero per-append cost (default)
  always   every append is durable before it returns, with cross-thread
           group commit (concurrent appenders piggyback on one fsync —
           the classic log-manager optimization)

Payload codecs: JSON records for metadata ops, npz (uncompressed — the WAL
is throughput-critical) reusing io/checkpoint's columnar table codec for
feature batches. Fault-injection hooks (faults.py) thread through every
write/fsync boundary."""

from __future__ import annotations

import json
import os
import re
import struct
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from geomesa_tpu.durability import faults
from geomesa_tpu.durability.faults import InjectedCrash

_MAGIC = b"GTW1"
_HEADER = struct.Struct("<4sQ")          # magic, first seq in segment
_FRAME = struct.Struct("<IIQB")          # crc, payload len, seq, kind
_CRC_PART = struct.Struct("<IQB")        # the crc-covered frame fields

# -- record kinds -------------------------------------------------------------

KINDS: Dict[str, int] = {
    # cold-store logical mutations (datastore.py hooks)
    "append": 1, "upsert": 2, "remove": 3, "update": 4, "age_off": 5,
    "create_schema": 6, "remove_schema": 7, "update_schema": 8,
    # hot-tier journal (stream/live.py) — GeoMessages + persist fencing
    "hot_put": 16, "hot_delete": 17, "hot_clear": 18, "hot_expire": 19,
    "persist_begin": 20, "persist_commit": 21,
}
KIND_NAMES = {v: k for k, v in KINDS.items()}


# -- payload codecs -----------------------------------------------------------


def _json_default(o):
    if isinstance(o, np.datetime64):
        return str(o)
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def encode_json(meta: dict) -> bytes:
    return json.dumps(meta, separators=(",", ":"),
                      default=_json_default).encode()


def decode_json(payload: bytes) -> dict:
    return json.loads(payload.decode())


def encode_table(meta: dict, table=None,
                 arrays: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """Compact raw-buffer payload: a JSON header (meta + column
    descriptors) followed by the concatenated array bytes. Deliberately NOT
    npz: zipfile framing pays a crc32 + copy per member and ~20% of the
    ingest budget — the WAL's outer frame already carries the CRC, so the
    payload is a straight memcpy of each column. String columns (fids,
    dictionary vocabs) ship as a length array + one utf-8 blob (no numpy
    unicode-dtype conversion, which dominates npz encode at scale).
    Snapshots keep the compressed npz codec (io/checkpoint) instead."""
    header_cols: list = []
    bufs: list = []

    def add_arr(key: str, arr) -> None:
        arr = np.ascontiguousarray(arr)
        b = arr.tobytes()
        header_cols.append({"k": key, "dt": arr.dtype.str,
                            "sh": list(arr.shape), "n": len(b)})
        bufs.append(b)

    def add_strs(key: str, values) -> None:
        # fast path: one join + one encode (no per-string python work).
        # The unit separator can only under-count if a VALUE contains it —
        # detected by the count check, which falls back to length-prefixed.
        try:
            joined = "\x1f".join(values)
        except TypeError:
            values = [str(v) for v in values]
            joined = "\x1f".join(values)
        n_vals = len(values)
        if n_vals == 0 or joined.count("\x1f") == n_vals - 1:
            blob = joined.encode("utf-8")
            header_cols.append({"k": key, "dt": "sepblob", "c": n_vals,
                                "n": len(blob)})
            bufs.append(blob)
            return
        enc = [str(v).encode("utf-8") for v in values]
        add_arr(key + ":lens",
                np.fromiter((len(e) for e in enc), dtype=np.int32,
                            count=len(enc)))
        blob = b"".join(enc)
        header_cols.append({"k": key, "dt": "blob", "n": len(blob)})
        bufs.append(blob)

    if table is not None:
        add_strs("__fids__", table.fids)
        if table.visibility is not None:
            add_arr("__vis__:codes", table.visibility.codes)
            add_strs("__vis__:vocab", table.visibility.vocab)
        from geomesa_tpu.features.geometry import GeometryArray
        from geomesa_tpu.features.table import StringColumn
        for attr in table.sft.attributes:
            col = table.columns[attr.name]
            k = f"col:{attr.name}"
            if isinstance(col, GeometryArray):
                add_arr(k + ":types", col.type_codes)
                add_arr(k + ":geom_off", col.geom_offsets)
                add_arr(k + ":part_off", col.part_offsets)
                add_arr(k + ":ring_off", col.ring_offsets)
                add_arr(k + ":coords", col.coords)
            elif isinstance(col, StringColumn):
                add_arr(k + ":codes", col.codes)
                add_strs(k + ":vocab", col.vocab)
            else:
                add_arr(k, np.asarray(col))
    for k, v in (arrays or {}).items():
        add_arr(f"x:{k}", np.asarray(v))
    header = encode_json({"meta": meta, "cols": header_cols})
    return struct.pack("<I", len(header)) + header + b"".join(bufs)


def peek_meta(payload: bytes) -> dict:
    """Just the meta dict of an ``encode_table`` payload — no array or
    string-column decode (recovery uses it to resolve the target schema
    before paying for the full decode)."""
    (hlen,) = struct.unpack_from("<I", payload)
    return json.loads(payload[4:4 + hlen].decode())["meta"]


def decode_table(payload: bytes, sft=None):
    """(meta, table | None, arrays) from an ``encode_table`` payload; the
    table decodes only when ``sft`` is given and table columns are present."""
    from geomesa_tpu.features.geometry import GeometryArray
    from geomesa_tpu.features.table import FeatureTable, StringColumn

    (hlen,) = struct.unpack_from("<I", payload)
    header = json.loads(payload[4:4 + hlen].decode())
    off = 4 + hlen
    vals: Dict[str, object] = {}
    for c in header["cols"]:
        b = payload[off:off + c["n"]]
        off += c["n"]
        if c["dt"] == "sepblob":
            vals[c["k"]] = b.decode("utf-8").split("\x1f") if c["c"] else []
        elif c["dt"] == "blob":
            lens = vals.pop(c["k"] + ":lens")
            ends = np.cumsum(lens)
            starts = ends - lens
            vals[c["k"]] = [b[s:e].decode("utf-8")
                            for s, e in zip(starts, ends)]
        else:
            vals[c["k"]] = np.frombuffer(b, dtype=np.dtype(c["dt"])) \
                .reshape(c["sh"])
    meta = header["meta"]
    table = None
    if sft is not None and "__fids__" in vals:
        data: Dict[str, object] = {}
        for attr in sft.attributes:
            k = f"col:{attr.name}"
            if attr.is_geometry:
                data[attr.name] = GeometryArray(
                    vals[k + ":types"], vals[k + ":geom_off"],
                    vals[k + ":part_off"], vals[k + ":ring_off"],
                    np.array(vals[k + ":coords"]))
            elif attr.type_name == "String":
                data[attr.name] = StringColumn(
                    np.array(vals[k + ":codes"]), vals[k + ":vocab"])
            else:
                data[attr.name] = np.array(vals[k])  # writable copy
        fids = np.asarray(vals["__fids__"], dtype=object)
        table = FeatureTable.build(sft, data, fids=fids)
        if "__vis__:codes" in vals:
            table.visibility = StringColumn(
                np.array(vals["__vis__:codes"]), vals["__vis__:vocab"])
    arrays = {k[2:]: v for k, v in vals.items() if k.startswith("x:")}
    return meta, table, arrays


# -- segment scanning ---------------------------------------------------------


_SEG_RE = re.compile(r"^(?P<name>.+)-(?P<seq>\d{20})\.log$")


def segment_path(directory: str, name: str, first_seq: int) -> str:
    return os.path.join(directory, f"{name}-{first_seq:020d}.log")


def segments(directory: str, name: str = "wal") -> List[str]:
    """Segment paths for ``name`` in ``directory``, oldest first."""
    if not os.path.isdir(directory):
        return []
    out = []
    for fn in os.listdir(directory):
        m = _SEG_RE.match(fn)
        if m and m.group("name") == name:
            out.append(os.path.join(directory, fn))
    return sorted(out)


def segment_first_seq(path: str) -> int:
    return int(_SEG_RE.match(os.path.basename(path)).group("seq"))


def scan_segment(path: str):
    """Parse one segment: ``(records, valid_end_offset, error)`` where
    records are ``(seq, kind_name, payload, offset)`` tuples, in order.
    Stops at the first torn/corrupt frame: ``valid_end_offset`` is where the
    intact prefix ends (recovery truncates there) and ``error`` says why
    (None = clean to EOF)."""
    records: List[Tuple[int, str, bytes, int]] = []
    with open(path, "rb") as fh:
        head = fh.read(_HEADER.size)
        if len(head) < _HEADER.size:
            return records, 0, "truncated segment header"
        magic, first_seq = _HEADER.unpack(head)
        if magic != _MAGIC:
            return records, 0, "bad segment magic"
        pos = _HEADER.size
        expect = first_seq
        while True:
            hdr = fh.read(_FRAME.size)
            if not hdr:
                return records, pos, None
            if len(hdr) < _FRAME.size:
                return records, pos, "torn frame header"
            crc, length, seq, kind = _FRAME.unpack(hdr)
            payload = fh.read(length)
            if len(payload) < length:
                return records, pos, "torn frame payload"
            if zlib.crc32(_CRC_PART.pack(length, seq, kind) + payload) != crc:
                return records, pos, "bad crc"
            if seq != expect:
                return records, pos, f"sequence gap (want {expect}, got {seq})"
            records.append((seq, KIND_NAMES.get(kind, f"kind{kind}"),
                            payload, pos))
            pos += _FRAME.size + length
            expect += 1


def iter_records(directory: str, name: str = "wal",
                 after_seq: int = 0) -> Iterator[Tuple[int, str, bytes]]:
    """Records with seq > ``after_seq`` across all segments, in order;
    stops silently at the first torn/corrupt frame (recovery handles the
    truncation separately via scan_segment)."""
    for seg in segments(directory, name):
        records, _, error = scan_segment(seg)
        for seq, kind, payload, _off in records:
            if seq > after_seq:
                yield seq, kind, payload
        if error is not None:
            return


_TORN_ERRORS = ("torn frame header", "torn frame payload", "bad crc",
                "truncated segment header")


def contiguity(directory: str, name: str = "wal") -> dict:
    """Whole-log contiguity diagnosis: where (if anywhere) the global
    sequence breaks, and what kind of break it is. Shippers and recovery
    both need the distinction a silent stop-at-first-error hides:

      torn_tail        the LAST segment ends in a cut/corrupt frame — a
                       mid-write crash; everything before it is intact and
                       nothing recoverable is lost
      missing_segment  records exist PAST the break (a deleted/corrupt
                       middle segment, or a first_seq jump between
                       segments) — later records can never be ordered and
                       ``unreachable_records`` of them would be dropped

    ``first_gap_seq`` is the first sequence number that should exist but
    cannot be read (None when the log is contiguous to its end)."""
    out = {"first_seq": None, "last_contiguous_seq": None,
           "first_gap_seq": None, "gap_kind": None, "gap_error": None,
           "unreachable_records": 0, "unreachable_segments": 0}
    segs = segments(directory, name)
    expect: Optional[int] = None
    unreachable_from: Optional[int] = None  # index of first stranded segment
    for i, seg in enumerate(segs):
        first = segment_first_seq(seg)
        if out["first_seq"] is None:
            out["first_seq"] = first
        if expect is not None and first > expect:
            # a whole segment's worth of seqs is missing between i-1 and i
            out["first_gap_seq"] = expect
            out["gap_kind"] = "missing_segment"
            out["gap_error"] = (f"segment starting at {first} follows "
                                f"last readable seq {expect - 1}")
            unreachable_from = i
            break
        records, _end, error = scan_segment(seg)
        if records:
            out["last_contiguous_seq"] = records[-1][0]
            expect = records[-1][0] + 1
        elif expect is None:
            expect = first
        if error is not None:
            out["first_gap_seq"] = expect
            out["gap_error"] = error
            # a break in the FINAL segment is the ordinary torn tail a
            # crash leaves; a break with segments after it strands them
            out["gap_kind"] = ("torn_tail" if i == len(segs) - 1
                               and error in _TORN_ERRORS
                               else "missing_segment")
            unreachable_from = i + 1
            break
    if unreachable_from is not None:
        for later in segs[unreachable_from:]:
            out["unreachable_segments"] += 1
            out["unreachable_records"] += len(scan_segment(later)[0])
    return out


def read_raw_frames(path: str, offset: int = 0, after_seq: int = 0):
    """Raw CRC-verified frames from one segment starting at byte
    ``offset`` (0 = start, past the header): yields ``(seq, kind_name,
    frame_bytes, end_offset)`` where ``frame_bytes`` is the exact on-disk
    ``crc|len|seq|kind|payload`` encoding — a shipper forwards it verbatim
    so the receiver re-verifies the SAME crc. Stops (without raising) at
    the first torn/corrupt frame; the caller may retry from the returned
    end_offset once more bytes exist (a torn live head is just a frame
    still being written)."""
    with open(path, "rb") as fh:
        if offset <= _HEADER.size:
            head = fh.read(_HEADER.size)
            if len(head) < _HEADER.size or head[:4] != _MAGIC:
                return
            offset = _HEADER.size
        fh.seek(offset)
        while True:
            hdr = fh.read(_FRAME.size)
            if len(hdr) < _FRAME.size:
                return
            crc, length, seq, kind = _FRAME.unpack(hdr)
            payload = fh.read(length)
            if len(payload) < length:
                return
            if zlib.crc32(_CRC_PART.pack(length, seq, kind) + payload) != crc:
                return
            offset += _FRAME.size + length
            if seq > after_seq:
                yield (seq, KIND_NAMES.get(kind, f"kind{kind}"),
                       hdr + payload, offset)


def verify_frame(frame: bytes):
    """Validate one raw frame's structure + CRC; returns
    ``(seq, kind_name, payload)`` or raises ValueError — the follower-side
    receipt check for shipped frames (runs BEFORE any duplicate-skip, so a
    corrupted frame can never masquerade as an already-held record)."""
    if len(frame) < _FRAME.size:
        raise ValueError("short frame")
    crc, length, seq, kind = _FRAME.unpack_from(frame)
    if len(frame) != _FRAME.size + length:
        raise ValueError(f"frame length mismatch ({len(frame)} != "
                         f"{_FRAME.size + length})")
    payload = frame[_FRAME.size:]
    if zlib.crc32(_CRC_PART.pack(length, seq, kind) + payload) != crc:
        raise ValueError(f"bad frame crc at seq {seq}")
    return seq, KIND_NAMES.get(kind, f"kind{kind}"), payload


class WalTailer:
    """Incremental raw-frame reader for the log shipper: tracks (segment,
    byte offset, next expected seq) so each ``poll()`` reads only NEW
    frames instead of rescanning the log. Follows size-based rotation; a
    torn live head (a frame mid-write) simply ends the poll and retries at
    the same offset next time. Raises FileNotFoundError when the needed
    segment was garbage-collected out from under the tail (the follower
    is then too far behind and must snapshot-catchup)."""

    def __init__(self, directory: str, name: str = "wal",
                 after_seq: int = 0):
        self.dir = directory
        self.name = name
        self.next_seq = int(after_seq) + 1
        self._seg: Optional[str] = None
        self._off = 0

    def _locate(self) -> Optional[str]:
        """Segment that should contain ``next_seq`` (newest first_seq <=
        next_seq); None when the log has nothing at or before it yet."""
        best = None
        for seg in segments(self.dir, self.name):
            if segment_first_seq(seg) <= self.next_seq:
                best = seg
        if best is None and segments(self.dir, self.name):
            raise FileNotFoundError(
                f"wal segment containing seq {self.next_seq} was "
                f"garbage-collected")
        return best

    def poll(self, limit: Optional[int] = None):
        """All newly readable ``(seq, kind_name, frame_bytes)`` in order
        (up to ``limit``)."""
        out = []
        while True:
            if self._seg is None:
                self._seg = self._locate()
                self._off = 0
                if self._seg is None:
                    return out
            if not os.path.exists(self._seg):
                raise FileNotFoundError(self._seg)
            advanced = False
            for seq, kind, frame, end in read_raw_frames(
                    self._seg, self._off, after_seq=self.next_seq - 1):
                self._off = end
                advanced = True
                if seq != self.next_seq:
                    # pre-existing intra-segment gap: unreachable past here
                    return out
                out.append((seq, kind, frame))
                self.next_seq = seq + 1
                if limit is not None and len(out) >= limit:
                    return out
            if not advanced and self._off == 0:
                # skipped records before next_seq count as progress too
                recs = list(read_raw_frames(self._seg, 0, after_seq=0))
                if recs:
                    self._off = recs[-1][3]
            # rotation: a successor segment owns next_seq now
            succ = None
            for seg in segments(self.dir, self.name):
                if seg != self._seg and \
                        segment_first_seq(seg) == self.next_seq:
                    succ = seg
                    break
            if succ is not None:
                self._seg, self._off = succ, 0
                continue
            return out


def inspect(directory: str, name: str = "wal") -> dict:
    """Debug dump for the CLI ``debug wal`` inspector: per-segment record
    listing (seq, kind, bytes), torn-tail diagnostics, and the whole-log
    contiguity diagnosis (first_gap_seq + torn-tail vs missing-segment
    classification)."""
    out: dict = {"dir": directory, "name": name, "segments": []}
    for seg in segments(directory, name):
        records, valid_end, error = scan_segment(seg)
        size = os.path.getsize(seg)
        out["segments"].append({
            "path": seg,
            "first_seq": segment_first_seq(seg),
            "bytes": size,
            "records": len(records),
            "seq_range": [records[0][0], records[-1][0]] if records else None,
            "kinds": {k: sum(1 for r in records if r[1] == k)
                      for k in {r[1] for r in records}},
            "torn": None if error is None else
                    {"error": error, "valid_end": valid_end,
                     "trailing_bytes": size - valid_end},
        })
    out["contiguity"] = contiguity(directory, name)
    return out


# -- the log ------------------------------------------------------------------


import weakref

# live WriteAheadLog instances (weak: a closed+dropped store frees its WAL)
# — the `wal.open_segments` gauge sums on-disk segment counts over these
_LIVE_WALS: "weakref.WeakSet" = weakref.WeakSet()


def open_segment_count() -> int:
    """Total on-disk segments across every live (unclosed) WAL in this
    process — the observability-buffer-pressure gauge feed."""
    total = 0
    for w in list(_LIVE_WALS):
        if not w._closed:
            total += len(segments(w.dir, w.name))
    return total


class WriteAheadLog:
    """One append-only log (a directory of numbered segments). Thread-safe;
    mutators call ``append`` before applying their mutation in memory
    (log-then-apply), recovery replays via ``iter_records``."""

    def __init__(self, directory: str, name: str = "wal",
                 fsync: Optional[str] = None,
                 segment_bytes: Optional[int] = None,
                 interval_ms: Optional[float] = None,
                 start_seq: int = 1):
        from geomesa_tpu import config
        self.dir = directory
        self.name = name
        self.policy = (fsync or config.WAL_FSYNC.get()).lower()
        if self.policy not in ("off", "batch", "always"):
            raise ValueError(f"unknown WAL fsync policy {self.policy!r}")
        self.segment_bytes = int(segment_bytes
                                 or config.WAL_SEGMENT_BYTES.get())
        self.interval_s = (interval_ms if interval_ms is not None
                           else config.WAL_INTERVAL_MS.get()) / 1000.0
        os.makedirs(directory, exist_ok=True)
        # a pre-existing break in the on-disk log (recovery normally cleans
        # one up first, but a follower/shipper-facing WAL may still carry
        # it): diagnosed once at open — live appends can never create one
        self._initial_gap = (contiguity(directory, name)
                             if segments(directory, name) else None)
        self._lock = threading.RLock()
        self._sync_cond = threading.Condition()
        self._sync_leader = False
        self._tail_cond = threading.Condition()
        self._tail_waiters = 0
        self._next_seq = int(start_seq)
        self._last_seq = int(start_seq) - 1
        self._synced_seq = self._last_seq
        self._written_bytes = 0
        self._synced_bytes = 0
        self._n_fsyncs = 0
        self._fh = None
        self._seg_size = 0
        self._closed = False
        self._syncer: Optional[threading.Thread] = None
        self._syncer_stop = threading.Event()
        self._open_segment()
        _LIVE_WALS.add(self)

    # -- state ---------------------------------------------------------------

    @property
    def last_seq(self) -> int:
        return self._last_seq

    @property
    def synced_seq(self) -> int:
        return self._synced_seq

    @property
    def unsynced_bytes(self) -> int:
        return max(0, self._written_bytes - self._synced_bytes)

    def stats(self) -> dict:
        gap = self._initial_gap or {}
        return {
            "policy": self.policy,
            "last_seq": self._last_seq,
            "synced_seq": self._synced_seq,
            "unsynced_bytes": self.unsynced_bytes,
            "fsyncs": self._n_fsyncs,
            "segments": len(segments(self.dir, self.name)),
            "segment_bytes": self._seg_size,
            # explicit contiguity break (None = contiguous): shippers and
            # recovery distinguish "torn tail" from "missing segment"
            # instead of silently dropping everything past the break
            "first_gap_seq": gap.get("first_gap_seq"),
            "gap_kind": gap.get("gap_kind"),
        }

    # -- writing -------------------------------------------------------------

    def append(self, kind: str, payload: bytes) -> int:
        """Append one record; returns its sequence number. Under policy
        ``always`` the record is fsync-durable on return (group commit);
        under ``batch`` within one commit window; under ``off`` whenever
        the OS flushes."""
        from geomesa_tpu import trace as _trace
        from geomesa_tpu.metrics import REGISTRY as _metrics
        k = KINDS[kind]
        t0 = time.perf_counter()
        with self._lock:
            if self._closed:
                raise ValueError("WAL is closed")
            faults.crash_point("wal.append.before")
            seq = self._next_seq
            # incremental crc: no header+payload concat copy on the hot path
            crc = zlib.crc32(payload,
                             zlib.crc32(_CRC_PART.pack(len(payload), seq, k)))
            hdr = _FRAME.pack(crc, len(payload), seq, k)
            frame_len = _FRAME.size + len(payload)
            cut = faults.torn_cut(frame_len)
            if cut is not None:
                # simulated power loss mid-write: persist the torn prefix so
                # recovery actually faces it, then die
                self._fh.write((hdr + payload)[:cut])
                self._fh.flush()
                os.fsync(self._fh.fileno())
                raise InjectedCrash("wal.append.torn")
            self._fh.write(hdr)
            self._fh.write(payload)
            self._next_seq = seq + 1
            self._last_seq = seq
            self._seg_size += frame_len
            self._written_bytes += frame_len
            need_rotate = self._seg_size >= self.segment_bytes
        _metrics.inc("wal.records")
        _metrics.observe_value("wal.append_bytes", frame_len)
        if self.policy == "always":
            self._group_sync(seq)
        elif self.policy == "batch":
            self._ensure_syncer()
        if _trace.enabled():
            _trace.record("wal.append", "wal_append",
                          time.perf_counter() - t0)
        if self._tail_waiters:
            with self._tail_cond:
                self._tail_cond.notify_all()
        if need_rotate:
            self.rotate()
        faults.crash_point("wal.append.after")
        return seq

    def append_frame(self, frame: bytes) -> int:
        """Append one pre-framed record (``crc|len|seq|kind|payload``)
        verbatim — the follower-side ingestion of a shipped frame. The
        frame's CRC is re-verified and its seq must be exactly the next
        expected (shipped logs stay byte-identical to the primary's,
        modulo segment boundaries). Durability policy applies as for
        ``append``."""
        if len(frame) < _FRAME.size:
            raise ValueError("short frame")
        crc, length, seq, kind = _FRAME.unpack_from(frame)
        if len(frame) != _FRAME.size + length:
            raise ValueError(
                f"frame length mismatch ({len(frame)} != "
                f"{_FRAME.size + length})")
        if zlib.crc32(_CRC_PART.pack(length, seq, kind)
                      + frame[_FRAME.size:]) != crc:
            raise ValueError(f"bad frame crc at seq {seq}")
        from geomesa_tpu.metrics import REGISTRY as _metrics
        with self._lock:
            if self._closed:
                raise ValueError("WAL is closed")
            if seq != self._next_seq:
                raise ValueError(
                    f"non-contiguous frame seq {seq} (expect "
                    f"{self._next_seq})")
            self._fh.write(frame)
            self._next_seq = seq + 1
            self._last_seq = seq
            self._seg_size += len(frame)
            self._written_bytes += len(frame)
            need_rotate = self._seg_size >= self.segment_bytes
        _metrics.inc("wal.records")
        _metrics.observe_value("wal.append_bytes", len(frame))
        if self.policy == "always":
            self._group_sync(seq)
        elif self.policy == "batch":
            self._ensure_syncer()
        if self._tail_waiters:
            with self._tail_cond:
                self._tail_cond.notify_all()
        if need_rotate:
            self.rotate()
        return seq

    def flush_to_os(self) -> None:
        """Push the userspace write buffer to the OS page cache (no fsync)
        so on-disk readers — the log shipper's tail — observe every
        appended frame immediately, regardless of fsync policy."""
        with self._lock:
            if self._fh is not None and not self._closed:
                self._fh.flush()

    def wait_for_seq(self, seq: int, timeout: Optional[float] = None) -> bool:
        """Block until a record with sequence >= ``seq`` has been appended
        (True) or ``timeout`` seconds pass (False). The shipper's idle
        wait: appends wake it immediately; the capped internal wait bounds
        the cost of any missed notify."""
        if self._last_seq >= seq:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._tail_cond:
            self._tail_waiters += 1
            try:
                while self._last_seq < seq and not self._closed:
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        return False
                    self._tail_cond.wait(0.1 if remaining is None
                                         else min(remaining, 0.1))
            finally:
                self._tail_waiters -= 1
        return self._last_seq >= seq

    def append_json(self, kind: str, meta: dict) -> int:
        return self.append(kind, encode_json(meta))

    def append_table(self, kind: str, meta: dict, table=None,
                     arrays=None) -> int:
        return self.append(kind, encode_table(meta, table, arrays))

    def sync(self) -> None:
        """Force a group fsync covering everything appended so far."""
        with self._lock:
            target = self._last_seq
        self._group_sync(target)

    def _group_sync(self, seq: int) -> None:
        """Group commit: make records up to ``seq`` durable. One thread
        leads (flush+fsync); concurrent callers piggyback on its fsync and
        return as soon as their seq is covered."""
        from geomesa_tpu import trace as _trace
        from geomesa_tpu.metrics import REGISTRY as _metrics
        with self._sync_cond:
            while True:
                if self._synced_seq >= seq:
                    return
                if not self._sync_leader:
                    self._sync_leader = True
                    break
                self._sync_cond.wait()
        try:
            with self._lock:
                fh = self._fh
                target = self._last_seq
                written = self._written_bytes
            t0 = time.perf_counter()
            faults.crash_point("wal.fsync")
            from geomesa_tpu import config
            from geomesa_tpu.durability.rotation import fsync_file
            attempts = int(config.RETRY_WAL_FSYNC.get())
            if attempts <= 1:
                fsync_file(fh)
            else:
                # transient-EIO absorption behind the shared capped-backoff
                # wrapper (GEOMESA_TPU_RETRY_WAL_FSYNC > 1 opts in; the
                # default stays strict so 'always' surfaces the first
                # failure to the writer that demanded durability)
                from geomesa_tpu.serve.resilience.breaker import retry_call
                retry_call(lambda: fsync_file(fh), attempts=attempts,
                           counter="wal.fsync_retries")
            dt = time.perf_counter() - t0
        except OSError:
            _metrics.inc("wal.fsync_errors")
            raise
        finally:
            with self._sync_cond:
                self._sync_leader = False
                self._sync_cond.notify_all()
        with self._sync_cond:
            group = max(0, target - self._synced_seq)
            self._synced_seq = max(self._synced_seq, target)
            self._sync_cond.notify_all()
        with self._lock:
            self._synced_bytes = max(self._synced_bytes, written)
            self._n_fsyncs += 1
        _metrics.inc("wal.fsyncs")
        if group:
            _metrics.observe_value("wal.group_size", group)
        if _trace.enabled():
            _trace.record("wal.fsync", "wal_fsync", dt)
        # callers whose seq landed after our target retry via recursion
        # (bounded: each level covers strictly more of the log)
        if seq > self._synced_seq:
            self._group_sync(seq)

    def _ensure_syncer(self) -> None:
        if self._syncer is not None:
            return
        with self._lock:
            if self._syncer is not None or self._closed:
                return
            t = threading.Thread(target=self._sync_loop,
                                 name=f"geomesa-wal-sync-{self.name}",
                                 daemon=True)
            self._syncer = t
        t.start()

    def _sync_loop(self) -> None:
        from geomesa_tpu.metrics import REGISTRY as _metrics
        while not self._syncer_stop.wait(self.interval_s):
            if self._closed:
                return
            if self.unsynced_bytes or self._synced_seq < self._last_seq:
                try:
                    self.sync()
                except OSError:
                    # injected/real fsync failure: counted (in _group_sync),
                    # retried next window — the batch policy's contract
                    continue
                except Exception:
                    _metrics.inc("wal.fsync_errors")
                    continue

    # -- segment lifecycle ---------------------------------------------------

    def _open_segment(self) -> None:
        path = segment_path(self.dir, self.name, self._next_seq)
        # "wb": a same-named leftover can only be an empty (header-only)
        # segment from a prior recover-then-crash — records would have
        # advanced the seq past it
        self._fh = open(path, "wb")
        self._fh.write(_HEADER.pack(_MAGIC, self._next_seq))
        self._fh.flush()
        self._seg_size = _HEADER.size
        self._written_bytes += _HEADER.size
        from geomesa_tpu.metrics import REGISTRY as _metrics
        _metrics.inc("wal.segments")

    def rotate(self) -> None:
        """Close the live segment (fsynced unless policy ``off``) and open
        its successor. Called on size overflow and after each snapshot."""
        # become the sync leader so no in-flight group fsync holds the old fh
        with self._sync_cond:
            while self._sync_leader:
                self._sync_cond.wait()
            self._sync_leader = True
        try:
            with self._lock:
                if self._closed:
                    return
                if self._seg_size <= _HEADER.size:
                    return  # empty segment: nothing to rotate
                faults.crash_point("wal.rotate")
                if self.policy != "off":
                    from geomesa_tpu.durability.rotation import fsync_file
                    fsync_file(self._fh)
                    self._synced_seq = self._last_seq
                    self._synced_bytes = self._written_bytes
                else:
                    self._fh.flush()
                self._fh.close()
                self._open_segment()
        finally:
            with self._sync_cond:
                self._sync_leader = False
                self._sync_cond.notify_all()

    def gc(self, upto_seq: int) -> int:
        """Delete segments made fully redundant by a snapshot covering
        ``upto_seq`` (every record with seq <= upto_seq is in the snapshot).
        A segment dies only when its successor proves it holds no later
        records. Returns segments removed."""
        faults.crash_point("wal.gc")
        segs = segments(self.dir, self.name)
        removed = 0
        with self._lock:
            current = self._fh.name if self._fh else None
        for i in range(len(segs) - 1):
            if segs[i] == current:
                continue
            if segment_first_seq(segs[i + 1]) <= upto_seq + 1:
                try:
                    os.remove(segs[i])
                    removed += 1
                except OSError:
                    pass
        return removed

    def close(self) -> None:
        self._syncer_stop.set()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fh.flush()
                if self.policy != "off":
                    os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass
            self._fh.close()
        if self._syncer is not None:
            self._syncer.join(timeout=2)
