"""Crash recovery: newest valid snapshot + WAL suffix replay.

≙ the reference stores' restart path (Accumulo tablet recovery: load the
last-flushed RFiles, sort and replay the WAL tail, truncating torn records)
mapped onto the columnar store:

  1. clean torn ``.tmp-snapshot-*`` leftovers;
  2. load the newest snapshot whose catalog + payloads verify (falling back
     older on corruption; empty store when none exists);
  3. replay WAL records with seq strictly greater than the snapshot's
     ``wal_seq`` through the store's ordinary mutation paths (logging
     suppressed — the segments on disk stay authoritative);
  4. at the first bad CRC / short frame, physically truncate the segment at
     the last whole record (the torn tail a mid-write crash leaves) and drop
     any later segments (they cannot be ordered past a gap);
  5. restore per-type fid counters and mutation-generation counters from the
     snapshot, then bump every type's generation once more — combined with
     the per-incarnation store epoch in the scheduler's cache keys, a
     recovered store can never serve a pre-crash cached plan.

Replay applies records via the public mutation methods, so device indexes,
stats batteries, metrics, and generation bumps all rebuild exactly as a live
mutation would have built them."""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

from geomesa_tpu.durability import snapshot as _snap
from geomesa_tpu.durability import wal as _wal


@dataclass
class RecoveryReport:
    """What recovery found and did (surfaced on /healthz + the CLI)."""

    recovered: bool = False
    snapshot_seq: int = 0
    snapshot_path: Optional[str] = None
    snapshots_rejected: int = 0
    replayed_records: int = 0
    truncated_bytes: int = 0
    torn_error: Optional[str] = None
    dropped_segments: int = 0
    apply_errors: int = 0
    last_seq: int = 0
    tmp_cleaned: int = 0
    duration_ms: float = 0.0
    types: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items()}


def _truncate(path: str, offset: int) -> int:
    """Physically cut a torn tail; returns bytes removed."""
    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(offset)
        fh.flush()
        os.fsync(fh.fileno())
    return max(0, size - offset)


def _apply_record(store, kind: str, payload: bytes) -> None:
    from geomesa_tpu.features.geometry import GeometryArray
    from geomesa_tpu.filter import ir

    if kind in ("append", "upsert"):
        meta = _wal.peek_meta(payload)
        t = meta["type"]
        _, table, _ = _wal.decode_table(payload, sft=store.schemas[t])
        if kind == "append":
            store._append(t, table)
        else:
            store.upsert(t, table)
        # continue the primary's fid sequence (records logged before this
        # meta field existed simply leave the counter alone)
        if "counter" in meta:
            store._counters[t] = max(store._counters.get(t, 0),
                                     int(meta["counter"]))
    elif kind == "remove":
        meta = _wal.decode_json(payload)
        store.remove_features(meta["type"],
                              ir.FidFilter(tuple(meta["fids"])))
    elif kind == "update":
        meta, _table, arrays = _wal.decode_table(payload)
        updates: dict = {}
        for name, wkts in meta.get("geoms", {}).items():
            updates[name] = GeometryArray.from_rows(list(wkts))
        updates.update(meta.get("scalars", {}))
        for name, vals in meta.get("string_lists", {}).items():
            updates[name] = list(vals)
        updates.update(arrays)
        store.update_features(meta["type"],
                              ir.FidFilter(tuple(meta["fids"])), updates)
    elif kind == "age_off":
        meta = _wal.decode_json(payload)
        store.age_off(meta["type"], now_ms=meta["now_ms"])
    elif kind == "create_schema":
        meta = _wal.decode_json(payload)
        store.create_schema(meta["type"], meta["spec"])
    elif kind == "remove_schema":
        store.remove_schema(_wal.decode_json(payload)["type"])
    elif kind == "update_schema":
        meta = _wal.decode_json(payload)
        store.update_schema(meta["type"], meta.get("add", ""),
                            meta.get("new_name"))
    else:
        raise ValueError(f"unknown WAL record kind {kind!r}")


def recover_into(store, path: str, name: str = "wal") -> RecoveryReport:
    """Reconstruct ``store`` (a fresh, empty TpuDataStore) from the
    durability layout at ``path``. Returns the report; the caller attaches
    the DurabilityManager afterwards with ``start_seq = report.last_seq+1``.
    """
    from geomesa_tpu import trace as _trace
    from geomesa_tpu.metrics import REGISTRY as _metrics

    t_start = time.perf_counter()
    report = RecoveryReport()
    with _trace.span("recovery", kind="recovery", dir=path):
        report.tmp_cleaned = _snap.clean_tmp(path)

        # newest snapshot that verifies; older ones are the fallback chain
        snap_types = None
        for seq, p in reversed(_snap.snapshot_dirs(path)):
            try:
                snap_seq, snap_types = _snap.load_snapshot(p)
            except (OSError, ValueError, KeyError):
                report.snapshots_rejected += 1
                continue
            report.snapshot_seq = snap_seq
            report.snapshot_path = p
            break
        if snap_types:
            for tname, entry in snap_types.items():
                store.create_schema(entry["sft"])
                if entry["table"] is not None:
                    store.load(tname, entry["table"])
                store._counters[tname] = entry["counter"]
                # continue the persisted generation sequence (monotonic
                # across incarnations; the epoch salt covers aliasing)
                store._generations[tname] = max(
                    store._generations.get(tname, 0), entry["generation"])

        # replay the WAL suffix
        last_applied = report.snapshot_seq
        wal_dirty = False
        segs = _wal.segments(os.path.join(path, "wal"), name)
        for i, seg in enumerate(segs):
            records, valid_end, error = _wal.scan_segment(seg)
            for seq, kind, payload, _off in records:
                if seq <= report.snapshot_seq:
                    continue
                if seq != last_applied + 1 and last_applied > report.snapshot_seq:
                    error = error or f"cross-segment gap at seq {seq}"
                    break
                try:
                    _apply_record(store, kind, payload)
                    report.replayed_records += 1
                except Exception:
                    report.apply_errors += 1
                    _metrics.inc("recovery.apply_errors")
                last_applied = seq
            if error is not None:
                report.torn_error = error
                report.truncated_bytes += _truncate(seg, valid_end)
                wal_dirty = True
                # nothing after a tear can be ordered — drop later segments
                for later in segs[i + 1:]:
                    try:
                        os.remove(later)
                        report.dropped_segments += 1
                    except OSError:
                        pass
                break
        report.last_seq = last_applied
        if wal_dirty:
            _metrics.inc("recovery.torn_truncations")
        _metrics.inc("recovery.records_replayed", report.replayed_records)

        # the recovery bump: no plan cached against any replayed generation
        # (or any pre-crash one) survives into serving
        with store._lock:
            for tname in list(store.schemas):
                store._bump_generation(tname)
        report.types = sorted(store.schemas)
        report.recovered = bool(snap_types) or report.replayed_records > 0 \
            or bool(segs)
    report.duration_ms = round((time.perf_counter() - t_start) * 1000, 3)
    return report
