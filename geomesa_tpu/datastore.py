"""DataStore facade — the framework entry point.

≙ GeoTools ``DataStoreFinder`` + ``GeoMesaDataStore``
(/root/reference/geomesa-index-api/.../geotools/GeoMesaDataStore.scala:49,
MetadataBackedDataStore.scala:123). The TPU store keeps GeoMesa's lifecycle:

  create_schema(sft)     — register the type, decide its indexes
  get_writer(type)       — batch feature writer (append); indexes build on
                           flush (bulk sort ≙ bulk ingest; incremental deltas
                           arrive with the live/streaming layer)
  query/count/explain    — plan + execute through QueryPlanner

Backends are factories keyed by params, mirroring the DataStoreFactorySpi
registry; the in-memory/TPU store registers as ``tpu`` (the moral slot of the
reference's in-memory CQEngine store — and the perf comparison target).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from geomesa_tpu import trace as _trace
from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import ir
from geomesa_tpu.index.api import QueryResult
from geomesa_tpu.index.planner import QueryPlanner
from geomesa_tpu.index.spatial import INDEX_CLASSES, FullScanIndex

_INDEX_BY_NAME = {c.name: c for c in INDEX_CLASSES}

# per-process store-incarnation counter: every TpuDataStore instance gets a
# unique epoch (pid + counter) that salts the serving-scheduler cache keys,
# so plans cached for one incarnation are unreachable from any other — even
# one restored with identical generation counters
import itertools as _itertools
import os as _os

_EPOCHS = _itertools.count(1)


def _next_epoch() -> str:
    return f"{_os.getpid():x}d{next(_EPOCHS)}"


class FeatureWriter:
    """Batch appender (≙ GeoMesaFeatureWriter append mode). Collects rows
    host-side; ``flush`` builds the columnar table and (re)builds indexes —
    the precompute-all-mutations-then-write atomicity discipline
    (IndexAdapter.scala:139-150) becomes build-then-swap."""

    def __init__(self, store: "TpuDataStore", type_name: str):
        self.store = store
        self.type_name = type_name
        self.sft = store.schemas[type_name]
        self._rows: List[dict] = []
        self._fids: List[Optional[str]] = []
        self._vis: List[str] = []

    def write(self, fid: Optional[str] = None, vis: str = "",
              **attributes) -> str:
        """``vis``: visibility expression for this feature (≙ the mutation
        visibility of geomesa-security; '' = public)."""
        missing = [a.name for a in self.sft.attributes if a.name not in attributes]
        if missing:
            raise ValueError(f"Missing attributes {missing}")
        self._rows.append(attributes)
        if fid is None:
            fid = f"{self.type_name}.{self.store._fid_counter(self.type_name)}"
        self._fids.append(fid)
        self._vis.append(vis)
        return fid

    def flush(self) -> None:
        if not self._rows:
            return
        data: Dict[str, list] = {a.name: [] for a in self.sft.attributes}
        for row in self._rows:
            for a in self.sft.attributes:
                data[a.name].append(row[a.name])
        cols: Dict[str, object] = {}
        for a in self.sft.attributes:
            cols[a.name] = GeometryArray.from_rows(data[a.name]) \
                if a.is_geometry else data[a.name]
        vis = self._vis if any(self._vis) else None
        batch = FeatureTable.build(self.sft, cols, fids=self._fids,
                                   visibilities=vis)
        self.store._append(self.type_name, batch)
        self._rows, self._fids, self._vis = [], [], []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.flush()


class TpuDataStore:
    """In-process TPU-backed datastore.

    Concurrency model (≙ the reference's immutable-plans + concurrent-store
    discipline, SURVEY.md §5): mutators (_append/flush/update_*/remove_*)
    serialize on a store-wide writer lock and follow build-then-swap — new
    tables/planners are constructed fully before any shared reference is
    reassigned, and existing FeatureTable/QueryPlanner objects are never
    mutated in place. Readers never take the lock for query execution; they
    grab one consistent (planner, delta) snapshot via ``_snapshot`` (a brief
    lock acquire, so a mid-flush reader can't pair a pre-flush planner with
    a post-flush delta and under/double-count) and then work purely on the
    captured objects. Exercised by tests/test_web.py's concurrent
    ingest+query stress test through the REST server's thread pool."""

    def __init__(self, params: Optional[dict] = None):
        import threading

        from geomesa_tpu import obs as _obs
        from geomesa_tpu.metrics import register_device_gauges
        register_device_gauges()
        _obs.install()
        self._lock = threading.RLock()
        self.params = params or {}
        self.schemas: Dict[str, SimpleFeatureType] = {}
        self.tables: Dict[str, FeatureTable] = {}
        self.planners: Dict[str, QueryPlanner] = {}
        # LSM delta tier: recent appends held as a small host-side run that
        # queries merge in exactly; flushed into the device-indexed main
        # table when it grows past the flush threshold (≙ the Lambda store's
        # hot tier shadowing the cold tier, LambdaDataStore.scala:180)
        self.deltas: Dict[str, Optional[FeatureTable]] = {}
        self._stats: Dict[str, object] = {}
        self._counters: Dict[str, int] = {}
        self._interceptors: Dict[str, list] = {}
        # per-type mutation generation (serve-path cache invalidation): every
        # ingest/flush/age-off/update/delete/schema-change bumps it, so a
        # plan or cover cached against generation g is unreachable once the
        # data it described has changed. Monotonic per NAME — it survives
        # remove_schema so a re-created type can't resurrect stale plans.
        self._generations: Dict[str, int] = {}
        # online build-then-swap reindex bookkeeping: per-type status dicts
        # plus the background worker threads (joinable by tests/shutdown)
        self._reindex_status: Dict[str, dict] = {}
        self._reindex_threads: Dict[str, object] = {}
        # incarnation epoch: salts scheduler cache keys (see _next_epoch)
        self.epoch = _next_epoch()
        self._scheduler = None  # lazy QueryScheduler (serve/scheduler.py)
        # audit trail (≙ AuditWriter): params {"audit": True | "path.jsonl"}
        audit_param = self.params.get("audit")
        if audit_param:
            from geomesa_tpu.index.guards import AuditWriter
            self.audit = AuditWriter(
                audit_param if isinstance(audit_param, str) else None,
                max_bytes=self.params.get("audit.max_bytes"))
        else:
            self.audit = None
        # durability (WAL + snapshots + recovery): params
        # {"durability": "<dir>"} or TpuDataStore.open(dir). Attaching to a
        # dir with an existing layout recovers into this store first.
        self.durability = None
        self.recovery_report = None
        # replication role object (replication/): a LogShipper when this
        # store is a fleet primary, a Follower when it is a read replica,
        # None standalone — /healthz and the fence checks read it
        self.replication = None
        dur_dir = self.params.get("durability")
        if dur_dir:
            from geomesa_tpu.durability.manager import attach as _attach
            _attach(self, dur_dir, params=self.params)

    # -- factory SPI --------------------------------------------------------

    @classmethod
    def can_process(cls, params: dict) -> bool:
        return params.get("backend", "tpu") == "tpu"

    @classmethod
    def create(cls, params: dict) -> "TpuDataStore":
        return cls(params)

    @classmethod
    def open(cls, path: str, params: Optional[dict] = None) -> "TpuDataStore":
        """Open (or create) a durable store at ``path``: crash recovery runs
        when a WAL/snapshot layout exists (newest valid snapshot + WAL
        suffix replay, torn tail truncated), and every subsequent mutation
        is write-ahead logged. ``store.recovery_report`` says what recovery
        did; ``store.durability`` exposes WAL/snapshot state."""
        p = dict(params or {})
        p["durability"] = path
        return cls(p)

    def close(self) -> None:
        """Flush + release durability resources (WAL fsync, background
        syncer), stop the query scheduler, and stop a primary-role log
        shipper (a Follower owns its store, not vice versa — it closes
        itself and then this store). Idempotent."""
        repl = self.replication
        if repl is not None and getattr(repl, "role", "") == "primary":
            repl.close()
        with self._lock:
            sched, self._scheduler = self._scheduler, None
        if sched is not None:
            sched.shutdown()
        if self.durability is not None:
            self.durability.close()

    # -- durability plumbing -------------------------------------------------

    def _wal_json(self, kind: str, meta: dict, rows: int = 0) -> None:
        """Log a metadata mutation record (callers hold the store lock;
        log-then-apply). No-op without durability or during replay."""
        if self.durability is not None:
            self.durability.log_json(kind, meta, rows=rows)

    def _wal_table(self, kind: str, meta: dict, table=None, arrays=None,
                   rows: int = 0) -> None:
        if self.durability is not None:
            self.durability.log_table(kind, meta, table=table, arrays=arrays,
                                      rows=rows)

    def _dur_tick(self) -> None:
        """Post-mutation hook, called AFTER the store lock is released:
        writes an incremental snapshot when thresholds are crossed."""
        if self.durability is not None:
            self.durability.maybe_snapshot()

    # -- schema lifecycle ---------------------------------------------------

    def create_schema(self, sft: Union[SimpleFeatureType, str],
                      spec: Optional[str] = None) -> SimpleFeatureType:
        if isinstance(sft, str):
            sft = SimpleFeatureType.from_spec(sft, spec or "")
        sft.feature_expiry  # validate up front, not on the first write
        with self._lock:
            if sft.name in self.schemas:
                raise ValueError(f"Schema {sft.name} already exists")
            self._wal_json("create_schema",
                           {"type": sft.name, "spec": sft.to_spec()})
            self.schemas[sft.name] = sft
            self.tables[sft.name] = None
        return sft

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self.schemas[type_name]

    def get_type_names(self) -> List[str]:
        return list(self.schemas)

    def remove_schema(self, type_name: str) -> None:
        with self._lock:
            self._wal_json("remove_schema", {"type": type_name})
            self._remove_schema_locked(type_name)

    def _remove_schema_locked(self, type_name: str) -> None:
        # _interceptors/_counters included: a re-created type of the same
        # name must not inherit the old type's guards or fid sequence.
        # _generations deliberately excluded (bumped instead): cached
        # plans must not survive a drop/re-create of the same name.
        self._bump_generation(type_name)
        for d in (self.schemas, self.tables, self.planners, self._stats,
                  self.deltas, self._counters, self._interceptors):
            d.pop(type_name, None)

    # -- writes -------------------------------------------------------------

    def get_writer(self, type_name: str) -> FeatureWriter:
        if type_name not in self.schemas:
            raise KeyError(type_name)
        return FeatureWriter(self, type_name)

    def load(self, type_name: str, table: FeatureTable,
             stats_cached: Optional[dict] = None) -> None:
        """Bulk load a prebuilt columnar table (the fast ingest path).
        ``stats_cached`` restores checkpointed sketches instead of
        re-observing (io.checkpoint)."""
        self._append(type_name, table, stats_cached)

    def _append(self, type_name: str, batch: FeatureTable,
                stats_cached: Optional[dict] = None) -> None:
        """Append path with LSM tiering: small batches land in the host-side
        delta run (cost ~ O(batch), not O(table)); the main device index
        rebuilds only on the first load or when the delta crosses the flush
        threshold. Queries merge main + delta exactly (see count/query)."""
        with self._lock:
            self._append_locked(type_name, batch, stats_cached)
        self._dur_tick()

    def _append_locked(self, type_name, batch, stats_cached=None) -> None:
        # WAL first (log-then-apply): the batch as handed in — replay runs
        # it through this same path, so write-path age-off re-applies there.
        # The fid counter rides in the meta so a replica/recovered store
        # continues the primary's fid sequence instead of restarting at 0.
        self._wal_table("append", {"type": type_name, "rows": len(batch),
                                   "counter": self._counters.get(type_name,
                                                                 0)},
                        table=batch, rows=len(batch))
        self._append_apply(type_name, batch, stats_cached)

    def _append_apply(self, type_name, batch, stats_cached=None) -> None:
        from geomesa_tpu.metrics import REGISTRY as _metrics
        _metrics.inc("ingest.features", len(batch))
        # every append changes query results (even a delta-tier landing), so
        # the serving caches must miss from here on
        self._bump_generation(type_name)
        # already-expired incoming rows never land (O(batch) mask; the
        # reference's write-path expiry check)
        batch, _ = self._apply_age_off(type_name, batch)
        current = self.tables.get(type_name)
        if current is None:
            self.tables[type_name] = batch
            self.deltas[type_name] = None
            with _trace.span("ingest.index_build", kind="aggregate"):
                self._rebuild_indexes(type_name, stats_cached)
            return
        delta = self.deltas.get(type_name)
        merged_delta = batch if delta is None else FeatureTable.concat([delta, batch])
        from geomesa_tpu import config
        frac = config.LSM_MAX_FRACTION.get()
        threshold = max(50_000, int(frac * len(current)))
        if stats_cached is not None or len(merged_delta) > threshold:
            # flush-through (large batch, or a checkpoint restore that must
            # land its cached sketches against the merged table)
            _metrics.inc("ingest.flushes")
            self.deltas[type_name] = None
            n_old = len(current)
            merged = FeatureTable.concat([current, merged_delta])
            merged, n_exp = self._apply_age_off(type_name, merged)
            if n_exp:
                # checkpointed sketches describe rows age-off just dropped —
                # re-observe rather than restore an overcounting battery
                stats_cached = None
            with _trace.span("ingest.index_build", kind="aggregate"):
                # age-off drops invalidate the resident sorted run's row
                # identity — only a clean append merges incrementally
                if n_exp or not self._merge_rebuild(type_name, merged, n_old,
                                                    stats_cached):
                    self.tables[type_name] = merged
                    self._rebuild_indexes(type_name, stats_cached)
        else:
            _metrics.inc("ingest.delta_appends")
            # stat sketches stay main-table-only while a delta is pending
            # (GeoMesaStats.update REPLACES the battery — re-observing just
            # the batch would swap whole-table estimates for batch-only
            # ones); the estimator drifts by at most the flush threshold
            # (~2%), and the next flush re-observes everything
            self.deltas[type_name] = merged_delta

    def flush(self, type_name: str) -> None:
        """Merge the delta run into the main device index (≙ the Lambda
        tier's persistence flush). No-op when the delta is empty."""
        with self._lock:
            delta = self.deltas.get(type_name)
            if delta is None:
                return
            with _trace.span("ingest.flush", kind="aggregate",
                             type=type_name):
                self._bump_generation(type_name)
                self.deltas[type_name] = None
                current = self.tables[type_name]
                n_old = len(current)
                merged = FeatureTable.concat([current, delta])
                # dtg age-off rides the flush (≙ compaction-time age-off
                # iterators): rows whose TTL lapsed since ingest drop here
                merged, n_exp = self._apply_age_off(type_name, merged)
                # a pure append merges the sorted delta run into the
                # resident sorted run; age-off drops force a full rebuild
                if n_exp or not self._merge_rebuild(type_name, merged,
                                                    n_old):
                    self.tables[type_name] = merged
                    self._rebuild_indexes(type_name)

    def upsert(self, type_name: str, batch: FeatureTable) -> int:
        """Atomic put-by-fid: remove existing rows whose fids collide with
        the batch, then append it — ONE mutation under ONE lock hold, logged
        as ONE WAL record. Idempotent: re-applying the same batch (a crash
        replay, a retried hot-tier persist) converges to the same state
        instead of losing or double-counting rows. ≙ the Lambda tier's
        hot→cold move, which the reference performs as delete+write against
        the persistent store. Returns rows written."""
        if type_name not in self.schemas:
            raise KeyError(type_name)
        with self._lock, _trace.span("ingest.upsert", kind="aggregate",
                                     type=type_name):
            self._wal_table("upsert", {"type": type_name,
                                       "rows": len(batch),
                                       "counter": self._counters.get(
                                           type_name, 0)},
                            table=batch, rows=len(batch))
            self._upsert_locked(type_name, batch)
        self._dur_tick()
        return len(batch)

    def _upsert_locked(self, type_name: str, batch: FeatureTable) -> None:
        from geomesa_tpu.metrics import REGISTRY as _metrics
        _metrics.inc("ingest.upserts")
        batch_fids = np.asarray(batch.fids, dtype=object)
        # collisions within the host-side delta run purge in place (cheap)
        delta = self.deltas.get(type_name)
        if delta is not None:
            ddup = np.isin(np.asarray(delta.fids, dtype=object), batch_fids)
            if ddup.any():
                keep = np.flatnonzero(~ddup)
                self.deltas[type_name] = delta.take(keep) if len(keep) \
                    else None
        current = self.tables.get(type_name)
        main_dup = None
        if current is not None and len(current):
            main_dup = np.isin(np.asarray(current.fids, dtype=object),
                               batch_fids)
            if not main_dup.any():
                main_dup = None
        if main_dup is None:
            # no main-table collisions: ride the ordinary LSM append path —
            # a small hot-tier persist lands in the delta run and must NOT
            # rebuild the cold device index (tests/test_lsm.py)
            self._append_apply(type_name, batch)
            return
        self._bump_generation(type_name)
        current = current.take(np.flatnonzero(~main_dup))
        delta = self.deltas.get(type_name)
        if delta is not None:
            current = FeatureTable.concat([current, delta])
            self.deltas[type_name] = None
        merged = FeatureTable.concat([current, batch]) \
            if len(current) else batch
        merged, _ = self._apply_age_off(type_name, merged)
        self.tables[type_name] = merged
        self._rebuild_indexes(type_name)

    def _apply_age_off(self, type_name: str, table: Optional[FeatureTable],
                       now_ms: Optional[int] = None):
        """(surviving table, n_expired) under the type's
        ``geomesa.feature.expiry`` TTL; no-op without one."""
        sft = self.schemas[type_name]
        exp = sft.feature_expiry
        if exp is None or table is None or len(table) == 0:
            return table, 0
        import time as _time
        attr, ttl_ms = exp
        now = int(_time.time() * 1000) if now_ms is None else int(now_ms)
        vals = np.asarray(table.columns[attr], dtype=np.int64)
        # null dates (NaT → int64 min) never expire — age-off drops only
        # rows whose date actually lapsed, like the reference iterators
        keep = (vals > now - ttl_ms) | (vals == np.iinfo(np.int64).min)
        n_exp = int(len(keep) - keep.sum())
        if n_exp == 0:
            return table, 0
        from geomesa_tpu.metrics import REGISTRY as _metrics
        _metrics.inc("ingest.aged_off", n_exp)
        return table.take(np.flatnonzero(keep)), n_exp

    def age_off(self, type_name: str, now_ms: Optional[int] = None) -> int:
        """Force an age-off compaction of the main table + delta (≙ running
        the reference's DtgAgeOffIterator at major compaction): drops every
        row whose ``geomesa.feature.expiry`` TTL has lapsed and rebuilds the
        device index if anything dropped. Returns the number removed.
        ``now_ms`` overrides the clock (maintenance jobs, tests)."""
        import time as _time
        # resolve the clock BEFORE logging so the WAL record replays with
        # the exact cutoff this compaction used (deterministic recovery)
        now = int(_time.time() * 1000) if now_ms is None else int(now_ms)
        with self._lock, _trace.span("ingest.age_off", kind="aggregate",
                                     type=type_name):
            self._wal_json("age_off", {"type": type_name, "now_ms": now})
            table = self.tables.get(type_name)
            delta = self.deltas.get(type_name)
            # merge the delta WITHOUT flush(): its age-off pass runs on the
            # real clock and would both ignore now_ms and hide its removals
            # from this method's returned count
            if delta is not None:
                table = FeatureTable.concat([table, delta])
            table2, n = self._apply_age_off(type_name, table, now)
            if n or delta is not None:
                self._bump_generation(type_name)
                self.deltas[type_name] = None
                self.tables[type_name] = table2
                self._rebuild_indexes(type_name)
        self._dur_tick()
        return n

    def _snapshot(self, type_name: str):
        """One consistent (planner, delta) pair. The brief lock acquire is
        the whole reader-side protocol: both refs are captured atomically
        w.r.t. flush/append swaps, then the query runs lock-free on the
        captured (immutable) objects."""
        with self._lock:
            return self._main_planner(type_name), self.deltas.get(type_name)

    def _delta_rows(self, delta: Optional[FeatureTable], f,
                    auths) -> "np.ndarray":
        """Matching row indices WITHIN a snapshotted delta run (host f64
        evaluation — the delta is bounded small, so brute force is exact and
        cheap). Takes the delta table itself, not the type name: readers must
        evaluate the SAME delta object their snapshot captured, never a
        re-read that a concurrent flush could have swapped."""
        import numpy as np

        from geomesa_tpu.filter.evaluate import evaluate as _evaluate
        from geomesa_tpu.filter.parser import parse_ecql

        if delta is None:
            return np.empty(0, dtype=np.int64)
        fir = parse_ecql(f) if isinstance(f, str) else f
        if isinstance(fir, ir.FidFilter):
            fids = set(fir.fids)
            rows = np.array([i for i, fid in enumerate(delta.fids)
                             if fid in fids], dtype=np.int64)
        else:
            rows = np.flatnonzero(_evaluate(fir, delta))
        if auths is not None and delta.visibility is not None and len(rows):
            from geomesa_tpu.security.visibility import allowed_codes
            allowed = allowed_codes(delta.visibility.vocab, auths)
            rows = rows[np.isin(delta.visibility.codes[rows], allowed)]
        return rows

    def _build_planner(self, type_name: str, table: FeatureTable,
                       stats_cached: Optional[dict] = None):
        """Construct a fresh (planner, stats) pair over ``table`` WITHOUT
        touching store state — the pure build half of build-then-swap. Safe
        to run off-lock against a captured table (background reindex); the
        caller installs the result under the lock."""
        from geomesa_tpu.stats.store import GeoMesaStats

        sft = self.schemas[type_name]
        names = sft.configured_indices
        indexes: List[object] = []
        for c in INDEX_CLASSES:
            if names is not None and c.name not in names:
                continue
            if c.supports(sft):
                indexes.append(c(sft, table))
                break  # one primary spatial index (others on demand later)
        from geomesa_tpu.index.attribute import AttributeIndex, indexed_attributes
        for attr in indexed_attributes(sft):
            indexes.append(AttributeIndex(sft, table, attr))
        indexes.append(FullScanIndex(sft, table))
        # fresh battery per rebuild (true build-then-swap): re-observing into
        # the SHARED GeoMesaStats would let a lock-free reader's snapshotted
        # planner see a half-populated sketch battery mid-rebuild
        stats = GeoMesaStats(sft)
        timeout = sft.user_data.get("geomesa.query.timeout")
        planner = QueryPlanner(
            sft, table, indexes, stats=stats,
            interceptors=self._interceptors.setdefault(type_name, []),
            audit=self.audit,
            timeout_ms=float(timeout) if timeout else None)
        stats.planner = planner
        if stats_cached is not None:
            stats.cached = stats_cached  # checkpoint restore
        else:
            stats.update(table)  # ≙ statUpdater flush on write
        return planner, stats

    def _install_planner(self, type_name: str, table: FeatureTable,
                         planner, stats) -> None:
        """Swap a fully-built planner in (callers hold the lock)."""
        self._stats[type_name] = stats
        self.planners[type_name] = planner
        from geomesa_tpu.index import prune as _prune
        from geomesa_tpu.metrics import REGISTRY as _metrics
        _metrics.set_gauge(f"store.rows.{type_name}", len(table))
        _metrics.set_gauge(f"store.index_blocks.{type_name}",
                           -(-len(table) // _prune.BLOCK_SIZE))

    def _rebuild_indexes(self, type_name: str,
                         stats_cached: Optional[dict] = None) -> None:
        table = self.tables[type_name]
        planner, stats = self._build_planner(type_name, table, stats_cached)
        self._install_planner(type_name, table, planner, stats)

    def _merge_rebuild(self, type_name: str, merged: FeatureTable,
                       n_old: int,
                       stats_cached: Optional[dict] = None) -> bool:
        """Incremental flush: merge the freshly-sorted delta run into each
        resident index's already-sorted run (index.merge_from) instead of
        re-sorting the whole table. Returns False when ineligible — caller
        falls back to the full rebuild. Callers hold the lock and have NOT
        yet installed ``merged`` into self.tables."""
        from geomesa_tpu import config
        if not config.MERGE_BUILD.get():
            return False
        n_new = len(merged)
        n_delta = n_new - n_old
        if n_old <= 0 or n_delta <= 0:
            return False
        if n_delta > config.MERGE_MAX_FRACTION.get() * max(1, n_old):
            # big deltas amortize better through a full sort — but a flush
            # shape that breaches EVERY time means the incremental path is
            # dead weight, so the fallback is counted and flight-logged for
            # the doctor's merge_fraction_breach cause
            from geomesa_tpu.metrics import REGISTRY as _m
            _m.inc("ingest.merge_fraction_breaches")
            _m.inc(f"ingest.merge_fraction_breaches.{type_name}")
            from geomesa_tpu.obs.flight import RECORDER as _rec
            _rec.record({"kind": "reindex", "type": type_name,
                         "phase": "merge_fraction_breach",
                         "delta_fraction": round(n_delta / max(1, n_old), 3)})
            return False
        old_planner = self.planners.get(type_name)
        current = self.tables.get(type_name)
        if old_planner is None or current is None or len(current) != n_old:
            return False
        sft = self.schemas[type_name]
        from geomesa_tpu.index.attribute import indexed_attributes
        if indexed_attributes(sft):
            # attribute indexes sort by value, not append order — a suffix
            # delta is not a sorted run for them, so no incremental path
            return False
        old_indexes = getattr(old_planner, "indexes", None) or []
        for old in old_indexes:
            if getattr(type(old), "merge_from", None) is None:
                return False
            if getattr(old, "table", None) is not current:
                return False  # stale planner (shouldn't happen under lock)
        from geomesa_tpu.metrics import REGISTRY as _metrics
        from geomesa_tpu.stats.store import GeoMesaStats
        with _trace.span("ingest.merge_build", kind="aggregate",
                         type=type_name):
            indexes = [type(old).merge_from(old, merged, n_old)
                       for old in old_indexes]
            stats = GeoMesaStats(sft)
            timeout = sft.user_data.get("geomesa.query.timeout")
            planner = QueryPlanner(
                sft, merged, indexes, stats=stats,
                interceptors=self._interceptors.setdefault(type_name, []),
                audit=self.audit,
                timeout_ms=float(timeout) if timeout else None)
            stats.planner = planner
            old_stats = self._stats.get(type_name)
            if stats_cached is not None:
                stats.cached = stats_cached  # checkpoint restore
            elif old_stats is not None and \
                    getattr(old_stats, "cached", None) is not None:
                # carry the pre-flush battery: it under-describes only the
                # delta rows (≤ MERGE_MAX_FRACTION) — the same bounded drift
                # readers already accept while a delta run is pending
                stats.cached = old_stats.cached
            else:
                stats.update(merged)
            self.tables[type_name] = merged
            self._install_planner(type_name, merged, planner, stats)
        _metrics.inc("ingest.merge_builds")
        return True

    # -- online build-then-swap reindex --------------------------------------

    def reindex(self, type_name: str, background: bool = True):
        """Rebuild the type's indexes OFF the serving path and atomically
        swap the new generation in (build-then-swap made explicit — the
        maintenance analogue of the reference's offline reindex jobs).
        Readers keep querying the old planner until the install instant;
        the generation bump invalidates every (epoch, type, generation)-
        keyed serving cache for free. ``background=True`` returns
        immediately with a status dict; the worker thread is joinable via
        ``self._reindex_threads[type_name]``."""
        if type_name not in self.schemas:
            raise KeyError(type_name)
        if not background:
            self._reindex_run(type_name)
            return self.reindex_status(type_name)
        import threading
        with self._lock:
            t = self._reindex_threads.get(type_name)
            if t is not None and t.is_alive():
                return self.reindex_status(type_name)  # already running
            self._reindex_status[type_name] = {"state": "running",
                                               "attempts": 0}
            t = threading.Thread(target=self._reindex_run,
                                 args=(type_name,),
                                 name=f"reindex-{type_name}", daemon=True)
            self._reindex_threads[type_name] = t
        t.start()
        return self.reindex_status(type_name)

    def reindex_status(self, type_name: str) -> dict:
        with self._lock:
            st = dict(self._reindex_status.get(type_name,
                                               {"state": "idle"}))
            t = self._reindex_threads.get(type_name)
            st["running"] = bool(t is not None and t.is_alive())
            return st

    def _reindex_run(self, type_name: str, max_retries: int = 3) -> None:
        import time as _time

        from geomesa_tpu import config
        from geomesa_tpu.metrics import REGISTRY as _metrics
        from geomesa_tpu.obs.flight import RECORDER as _flight
        from geomesa_tpu.obs.profiling import PROGRESS as _progress
        throttle = max(0.0, config.REINDEX_THROTTLE_MS.get()) / 1000.0
        status = {"state": "running", "attempts": 0}
        with self._lock:
            self._reindex_status[type_name] = status
        t0 = _time.perf_counter()
        try:
            for attempt in range(1, max_retries + 1):
                status["attempts"] = attempt
                # land any pending delta first so the rebuilt generation
                # covers every row readers can currently see
                self.flush(type_name)
                with self._lock:
                    base_table = self.tables.get(type_name)
                if base_table is None:
                    status["state"] = "failed"
                    status["error"] = "no table"
                    return
                _flight.record({"kind": "reindex", "type": type_name,
                                "phase": "build_started",
                                "rows": len(base_table),
                                "attempt": attempt})
                if throttle:
                    _time.sleep(throttle)  # yield to serving traffic
                # the expensive part runs entirely OFF-lock against the
                # captured immutable table — queries proceed unimpeded
                planner, stats = self._build_planner(type_name, base_table)
                if throttle:
                    _time.sleep(throttle)
                with self._lock:
                    if self.tables.get(type_name) is not base_table:
                        # a concurrent flush/upsert swapped the table while
                        # we built — this generation describes stale rows;
                        # discard and retry against the new table
                        _metrics.inc("reindex.aborts")
                        _metrics.inc(f"reindex.aborts.{type_name}")
                        _flight.record({"kind": "reindex",
                                        "type": type_name,
                                        "phase": "aborted",
                                        "attempt": attempt})
                        continue
                    with _progress.phase("swap_install",
                                         rows=len(base_table),
                                         op="reindex",
                                         type_name=type_name):
                        self._install_planner(type_name, base_table,
                                              planner, stats)
                        self._bump_generation(type_name)
                    gen = self._generations.get(type_name, 0)
                status["state"] = "installed"
                status["generation"] = gen
                status["rows"] = len(base_table)
                status["seconds"] = round(_time.perf_counter() - t0, 3)
                _metrics.inc("reindex.installs")
                _flight.record({"kind": "reindex", "type": type_name,
                                "phase": "installed", "generation": gen,
                                "rows": len(base_table),
                                "attempt": attempt,
                                "seconds": status["seconds"]})
                # ship the rebuilt generation fleet-wide: a fresh snapshot
                # makes follower catch-up land it byte-identically
                if self.durability is not None and \
                        config.REINDEX_SNAPSHOT.get():
                    try:
                        self.durability.snapshot()
                    except Exception:  # noqa: BLE001 - snapshot is advisory
                        pass
                return
            status["state"] = "aborted"
            status["seconds"] = round(_time.perf_counter() - t0, 3)
        except Exception as e:  # noqa: BLE001 - surfaced via status
            status["state"] = "failed"
            status["error"] = f"{type(e).__name__}: {e}"
            status["seconds"] = round(_time.perf_counter() - t0, 3)
            _metrics.inc("reindex.failures")
            _metrics.inc(f"reindex.failures.{type_name}")
            _flight.record({"kind": "reindex", "type": type_name,
                            "phase": "failed", "error": status["error"]})

    def _fid_counter(self, type_name: str) -> int:
        with self._lock:  # read-modify-write: two writers must never share a fid
            c = self._counters.get(type_name, 0)
            self._counters[type_name] = c + 1
            return c

    # -- serve-path cache generation ----------------------------------------

    def _bump_generation(self, type_name: str) -> None:
        """Advance the type's mutation generation (callers hold the lock)."""
        self._generations[type_name] = self._generations.get(type_name, 0) + 1

    def generation(self, type_name: str) -> int:
        """Current mutation generation — the serving caches' invalidation
        token (≙ the reference's metadata/stats cache expiry, made exact)."""
        with self._lock:
            return self._generations.get(type_name, 0)

    def _sched_snapshot(self, type_name: str):
        """(planner, delta, generation, epoch) captured atomically for the
        query scheduler — the scheduler-side twin of ``_snapshot``. The
        epoch salts cache keys so plans cached against a prior store
        incarnation (same name, same restored generation) never alias."""
        with self._lock:
            return (self._main_planner(type_name),
                    self.deltas.get(type_name),
                    self._generations.get(type_name, 0),
                    self.epoch)

    def scheduler(self):
        """The store's micro-batching query scheduler (lazily started; one
        per store). Concurrent counts submitted here coalesce into fused
        batched device dispatches — see serve/scheduler.py. A scheduler
        whose worker threads died (fault injection, a bug) is replaced
        with a fresh one on next access — outstanding futures were already
        failed with a structured error by the crash handler."""
        with self._lock:
            if self._scheduler is not None and not self._scheduler.healthy():
                from geomesa_tpu.metrics import REGISTRY as _metrics
                _metrics.inc("scheduler.restarts")
                self._scheduler.shutdown(timeout=0.1)
                self._scheduler = None
            if self._scheduler is None:
                from geomesa_tpu.serve.scheduler import (QueryScheduler,
                                                         StoreBinding)
                self._scheduler = QueryScheduler(StoreBinding(self))
            return self._scheduler

    def count_many(self, type_name: str, filters,
                   auths: Optional[list] = None,
                   deadline_ms: Optional[float] = None,
                   priority: str = "interactive",
                   tenant: Optional[str] = None) -> List[int]:
        """Counts for many filters through the scheduler: compatible queries
        fuse into single batched device dispatches; repeated/parameterized
        filters hit the plan/cover caches. Order-preserving. ``deadline_ms``
        bounds every count in the set; ``priority`` classes the work for
        admission control ('interactive' | 'batch'); ``tenant`` labels it
        for workload analytics/metering (auths-derived when omitted)."""
        return self.scheduler().count_many(type_name, filters, auths=auths,
                                           deadline_ms=deadline_ms,
                                           priority=priority, tenant=tenant)

    def count_future(self, type_name: str, f: Union[str, ir.Filter] = "INCLUDE",
                     auths: Optional[list] = None,
                     deadline_ms: Optional[float] = None,
                     priority: str = "interactive"):
        """Async count: submit to the scheduler and return the Request
        handle (``.result()`` blocks; ``.future`` is a concurrent.futures
        Future) — the serving-path analogue of PreparedQuery.count_async."""
        return self.scheduler().submit(type_name, f, auths=auths,
                                       deadline_ms=deadline_ms,
                                       priority=priority)

    def count_coalesced(self, type_name: str,
                        f: Union[str, ir.Filter] = "INCLUDE",
                        auths: Optional[list] = None,
                        deadline_ms: Optional[float] = None,
                        priority: str = "interactive",
                        tenant: Optional[str] = None) -> int:
        """Count via the scheduler when serving coalescing is enabled
        (GEOMESA_TPU_SCHEDULER / params {'scheduler': False}); otherwise the
        direct per-request path. The web /count route calls this, so
        concurrent HTTP requests share device dispatches — and propagate
        their deadline/priority/tenant envelope into the scheduler."""
        from geomesa_tpu import config
        if not config.SCHED_ENABLED.get() \
                or self.params.get("scheduler") is False:
            return self.count(type_name, f, auths=auths,
                              deadline_ms=deadline_ms)
        return self.scheduler().count(type_name, f, auths=auths,
                                      deadline_ms=deadline_ms,
                                      priority=priority, tenant=tenant)

    # -- queries ------------------------------------------------------------

    def planner(self, type_name: str) -> QueryPlanner:
        """The type's QueryPlanner over a fully-merged view: any pending
        delta run flushes first, so external consumers (processes, exports,
        aggregation helpers) always see exact state. Datastore-level
        count/query merge the delta inline instead and never force a flush."""
        with self._lock:
            self.flush(type_name)
            return self._main_planner(type_name)

    def _main_planner(self, type_name: str) -> QueryPlanner:
        if type_name not in self.planners:
            if self.tables.get(type_name) is None:
                raise ValueError(f"No data written to {type_name}")
        return self.planners[type_name]

    def cluster_scan(self, type_name: str):
        """ClusterScan over the type's primary index: on an active
        multi-process cluster the (locally-held, key-range-partitioned)
        index columns assemble into process-spanning global arrays —
        counts/density psum to the exact global answer, selects merge in
        rank order. Single-process it is an ordinary DistributedScan
        over the local mesh. The shard layout registers on /cluster."""
        from geomesa_tpu.cluster.exec import ClusterScan
        from geomesa_tpu.cluster.runtime import runtime
        from geomesa_tpu.cluster.table import ClusterShardedTable
        rt = runtime()
        idx = self.planner(type_name).indexes[0]
        host_cols = {k: np.asarray(v)
                     for k, v in idx.device.columns.items()}
        st = ClusterShardedTable.from_local_columns(rt, host_cols)
        rt.register_table(type_name, st.layout.summary())
        return ClusterScan(st)

    def query(self, type_name: str, f: Union[str, ir.Filter] = "INCLUDE",
              hints: Optional[dict] = None, auths: Optional[list] = None,
              deadline_ms: Optional[float] = None):
        """Run a query; ``hints`` switch the result form exactly like the
        reference's QueryHints (conf/QueryHints.scala — DENSITY_*/BIN_*/
        STATS_*/SAMPLING keys):

          hints["density"] = {"bbox": (..), "width": W, "height": H,
                              "weight": attr?}        → DensityGrid
          hints["bin"]     = {"track": attr, "label": attr?, "sort": bool}
                                                       → packed BIN records
          hints["stats"]   = stat spec string          → Stat sketch
          hints["sample"]  = n | {"n": n, "by": attr?} → sampled QueryResult

        Result-shaping hints compose on the plain path (≙ sort/maxFeatures/
        transform/reprojection of QueryPlanner.runQuery:56-94):

          hints["sort"]      = attr | "-attr" | [specs]   (stable, major-first)
          hints["limit"]     = n                          (applied pre-hydration)
          hints["transform"] = ["attr", "out=expr(...)"]  (projected type)
          hints["crs"]       = "EPSG:3857"                (output reprojection)
        """
        from geomesa_tpu.serve.resilience import deadline as _rdl
        with _trace.trace("query.features", type=type_name, filter=str(f)), \
                _rdl.scope(deadline_ms):
            return self._query_impl(type_name, f, hints, auths)

    def _query_impl(self, type_name, f, hints, auths):
        if not hints:
            planner, delta = self._snapshot(type_name)
            res = planner.query(f, auths=auths)
            if delta is None:
                return res
            drows = self._delta_rows(delta, f, auths)
            # stacked row space: delta rows ride above the main table
            # (QueryResult.indices document this via the plan's explain;
            # res.table holds the fully-hydrated rows either way)
            n_main = len(planner.table)
            rows = np.concatenate([res.indices, drows + n_main])
            sub = FeatureTable.concat([res.table, delta.take(drows)]) \
                if len(drows) else res.table
            out = QueryResult(rows, sub, res.plan)
            if res.plan is not None:
                res.plan.explain["stacked_rows_base"] = n_main
            return out
        shaping_keys = {"sort", "limit", "transform", "crs"}
        if shaping_keys.issuperset(hints):
            # shaping merges any pending delta INLINE (sort/limit/transform
            # are host-side anyway) — no flush, the LSM tier stays warm
            from geomesa_tpu.index.shaping import (reproject_table,
                                                   shape_local,
                                                   transform_table)
            planner, delta = self._snapshot(type_name)
            plan = planner.plan(f)
            rows = planner.select_indices(f, plan=plan, auths=auths)
            if delta is None:
                from geomesa_tpu.index.shaping import shape_rows
                rows = shape_rows(planner.table, rows, hints.get("sort"),
                                  hints.get("limit"))
                sub = planner.table.take(rows)
            else:
                drows = self._delta_rows(delta, f, auths)
                sub = FeatureTable.concat(
                    [planner.table.take(rows), delta.take(drows)])
                rows = np.concatenate(
                    [rows, drows + len(planner.table)])
                local = shape_local(sub, hints.get("sort"),
                                    hints.get("limit"))
                rows = rows[local]
                sub = sub.take(local)
            if "transform" in hints:
                sub = transform_table(sub, hints["transform"])
            if "crs" in hints:
                sub = reproject_table(sub, hints["crs"])
            return QueryResult(rows, sub, plan)
        # auths compose with every aggregation hint: the visibility-code
        # mask folds into the device scan (planner._apply_auths) exactly as
        # VisibilityFilter rides the reference's server-side scans
        if "density" in hints:
            # density merges any pending delta INCREMENTALLY (a host grid
            # for the delta rows adds onto the device grid) — a dashboard
            # repaint must never trigger an O(table) flush
            from geomesa_tpu.aggregates.density import density, host_grid
            planner, delta = self._snapshot(type_name)
            d = dict(hints["density"])
            grid = density(planner, f, d["bbox"], d.get("width", 256),
                           d.get("height", 256), d.get("weight"),
                           auths=auths)
            if delta is not None:
                drows = self._delta_rows(delta, f, auths)
                grid.weights = grid.weights + host_grid(
                    delta, drows, d["bbox"], grid.width, grid.height,
                    d.get("weight"))
            return grid
        planner = self.planner(type_name)  # other aggregations see merged state
        if "bin" in hints:
            from geomesa_tpu.aggregates.bin import bin_records
            b = dict(hints["bin"])
            return bin_records(planner, f, b["track"], b.get("label"),
                               b.get("sort", False), auths=auths)
        if "stats" in hints:
            return self.stats(type_name).run_stat(hints["stats"], f,
                                                  auths=auths)
        if "sample" in hints:
            from geomesa_tpu.aggregates.sampling import sample_rows
            s = hints["sample"]
            s = {"n": s} if isinstance(s, int) else dict(s)
            plan = planner.plan(f)
            rows = sample_rows(planner, f, s["n"], s.get("by"), plan=plan,
                               auths=auths)
            return QueryResult(rows, planner.table.take(rows), plan)
        raise ValueError(f"Unknown hints: {sorted(hints)}")

    def count(self, type_name: str, f: Union[str, ir.Filter] = "INCLUDE",
              auths: Optional[list] = None,
              deadline_ms: Optional[float] = None) -> int:
        from geomesa_tpu.metrics import REGISTRY as _metrics
        from geomesa_tpu.serve.resilience import deadline as _rdl
        _metrics.inc("query.counts")
        with _trace.trace("query.count", type=type_name, filter=str(f)), \
                _rdl.scope(deadline_ms):
            return self._count_impl(type_name, f, auths)

    def _count_impl(self, type_name, f, auths) -> int:
        planner, delta = self._snapshot(type_name)
        c = planner.count(f, auths=auths)
        if delta is not None:
            c += len(self._delta_rows(delta, f, auths))
        return c

    def explain(self, type_name: str, f: Union[str, ir.Filter],
                analyze: bool = False, auths: Optional[list] = None) -> dict:
        planner, delta = self._snapshot(type_name)
        out = planner.explain(f, analyze=analyze, auths=auths)
        if delta is not None:
            out["delta_rows"] = len(delta)  # unflushed LSM run merged inline
            if analyze and "analyze" in out:
                # store-level analyze must match store-level count: the
                # planner executed the main table only, the delta rows
                # merge here exactly like _count_impl does
                d = int(len(self._delta_rows(delta, f, auths)))
                out["analyze"]["rows_matched"] += d
                out["analyze"]["rows_scanned"] += len(delta)
                out["analyze"]["delta_rows_matched"] = d
        if analyze and "analyze" in out:
            # overlay the LIVE scheduler's cache provenance: would this
            # filter be served from the plan cache right now? (peek only —
            # an explain must not skew serving hit rates)
            sched = self._scheduler
            if sched is not None and sched.healthy():
                from geomesa_tpu.filter.parser import parse_ecql as _pe
                f_ir = _pe(f) if isinstance(f, str) else f
                auths_key = None if auths is None \
                    else tuple(sorted(str(a) for a in auths))
                pkey = (self.epoch, type_name, self.generation(type_name),
                        repr(f_ir), auths_key)
                out["analyze"]["provenance"]["plan_cache"] = \
                    "hit" if sched.plans.peek(pkey) else "miss"
                # same key shape as the plan cache: would a scheduled
                # count be answered from the hot-result cache right now?
                out["analyze"]["provenance"]["result_cache"] = \
                    "hit" if sched.results.peek(pkey) else "miss"
        return out

    def stats(self, type_name: str):
        """Per-type stats API (≙ GeoMesaDataStore.stats)."""
        self.planner(type_name)  # materialize
        return self._stats[type_name]

    def add_interceptor(self, type_name: str, interceptor) -> None:
        """Attach a query interceptor/guard (≙ the geomesa.query.interceptors
        SPI registration)."""
        self._interceptors.setdefault(type_name, []).append(interceptor)

    # -- deletes ------------------------------------------------------------

    def update_features(self, type_name: str, f: Union[str, ir.Filter],
                        updates: Dict[str, object]) -> int:
        """Modify attributes of matching features in place (≙ the reference's
        modify writer, GeoMesaFeatureWriter.scala:152-179: read matching
        features, set attributes, rewrite index rows). Columnar form: patch
        the columns at the matching rows, rebuild indexes (bulk-modify
        discipline — key-bearing attributes change index keys anyway).

        ``updates``: attr → scalar, array (len == matches), or callable
        receiving the matching sub-table and returning values.

        Build-then-swap: patched columns land in a NEW FeatureTable that
        replaces the shared one only at the end — a concurrent reader's
        snapshot keeps seeing the consistent pre-update table, never a mix
        of patched and unpatched columns."""
        with self._lock:
            planner = self.planner(type_name)  # flushes any delta first
            rows = planner.select_indices(f)
            if len(rows) == 0:
                return 0
            table = planner.table
            cols: Dict[str, object] = dict(table.columns)
            sub = None
            # WAL record: the RESOLVED mutation (fids + final values, with
            # callables already evaluated) — replay needs no closures and
            # no re-planning of the original filter
            wal_meta = {"type": type_name,
                        "fids": [str(x) for x in table.fids_at(rows)],
                        "scalars": {}, "geoms": {}, "string_lists": {}}
            wal_arrays: Dict[str, object] = {}
            for name, val in updates.items():
                attr = self.schemas[type_name].attribute(name)
                if callable(val):
                    sub = sub if sub is not None else table.take(rows)
                    val = val(sub)
                col = table.columns[name]
                if isinstance(col, GeometryArray):
                    new_geoms = val if isinstance(val, GeometryArray) \
                        else GeometryArray.from_rows(
                            [val] * len(rows) if isinstance(val, str)
                            else list(val))
                    wal_meta["geoms"][name] = [new_geoms.wkt(i)
                                               for i in range(len(rows))]
                    keep = np.ones(len(table), dtype=bool)
                    keep[rows] = False
                    order = np.concatenate([np.flatnonzero(keep), rows])
                    inv = np.empty(len(table), dtype=np.int64)
                    inv[order] = np.arange(len(table))
                    merged = GeometryArray.concat(
                        [col.take(np.flatnonzero(keep)), new_geoms])
                    cols[name] = merged.take(inv)
                elif isinstance(col, StringColumn):
                    # vectorized decode→patch→re-encode (never a per-row
                    # Python loop over the full column)
                    values = np.asarray(col.vocab, dtype=object)[col.codes]
                    values[rows] = val if isinstance(val, str) \
                        else np.asarray([str(v) for v in val], dtype=object)
                    cols[name] = StringColumn.encode(values)
                    if isinstance(val, str):
                        wal_meta["scalars"][name] = val
                    else:
                        wal_meta["string_lists"][name] = [str(v) for v in val]
                else:
                    # copy-on-write: loaded tables may alias caller arrays
                    arr = np.array(col, copy=True)
                    if attr.type_name == "Date":
                        v = np.asarray(val)
                        if v.dtype.kind in "MUS":
                            val = v.astype("datetime64[ms]").astype(np.int64)
                    arr[rows] = val
                    cols[name] = arr
                    if np.ndim(val) == 0:
                        wal_meta["scalars"][name] = val
                    else:
                        wal_arrays[name] = np.asarray(val)
            self._wal_table("update", wal_meta, arrays=wal_arrays,
                            rows=len(rows))
            self._bump_generation(type_name)
            self.tables[type_name] = FeatureTable(
                table.sft, table._fids, cols, table.visibility,
                _n=len(table))
            self._rebuild_indexes(type_name)
            n_updated = int(len(rows))
        self._dur_tick()
        return n_updated

    def update_schema(self, type_name: str, add_attributes: str = "",
                      new_name: Optional[str] = None) -> SimpleFeatureType:
        """Schema evolution (≙ MetadataBackedDataStore.updateSchema:227):
        append new attributes (spec-string syntax; existing rows take the
        type's zero/empty value) and/or rename the type."""
        with self._lock:
            out = self._update_schema_locked(type_name, add_attributes,
                                             new_name)
        self._dur_tick()
        return out

    def _update_schema_locked(self, type_name, add_attributes, new_name):
        self._wal_json("update_schema", {"type": type_name,
                                         "add": add_attributes,
                                         "new_name": new_name})
        sft = self.schemas[type_name]
        spec = sft.to_spec()
        if add_attributes:
            body = spec.split(";")[0]
            user = spec[len(body):]
            spec = body + "," + add_attributes + user
        out = SimpleFeatureType.from_spec(new_name or type_name, spec)
        old_names = {a.name for a in sft.attributes}
        for attr in out.attributes:
            if attr.is_geometry and attr.name not in old_names:
                raise ValueError("Cannot add a geometry attribute")
        table = self.tables.get(type_name)
        if table is not None:
            self.flush(type_name)
            table = self.tables[type_name]
            n = len(table)
            cols: Dict[str, object] = dict(table.columns)
            for attr in out.attributes:
                if attr.name in cols:
                    continue
                if attr.type_name == "String":
                    cols[attr.name] = StringColumn(
                        np.zeros(n, np.int32), [""])
                else:
                    cols[attr.name] = np.zeros(n, dtype=attr.binding)
            new_table = FeatureTable(out, table._fids, cols,
                                     table.visibility, _n=n)
        final = new_name or type_name
        if new_name is not None and new_name != type_name:
            if new_name in self.schemas:
                raise ValueError(f"Schema {new_name} already exists")
            # locked variant: the update_schema record above already covers
            # the rename — a nested remove_schema record would double-log
            self._remove_schema_locked(type_name)
        self._bump_generation(final)
        self.schemas[final] = out
        # the stat battery is built against the OLD attribute set — drop it
        # so the rebuild re-observes with the evolved schema
        self._stats.pop(final, None)
        if table is not None:
            self.tables[final] = new_table
            self.deltas[final] = None
            self._rebuild_indexes(final)
        else:
            self.tables[final] = None
        return out

    def remove_features(self, type_name: str, f: Union[str, ir.Filter]) -> int:
        """Delete matching features; returns the number removed (≙ GeoTools
        removeFeatures / the age-off iterators). Rebuilds indexes over the
        survivors — bulk deletion, matching the columnar build discipline."""
        with self._lock:
            planner = self.planner(type_name)
            rows = planner.select_indices(f)
            if len(rows) == 0:
                return 0
            # log the resolved fid set, not the filter: replay removes
            # exactly these rows regardless of later index/stats drift
            self._wal_json(
                "remove",
                {"type": type_name,
                 "fids": [str(x) for x in planner.table.fids_at(rows)]},
                rows=len(rows))
            keep = np.ones(len(planner.table), dtype=bool)
            keep[rows] = False
            self._bump_generation(type_name)
            self.tables[type_name] = planner.table.take(np.nonzero(keep)[0])
            self._rebuild_indexes(type_name)
            n_removed = int(len(rows))
        self._dur_tick()
        return n_removed


class DataStoreFinder:
    """Registry of datastore factories, keyed by params (SPI-equivalent,
    ≙ META-INF/services DataStoreFactorySpi discovery)."""

    _factories: List[type] = [TpuDataStore]

    @classmethod
    def register(cls, factory: type) -> None:
        if factory not in cls._factories:
            cls._factories.append(factory)

    @classmethod
    def get_data_store(cls, **params):
        for factory in cls._factories:
            if factory.can_process(params):
                return factory.create(params)
        raise ValueError(f"No datastore factory for params {sorted(params)}")
