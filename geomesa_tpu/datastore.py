"""DataStore facade — the framework entry point.

≙ reference GeoTools ``DataStoreFinder`` + ``GeoMesaDataStore``
(/root/reference/geomesa-index-api/.../geotools/GeoMesaDataStore.scala:49).
Round-1 surface: an in-process registry of named stores; ``create_schema`` /
``get_writer`` / ``get_query_runner`` land as the index layer comes up.
"""

from __future__ import annotations

from typing import Dict


class DataStoreFinder:
    """Registry of datastore factories, keyed by params (SPI-equivalent)."""

    _factories: Dict[str, type] = {}

    @classmethod
    def register(cls, name: str, factory: type) -> None:
        cls._factories[name] = factory

    @classmethod
    def get_data_store(cls, **params):
        for name, factory in cls._factories.items():
            if factory.can_process(params):
                return factory.create(params)
        raise ValueError(f"No datastore factory for params {sorted(params)}")
