"""BIN trajectory encoding.

≙ reference `BinAggregatingScan` + `BinaryOutputEncoder`
(index/iterators/BinAggregatingScan.scala, utils/bin/BinaryOutputEncoder.scala:
28,59): pack matching features into fixed 16-byte (or 24-byte labelled)
records — trackId:int32, dtg:int32 epoch seconds, lat:f32, lon:f32
[, label:int64] — the massive-trajectory wire format. The scan/filter runs on
device; the pack is one vectorized structured-array assembly over the
surviving rows (columnar in, columnar out — no per-feature loop).
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from geomesa_tpu.features.table import StringColumn
from geomesa_tpu.stats.sketches import hash64

BIN_DTYPE = np.dtype([("track", "<i4"), ("dtg", "<i4"),
                      ("lat", "<f4"), ("lon", "<f4")])
BIN_LABEL_DTYPE = np.dtype([("track", "<i4"), ("dtg", "<i4"),
                            ("lat", "<f4"), ("lon", "<f4"), ("label", "<i8")])


def _track_ids(col) -> np.ndarray:
    """Stable int32 track ids (≙ trackId hashCode semantics: a deterministic
    int per distinct value)."""
    if isinstance(col, StringColumn):
        vocab_ids = (hash64(np.asarray(col.vocab, dtype=object))
                     & np.uint64(0x7FFFFFFF)).astype(np.int32)
        return vocab_ids[col.codes]
    arr = np.asarray(col)
    if arr.dtype.kind in "iub":
        return arr.astype(np.int32)
    return (hash64(arr) & np.uint64(0x7FFFFFFF)).astype(np.int32)


def _label_ids(col) -> np.ndarray:
    if isinstance(col, StringColumn):
        vocab_ids = hash64(np.asarray(col.vocab, dtype=object)).astype(np.int64)
        return vocab_ids[col.codes]
    return np.asarray(col).astype(np.int64)


def bin_records(planner, f, track: str, label: Optional[str] = None,
                sort: bool = False, auths=None) -> np.ndarray:
    """Matching rows as a packed structured array (``.tobytes()`` is the wire
    form). sort=True orders by dtg (≙ the BinSorter merge phase); ``auths``
    restricts to visible rows."""
    sft = planner.sft
    dtg_attr = sft.dtg_attribute
    if dtg_attr is None:
        raise ValueError("BIN encoding requires a date attribute")
    rows = planner.select_indices(f, auths=auths)
    sub = planner.table.take(rows)
    x, y = sub.geometry().point_xy() if sub.geometry().is_points else _centroids(sub)
    out = np.empty(len(rows), dtype=BIN_LABEL_DTYPE if label else BIN_DTYPE)
    out["track"] = _track_ids(sub.columns[track])
    out["dtg"] = (np.asarray(sub.columns[dtg_attr.name], dtype=np.int64)
                  // 1000).astype(np.int32)
    out["lat"] = y.astype(np.float32)
    out["lon"] = x.astype(np.float32)
    if label:
        out["label"] = _label_ids(sub.columns[label])
    if sort:
        out = out[np.argsort(out["dtg"], kind="stable")]
    return out


def _centroids(sub):
    bb = sub.geometry().bboxes()
    return (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2


def decode_bin(buf: Union[bytes, np.ndarray], labelled: bool = False) -> np.ndarray:
    """Wire bytes → structured array (the client decode side)."""
    if isinstance(buf, np.ndarray):
        return buf
    return np.frombuffer(buf, dtype=BIN_LABEL_DTYPE if labelled else BIN_DTYPE)
