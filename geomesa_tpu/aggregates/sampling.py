"""Result sampling.

≙ reference `SamplingIterator` (index/iterators/SamplingIterator.scala):
keep 1-in-n of the matching features, optionally per-thread-key (the
``by`` attribute groups so every track keeps points). Selection runs on
device; the thinning is a cheap host stride over the surviving row ids —
transfer and hydration shrink by the sample factor, which is the point.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from geomesa_tpu.features.table import StringColumn


def sample_rows(planner, f, n: int, by: Optional[str] = None,
                plan=None, auths=None) -> np.ndarray:
    """Row indices of a 1-in-n sample of matches (per ``by``-group when set).
    Pass a precomputed plan to avoid re-planning; ``auths`` restricts to
    visible rows."""
    rows = planner.select_indices(f, plan=plan, auths=auths)
    if n <= 1:
        return rows
    if len(rows) == 0 or by is None:
        return rows[::n]
    col = planner.table.columns[by]
    keys = col.codes[rows] if isinstance(col, StringColumn) else np.asarray(col)[rows]
    # stable per-group stride: order by (group, position), take every n-th
    # within each group run
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    starts = np.r_[0, np.nonzero(np.diff(sorted_keys))[0] + 1]
    pos_in_group = np.arange(len(rows)) - np.repeat(
        starts, np.diff(np.r_[starts, len(rows)]))
    keep = order[pos_in_group % n == 0]
    return np.sort(rows[keep])
