"""Device-side grid readback codecs (sparse + fp16 packing).

≙ reference ``DensityScan`` result encoding (index/iterators/DensityScan.
scala:95-106): the reference ships each server's partial grid as *sparse*
kryo-encoded (cell, weight) pairs because the dense grid dominates the wire
cost back to the client. Here the expensive wire is the RPC tunnel between
host and chip, so the pack runs ON DEVICE (one tiny fused kernel after the
scatter) and the host decodes:

- ``sparse``: ``[nnz, count, mass_bits, cell_idx…(cap), fp16 weight pairs]``
  — 6 bytes per nonzero cell. Chosen when the match-count bound says cell
  occupancy stays under ~1/3 (below that it beats the fp16-dense encoding).
- ``fp16``: same header + the full grid as fp16 packed two-per-uint32 —
  2 bytes/cell, half the raw f32 readback, exact for integer cell counts
  up to 2048 (the unweighted case by construction).

Both carry a device-computed f32 ``mass`` in the header; the decoder checks
the decoded sum against it and signals a fallback to the raw f32 grid when
fp16 rounding (huge per-cell weights, inf saturation) would distort the
result. Everything is uint32 on the wire so a render costs exactly ONE
device fetch.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

HEADER = 4  # [nnz, count, mass_bits, maxcell_bits]

# decoded f64 sum vs device f32 mass: fp16 carries ~11 mantissa bits, so a
# sum of rounded cells stays within ~2^-10 relative of the true mass; beyond
# that something saturated (inf) or overflowed and the caller must re-fetch
MASS_RTOL = 2e-3


def _fp16_pairs(w: jnp.ndarray) -> jnp.ndarray:
    """(M,) f32 → (ceil(M/2),) uint32 of bit-packed fp16 pairs."""
    h = lax.bitcast_convert_type(w.astype(jnp.float16), jnp.uint16)
    h = h.astype(jnp.uint32)
    if h.shape[0] % 2:
        h = jnp.concatenate([h, jnp.zeros((1,), jnp.uint32)])
    h = h.reshape(-1, 2)
    return h[:, 0] | (h[:, 1] << 16)


def _header(flat: jnp.ndarray, nnz: jnp.ndarray, count: jnp.ndarray):
    mass = jnp.sum(flat, dtype=jnp.float32)
    # max cell rides along so narrow encodings can reject per-cell overflow
    # exactly — a clipped hotspot can be tiny relative to the global mass
    # and would otherwise slip through the mass guard
    peak = jnp.max(flat, initial=0.0).astype(jnp.float32)
    return jnp.stack([
        nnz.astype(jnp.uint32),
        count.astype(jnp.uint32),
        lax.bitcast_convert_type(mass, jnp.uint32),
        lax.bitcast_convert_type(peak, jnp.uint32),
    ])


def pack_sparse(grid: jnp.ndarray, count: jnp.ndarray, cap: int) -> jnp.ndarray:
    """Nonzero cells of an (H, W) f32 grid as one uint32 vector."""
    flat = grid.reshape(-1)
    hw = flat.shape[0]
    nz = flat != 0
    sel = jnp.nonzero(nz, size=cap, fill_value=hw)[0]
    ok = sel < hw
    w = jnp.where(ok, flat[jnp.clip(sel, 0, hw - 1)], 0.0)
    head = _header(flat, jnp.sum(nz), count)
    return jnp.concatenate([head, sel.astype(jnp.uint32), _fp16_pairs(w)])


def pack_fp16(grid: jnp.ndarray, count: jnp.ndarray) -> jnp.ndarray:
    """Whole (H, W) f32 grid as fp16, two cells per uint32."""
    flat = grid.reshape(-1)
    head = _header(flat, jnp.sum(flat != 0), count)
    return jnp.concatenate([head, _fp16_pairs(flat)])


def pack_u8(grid: jnp.ndarray, count: jnp.ndarray) -> jnp.ndarray:
    """Whole (H, W) grid as uint8 cells, four per uint32 — 1 byte/cell,
    exact for integer counts ≤255 (the unweighted-render common case; the
    measured tunnel fetch curve has a knee at ~256KB, which a 512² grid hits
    exactly at 1 byte/cell). Saturated/fractional cells distort the decoded
    sum, which the mass guard catches → caller downgrades encodings."""
    flat = grid.reshape(-1)
    head = _header(flat, jnp.sum(flat != 0), count)
    q = jnp.clip(jnp.rint(flat), 0, 255).astype(jnp.uint32)
    pad = (-q.shape[0]) % 4
    if pad:
        q = jnp.concatenate([q, jnp.zeros((pad,), jnp.uint32)])
    q = q.reshape(-1, 4)
    body = q[:, 0] | (q[:, 1] << 8) | (q[:, 2] << 16) | (q[:, 3] << 24)
    return jnp.concatenate([head, body])


def _unpack_fp16_pairs(u: np.ndarray, m: int) -> np.ndarray:
    h = np.empty(u.size * 2, np.uint16)
    h[0::2] = (u & 0xFFFF).astype(np.uint16)
    h[1::2] = (u >> 16).astype(np.uint16)
    return h[:m].view(np.float16).astype(np.float32)


def _f32_bits(word) -> float:
    return float(np.array([word], dtype=np.uint32).view(np.float32)[0])


def decode(packed: np.ndarray, mode: str, cap: Optional[int],
           height: int, width: int
           ) -> Optional[Tuple[np.ndarray, int, float]]:
    """Packed uint32 vector → ((H, W) f32 grid, count, mass), or ``None``
    when the encoding can't represent the result faithfully (sparse cap
    overflow, u8/fp16 per-cell overflow, rounding drift past the mass
    guard) and the caller should step down the encoding ladder."""
    packed = np.asarray(packed, dtype=np.uint32)
    nnz = int(packed[0])
    count = int(packed[1])
    mass = _f32_bits(packed[2])
    peak = _f32_bits(packed[3])
    if mode == "u8" and peak > 255.0:
        return None  # a clipped hotspot may be tiny vs the global mass
    if mode == "fp16" and peak > 65504.0:
        return None  # fp16 saturates to inf
    grid = np.zeros((height, width), dtype=np.float32)
    hw = height * width
    if mode == "sparse":
        if nnz > cap:
            return None
        idx = packed[HEADER: HEADER + nnz].astype(np.int64)
        w = _unpack_fp16_pairs(packed[HEADER + cap:], cap)[:nnz]
        grid.reshape(-1)[idx] = w
    elif mode == "u8":
        body = packed[HEADER:]
        cells = np.empty(body.size * 4, np.uint8)
        cells[0::4] = body & 0xFF
        cells[1::4] = (body >> 8) & 0xFF
        cells[2::4] = (body >> 16) & 0xFF
        cells[3::4] = (body >> 24) & 0xFF
        grid = cells[:hw].astype(np.float32).reshape(height, width)
    else:
        grid = _unpack_fp16_pairs(packed[HEADER:], hw).reshape(height, width)
    got = float(grid.sum(dtype=np.float64))
    if not np.isfinite(got) or abs(got - mass) > MASS_RTOL * max(abs(mass), 1.0):
        return None
    return grid, count, mass


def choose(count_bound: int, height: int, width: int, mode: str = "auto",
           unit_weights: bool = False) -> list:
    """Encoding ladder (cheapest wire cost first) from a bound on the number
    of matched rows (nnz ≤ min(matches, cells)). Each entry is
    (mode, sparse_cap); the caller walks down the ladder when a decode
    reports it couldn't carry the result, ending at raw f32 readback.
    ``unit_weights`` admits the u8 encoding (exact only for integer counts
    ≤255/cell)."""
    if mode == "none":
        return []
    if mode not in ("auto", "sparse", "fp16", "u8"):
        mode = "auto"  # malformed knob values fall back (reference behavior)
    if mode == "u8" and not unit_weights:
        # u8 per-cell rounding of fractional weights can cancel in the mass
        # guard while individual cells are off by up to 0.5 — not faithful
        mode = "fp16"
    hw = height * width
    nnzb = max(1, min(int(count_bound), hw))
    cap = 1 << max(5, (nnzb - 1).bit_length())
    if mode != "auto":
        return [(mode, cap if mode == "sparse" else None)]
    ladder = [("sparse", cap), ("fp16", None)]
    if unit_weights:
        ladder.insert(0, ("u8", None))
    # an encoding that ships more bytes than the raw f32 grid (sparse cap at
    # high occupancy) is strictly worse than falling straight to raw
    ladder = [mc for mc in ladder
              if packed_bytes(mc[0], mc[1], height, width) < 4 * hw]
    ladder.sort(key=lambda mc: packed_bytes(mc[0], mc[1], height, width))
    return ladder


def packed_bytes(mode: str, cap: Optional[int], height: int, width: int) -> int:
    hw = height * width
    if mode == "sparse":
        return 4 * (HEADER + cap + (cap + 1) // 2)
    if mode == "u8":
        return 4 * (HEADER + (hw + 3) // 4)
    return 4 * (HEADER + (hw + 1) // 2)


PACK_FNS = {"sparse": pack_sparse, "fp16": pack_fp16, "u8": pack_u8}

_PACK_JITS: dict = {}


def pack_jit(mode: str, cap: Optional[int]):
    """Jitted pack fn cached per (mode, cap) — a fresh jax.jit closure per
    prepared query would retrace/recompile the identical kernel every time
    (10-90s each through a tunnel)."""
    key = (mode, cap)
    if key not in _PACK_JITS:
        base = PACK_FNS[mode]
        if mode == "sparse":
            _PACK_JITS[key] = jax.jit(
                lambda g, c, _b=base, _p=cap: _b(g, c, _p))
        else:
            _PACK_JITS[key] = jax.jit(base)
    return _PACK_JITS[key]
