"""Server-side aggregations (≙ reference index.iterators, SURVEY.md §2.4):
DensityScan → scatter-add heat maps, StatsScan → device sketch reductions,
BinAggregatingScan → packed trajectory records. Each runs as an alternate
reducer over the same scan mask the query planner produces, exactly how the
reference swaps aggregating iterators in via query hints."""

from geomesa_tpu.aggregates.density import DensityGrid, density

__all__ = ["DensityGrid", "density"]
