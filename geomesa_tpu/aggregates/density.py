"""Density (heat-map) aggregation.

≙ reference ``DensityScan`` (index/iterators/DensityScan.scala:29): snap each
matching feature onto a width×height grid over the render bbox, accumulating
optional per-feature weights, then merge per-server partial grids client-side.
Here the snap+accumulate is one scatter-add kernel fused behind the scan mask;
under a device mesh the per-device partial grids merge with a psum (the
reducer step riding ICI instead of client RPC).

Grid snap semantics mirror GridSnap.scala:23: i = floor((x - xmin)/sizeX * W),
clamped to the grid, features outside the bbox excluded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DensityGrid:
    bbox: tuple            # (xmin, ymin, xmax, ymax)
    width: int
    height: int
    weights: np.ndarray    # (height, width) float32

    def to_points(self):
        """Non-zero cells as (x_center, y_center, weight) — the decode side
        (DensityScan.decodeResult)."""
        xmin, ymin, xmax, ymax = self.bbox
        iy, ix = np.nonzero(self.weights)
        dx = (xmax - xmin) / self.width
        dy = (ymax - ymin) / self.height
        return (xmin + (ix + 0.5) * dx, ymin + (iy + 0.5) * dy, self.weights[iy, ix])


def density_kernel(mask: jnp.ndarray, x: jnp.ndarray, y: jnp.ndarray,
                   grid: jnp.ndarray, width: int, height: int,
                   weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure scatter-add: (H, W) grid of weights. grid = [xmin,ymin,xmax,ymax].
    The snap semantics live in index/scan._grid_scatter (one home; the
    compact/pruned device paths use it directly — this wrapper serves the
    mesh/dist full-mask path)."""
    from geomesa_tpu.index.scan import _grid_scatter
    return _grid_scatter(x, y, mask, weight, grid, width, height)


_COMPACT_TIERS = (1 << 17, 1 << 20, 1 << 23)


def prepare_density(planner, f, bbox, width: int = 256, height: int = 256,
                    weight_attr: Optional[str] = None, auths=None):
    """Plan once, stage constants, return a zero-arg callable producing a
    DensityGrid per call (≙ a configured DensityScan handed to the servers).

    Device path (plan fully device-exact): range-pruned block gather+scatter
    when the planner has a cover, else mask → compact → scatter (a TPU
    scatter prices per update, so compacting ~matches beats scattering all N
    rows by ~N/matches). The returned callable carries ``.dispatch()`` —
    async device dispatch returning the (H, W) device array without readback
    — so renders pipeline. Host fallback mirrors LocalQueryRunner's density
    transform.
    """
    plan = planner._apply_auths(planner.plan(f), auths)
    shape = (height, width)

    def run_empty():
        return DensityGrid(tuple(bbox), width, height,
                           np.zeros(shape, np.float32))

    if plan.empty:
        return run_empty

    idx = plan.index
    weight_on_device = weight_attr is None or (
        idx is not None and weight_attr in idx.device.columns
        and planner.sft.attribute(weight_attr).type_name in
        ("Int", "Integer", "Long", "Float", "Double"))
    device_ok = (plan.device_exact and "xf" in idx.device.columns
                 and weight_on_device)
    if device_ok:
        from geomesa_tpu.index import prune as _prune

        blocks = planner._pruned_blocks(plan)
        if blocks is not None and len(blocks) == 0:
            return run_empty  # provably-empty cover
        if blocks is not None:
            disp0 = idx.kernels.prepare_density_blocks(
                plan.primary_kind, plan.boxes_loose, plan.windows,
                plan.residual_device, bbox, width, height, blocks,
                _prune.BLOCK_SIZE, weight_attr)
        else:
            # size the compaction from an exact count (static data — the
            # capacity can then never overflow)
            cnt = planner._count(plan, f, auths)
            cap = next((t for t in _COMPACT_TIERS if cnt <= t),
                       1 << max(0, (max(cnt, 1) - 1)).bit_length())
            disp0 = idx.kernels.prepare_density_compact(
                plan.primary_kind, plan.boxes_loose, plan.windows,
                plan.residual_device, bbox, width, height, cap, weight_attr)

        def dispatch():
            return disp0()[0]

        def run():
            return DensityGrid(tuple(bbox), width, height,
                               np.asarray(dispatch()))
        run.dispatch = dispatch
        return run

    def run_host():
        return _host_density(planner, f, plan, bbox, width, height,
                             weight_attr, auths)
    return run_host


def density(planner, f, bbox, width: int = 256, height: int = 256,
            weight_attr: Optional[str] = None, auths=None) -> DensityGrid:
    """One-shot density query (plan + execute). Repeated renders should hold
    onto ``prepare_density`` instead — it skips re-planning and re-staging."""
    return prepare_density(planner, f, bbox, width, height, weight_attr,
                           auths)()


def host_grid(table, rows: np.ndarray, bbox, width: int, height: int,
              weight_attr: Optional[str] = None) -> np.ndarray:
    """Snap+accumulate selected table rows onto an (H, W) grid on the host
    (the LocalQueryRunner density transform; also the LSM delta tier's
    incremental contribution)."""
    garr = table.geometry()
    bbs = garr.bboxes()[rows]
    x = (bbs[:, 0] + bbs[:, 2]) / 2
    y = (bbs[:, 1] + bbs[:, 3]) / 2
    w = np.asarray(table.column(weight_attr), dtype=np.float64)[rows] \
        if weight_attr else None
    xmin, ymin, xmax, ymax = bbox
    fx = (x - xmin) / (xmax - xmin)
    fy = (y - ymin) / (ymax - ymin)
    inb = (fx >= 0) & (fx < 1) & (fy >= 0) & (fy < 1)
    ix = np.clip((fx[inb] * width).astype(np.int64), 0, width - 1)
    iy = np.clip((fy[inb] * height).astype(np.int64), 0, height - 1)
    weights = np.zeros((height, width), dtype=np.float32)
    np.add.at(weights, (iy, ix), w[inb] if w is not None else 1.0)
    return weights


def _host_density(planner, f, plan, bbox, width, height, weight_attr,
                  auths) -> DensityGrid:
    """Host fallback (≙ LocalQueryRunner.transform density path)."""
    rows = planner.select_indices(f, plan=plan, auths=auths)
    weights = host_grid(planner.table, rows, bbox, width, height, weight_attr)
    return DensityGrid(tuple(bbox), width, height, weights)


