"""Density (heat-map) aggregation.

≙ reference ``DensityScan`` (index/iterators/DensityScan.scala:29): snap each
matching feature onto a width×height grid over the render bbox, accumulating
optional per-feature weights, then merge per-server partial grids client-side.
Here the snap+accumulate is one scatter-add kernel fused behind the scan mask;
under a device mesh the per-device partial grids merge with a psum (the
reducer step riding ICI instead of client RPC).

Grid snap semantics mirror GridSnap.scala:23: i = floor((x - xmin)/sizeX * W),
clamped to the grid, features outside the bbox excluded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DensityGrid:
    bbox: tuple            # (xmin, ymin, xmax, ymax)
    width: int
    height: int
    weights: np.ndarray    # (height, width) float32

    def to_points(self):
        """Non-zero cells as (x_center, y_center, weight) — the decode side
        (DensityScan.decodeResult)."""
        xmin, ymin, xmax, ymax = self.bbox
        iy, ix = np.nonzero(self.weights)
        dx = (xmax - xmin) / self.width
        dy = (ymax - ymin) / self.height
        return (xmin + (ix + 0.5) * dx, ymin + (iy + 0.5) * dy, self.weights[iy, ix])


def density_kernel(mask: jnp.ndarray, x: jnp.ndarray, y: jnp.ndarray,
                   grid: jnp.ndarray, width: int, height: int,
                   weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure scatter-add: (H, W) grid of weights. grid = [xmin,ymin,xmax,ymax]."""
    xmin, ymin, xmax, ymax = grid[0], grid[1], grid[2], grid[3]
    fx = (x - xmin) / (xmax - xmin)
    fy = (y - ymin) / (ymax - ymin)
    inb = (fx >= 0) & (fx < 1) & (fy >= 0) & (fy < 1)
    ix = jnp.clip((fx * width).astype(jnp.int32), 0, width - 1)
    iy = jnp.clip((fy * height).astype(jnp.int32), 0, height - 1)
    w = jnp.where(mask & inb, weight if weight is not None else 1.0, 0.0).astype(jnp.float32)
    return jnp.zeros((height, width), dtype=jnp.float32).at[iy, ix].add(w)


def density(planner, f, bbox, width: int = 256, height: int = 256,
            weight_attr: Optional[str] = None) -> DensityGrid:
    """Run a density query through the planner's chosen strategy.

    Device path when the plan needs no host refinement (loose-boundary snap
    differences are inside one grid cell for any realistic grid); host
    fallback mirrors LocalQueryRunner's density transform.
    """
    plan, mask = planner.scan_mask(f)
    grid = np.asarray(bbox, dtype=np.float32)
    if plan.empty:
        return DensityGrid(tuple(bbox), width, height, np.zeros((height, width), np.float32))

    idx = plan.index
    if mask is not None and "xf" in idx.device.columns:
        cols = idx.device.columns
        wcol = cols.get(weight_attr) if weight_attr else None
        out = _jit_density(mask, cols["xf"], cols["yf"], jnp.asarray(grid),
                           width, height, wcol)
        return DensityGrid(tuple(bbox), width, height, np.asarray(out))

    # host fallback (≙ LocalQueryRunner.transform density path)
    rows = planner.select_indices(f, plan=plan)
    sub = planner.table.take(rows)
    garr = sub.geometry()
    bbs = garr.bboxes()
    x = (bbs[:, 0] + bbs[:, 2]) / 2
    y = (bbs[:, 1] + bbs[:, 3]) / 2
    w = np.asarray(sub.column(weight_attr), dtype=np.float64) if weight_attr else None
    xmin, ymin, xmax, ymax = bbox
    fx = (x - xmin) / (xmax - xmin)
    fy = (y - ymin) / (ymax - ymin)
    inb = (fx >= 0) & (fx < 1) & (fy >= 0) & (fy < 1)
    ix = np.clip((fx[inb] * width).astype(np.int64), 0, width - 1)
    iy = np.clip((fy[inb] * height).astype(np.int64), 0, height - 1)
    weights = np.zeros((height, width), dtype=np.float32)
    np.add.at(weights, (iy, ix), w[inb] if w is not None else 1.0)
    return DensityGrid(tuple(bbox), width, height, weights)


_jit_density_fn = jax.jit(density_kernel, static_argnames=("width", "height"))


def _jit_density(mask, x, y, grid, width, height, weight):
    return _jit_density_fn(mask, x, y, grid, width, height, weight)
