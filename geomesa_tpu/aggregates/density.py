"""Density (heat-map) aggregation.

≙ reference ``DensityScan`` (index/iterators/DensityScan.scala:29): snap each
matching feature onto a width×height grid over the render bbox, accumulating
optional per-feature weights, then merge per-server partial grids client-side.
Here the snap+accumulate is one scatter-add kernel fused behind the scan mask;
under a device mesh the per-device partial grids merge with a psum (the
reducer step riding ICI instead of client RPC).

Grid snap semantics mirror GridSnap.scala:23: i = floor((x - xmin)/sizeX * W),
clamped to the grid, features outside the bbox excluded.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu import trace as _trace


@dataclass
class DensityGrid:
    bbox: tuple            # (xmin, ymin, xmax, ymax)
    width: int
    height: int
    weights: np.ndarray    # (height, width) float32

    def to_points(self):
        """Non-zero cells as (x_center, y_center, weight) — the decode side
        (DensityScan.decodeResult)."""
        xmin, ymin, xmax, ymax = self.bbox
        iy, ix = np.nonzero(self.weights)
        dx = (xmax - xmin) / self.width
        dy = (ymax - ymin) / self.height
        return (xmin + (ix + 0.5) * dx, ymin + (iy + 0.5) * dy, self.weights[iy, ix])


def density_kernel(mask: jnp.ndarray, x: jnp.ndarray, y: jnp.ndarray,
                   grid: jnp.ndarray, width: int, height: int,
                   weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Pure scatter-add: (H, W) grid of weights. grid = [xmin,ymin,xmax,ymax].
    The snap semantics live in index/scan._grid_scatter (one home; the
    compact/pruned device paths use it directly — this wrapper serves the
    mesh/dist full-mask path)."""
    from geomesa_tpu.index.scan import _grid_scatter
    return _grid_scatter(x, y, mask, weight, grid, width, height)


_COMPACT_TIERS = (1 << 17, 1 << 20, 1 << 23)


def prepare_density(planner, f, bbox, width: int = 256, height: int = 256,
                    weight_attr: Optional[str] = None, auths=None):
    """Plan once, stage constants, return a zero-arg callable producing a
    DensityGrid per call (≙ a configured DensityScan handed to the servers).

    Device path (plan fully device-exact): range-pruned block gather+scatter
    when the planner has a cover, else mask → compact → scatter (a TPU
    scatter prices per update, so compacting ~matches beats scattering all N
    rows by ~N/matches). The returned callable carries ``.dispatch()`` —
    async device dispatch returning the (H, W) device array without readback
    — so renders pipeline. Host fallback mirrors LocalQueryRunner's density
    transform.
    """
    plan = planner._apply_auths(planner.plan(f), auths)
    shape = (height, width)

    def run_empty():
        return DensityGrid(tuple(bbox), width, height,
                           np.zeros(shape, np.float32))

    if plan.empty:
        return run_empty

    from geomesa_tpu.index.api import UnionScanPlan
    if isinstance(plan, UnionScanPlan) and weight_attr is None:
        # OR-of-covers: when every branch is a device-exact scan on one
        # index, the whole union renders in ONE fused dispatch (the branch
        # masks OR in-program); otherwise per-branch select + host grid
        from geomesa_tpu.index import compiled as _fused

        def run_union():
            with _trace.trace("density", type=planner.sft.name):
                out = _fused.try_union_density(planner, plan, auths, bbox,
                                               width, height)
            if out is None:
                return _host_density(planner, f, plan, bbox, width, height,
                                     weight_attr, auths)
            return DensityGrid(tuple(bbox), width, height, out[0])
        return run_union

    idx = plan.index
    weight_on_device = weight_attr is None or (
        idx is not None and weight_attr in idx.device.columns
        and planner.sft.attribute(weight_attr).type_name in
        ("Int", "Integer", "Long", "Float", "Double"))
    device_ok = (plan.device_exact and "xf" in idx.device.columns
                 and weight_on_device)
    if device_ok:
        from geomesa_tpu.aggregates import grid_codec
        from geomesa_tpu.config import DENSITY_PACK
        from geomesa_tpu.index import prune as _prune

        blocks = planner._pruned_blocks(plan)
        if blocks is not None and len(blocks) == 0:
            return run_empty  # provably-empty cover

        state: dict = {}

        def _stage_compact(cnt):
            cap = next((t for t in _COMPACT_TIERS if cnt <= t),
                       1 << max(0, (max(cnt, 1) - 1)).bit_length())
            state["disp"] = idx.kernels.prepare_density_compact(
                plan.primary_kind, plan.boxes_loose, plan.windows,
                plan.residual_device, bbox, width, height, cap, weight_attr)
            state["cap"] = cap

        def _stage_pack(bound):
            """Device-side readback encoding ladder (u8/sparse/fp16 → raw)
            sized from a bound on the matched rows — nonzero cells can't
            exceed it. Encodings that can't carry a result (cap overflow,
            saturation) get popped at decode time."""
            state["ladder"] = grid_codec.choose(
                bound, height, width, DENSITY_PACK.get(),
                unit_weights=weight_attr is None)
            state["pack"] = _next_pack()

        def _next_pack():
            if state["ladder"]:
                pmode, pcap = state["ladder"].pop(0)
                return (pmode, pcap, grid_codec.pack_jit(pmode, pcap))
            return None

        if blocks is not None:
            state["disp"] = idx.kernels.prepare_density_blocks(
                plan.primary_kind, plan.boxes_loose, plan.windows,
                plan.residual_device, bbox, width, height, blocks,
                _prune.BLOCK_SIZE, weight_attr)
            state["cap"] = None  # gather scan — no compaction to overflow
            _stage_pack(len(blocks) * _prune.BLOCK_SIZE)
        else:
            cnt = planner._count(plan, f, auths)
            _stage_compact(cnt)
            _stage_pack(cnt)

        def dispatch():
            return state["disp"]()[0]

        def run():
            for _ in range(6):
                with _trace.trace("density", type=planner.sft.name):
                    with _trace.span("device_scan", kind="device_scan"):
                        g, c = state["disp"]()
                    pack = state["pack"]
                    if pack is not None:
                        pmode, pcap, fn = pack
                        with _trace.span("device_scan", kind="device_scan"):
                            packed = fn(g, c)
                        with _trace.span("device_wait", kind="device_wait"):
                            packed = np.asarray(
                                jax.block_until_ready(packed))
                        with _trace.span("aggregate", kind="aggregate"):
                            dec = grid_codec.decode(packed, pmode,
                                                    pcap, height, width)
                        if dec is None:
                            # cap overflow / saturation / rounding drift: this
                            # encoding can't carry the result — step down the
                            # ladder (ultimately to raw f32)
                            state["pack"] = _next_pack()
                            with _trace.span("device_wait",
                                             kind="device_wait"):
                                weights, got = np.asarray(g), int(c)
                        else:
                            weights, got, _mass = dec
                    else:
                        with _trace.span("device_wait", kind="device_wait"):
                            weights, got = np.asarray(g), int(c)
                if state["cap"] is not None and got > state["cap"]:
                    # the match count outgrew the compaction capacity (table
                    # mutated since prepare): the scatter dropped rows —
                    # restage with a bigger cap instead of returning a grid
                    # that silently lost mass
                    _stage_compact(got)
                    if state["pack"] is not None:
                        _stage_pack(got)
                    continue
                return DensityGrid(tuple(bbox), width, height, weights)
            raise RuntimeError("density capacity kept overflowing under "
                               "concurrent mutation; flush and retry")
        run.dispatch = dispatch
        run.packed = lambda: state["pack"] and state["pack"][:2]
        return run

    def run_host():
        with _trace.trace("density", type=planner.sft.name, path="host"):
            return _host_density(planner, f, plan, bbox, width, height,
                                 weight_attr, auths)
    return run_host


def density(planner, f, bbox, width: int = 256, height: int = 256,
            weight_attr: Optional[str] = None, auths=None) -> DensityGrid:
    """One-shot density query (plan + execute). Repeated renders should hold
    onto ``prepare_density`` instead — it skips re-planning and re-staging."""
    return prepare_density(planner, f, bbox, width, height, weight_attr,
                           auths)()


def host_grid(table, rows: np.ndarray, bbox, width: int, height: int,
              weight_attr: Optional[str] = None) -> np.ndarray:
    """Snap+accumulate selected table rows onto an (H, W) grid on the host
    (the LocalQueryRunner density transform; also the LSM delta tier's
    incremental contribution)."""
    garr = table.geometry()
    bbs = garr.bboxes()[rows]
    x = (bbs[:, 0] + bbs[:, 2]) / 2
    y = (bbs[:, 1] + bbs[:, 3]) / 2
    w = np.asarray(table.column(weight_attr), dtype=np.float64)[rows] \
        if weight_attr else None
    xmin, ymin, xmax, ymax = bbox
    fx = (x - xmin) / (xmax - xmin)
    fy = (y - ymin) / (ymax - ymin)
    inb = (fx >= 0) & (fx < 1) & (fy >= 0) & (fy < 1)
    ix = np.clip((fx[inb] * width).astype(np.int64), 0, width - 1)
    iy = np.clip((fy[inb] * height).astype(np.int64), 0, height - 1)
    weights = np.zeros((height, width), dtype=np.float32)
    np.add.at(weights, (iy, ix), w[inb] if w is not None else 1.0)
    return weights


def _host_density(planner, f, plan, bbox, width, height, weight_attr,
                  auths) -> DensityGrid:
    """Host fallback (≙ LocalQueryRunner.transform density path)."""
    rows = planner.select_indices(f, plan=plan, auths=auths)
    with _trace.span("aggregate", kind="aggregate", rows=len(rows)):
        weights = host_grid(planner.table, rows, bbox, width, height,
                            weight_attr)
    return DensityGrid(tuple(bbox), width, height, weights)


