"""Device-side stat reductions (the StatsScan kernel path).

≙ reference `StatsScan` (index/iterators/StatsScan.scala): sketches computed
next to the data. On TPU the scan mask stays on device and each supported
sketch becomes one fused reduction over it (scatter-add bincounts, masked
sums) — only the tiny reduced result crosses to the host. Unsupported sketch
kinds fall back to select+observe (the LocalQueryRunner path); the split is
per-leaf so one spec string can mix both.

Device-computable: Count, Histogram (numeric), Z2Histogram (point layers),
Enumeration (dictionary strings), GroupBy(string, Count()). MinMax keeps the
host path — its HLL cardinality needs 64-bit hashing the TPU has no business
doing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.stats import sketches as sk


@functools.partial(jax.jit, static_argnames=("bins",))
def _masked_hist(col, mask, lo, hi, bins: int):
    frac = (col.astype(jnp.float32) - lo) / (hi - lo)
    idx = jnp.clip((frac * bins).astype(jnp.int32), 0, bins - 1)
    return jnp.zeros((bins,), jnp.int32).at[idx].add(mask.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("g",))
def _masked_grid(x, y, mask, g: int):
    ix = jnp.clip(((x + 180.0) / 360.0 * g).astype(jnp.int32), 0, g - 1)
    iy = jnp.clip(((y + 90.0) / 180.0 * g).astype(jnp.int32), 0, g - 1)
    return jnp.zeros((g, g), jnp.int32).at[iy, ix].add(mask.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("n",))
def _masked_bincount(codes, mask, n: int):
    return jnp.zeros((n,), jnp.int32).at[codes].add(mask.astype(jnp.int32))


def observe_on_device(leaf: sk.Stat, index, mask) -> bool:
    """Try to fold the masked scan into ``leaf`` via a device reduction.
    Returns False when this sketch kind must take the host path."""
    cols = index.device.columns
    sft = index.sft

    if isinstance(leaf, sk.CountStat):
        leaf.observe(int(jnp.sum(mask)))
        return True

    if isinstance(leaf, sk.HistogramStat):
        attr = leaf.attr
        try:
            spec = sft.attribute(attr)
        except KeyError:
            return False
        if attr not in cols or spec.type_name not in ("Int", "Integer", "Float"):
            return False
        counts = np.asarray(_masked_hist(cols[attr], mask,
                                         np.float32(leaf.lo), np.float32(leaf.hi),
                                         leaf.bins))
        leaf.counts += counts.astype(np.int64)
        return True

    if isinstance(leaf, sk.Z2HistogramStat):
        if "xf" not in cols:
            return False
        grid = np.asarray(_masked_grid(cols["xf"], cols["yf"], mask, leaf.g))
        leaf.counts += grid.astype(np.int64)
        return True

    if isinstance(leaf, sk.EnumerationStat):
        vocab = index.vocabs.get(leaf.attr)
        if vocab is None or leaf.attr not in cols:
            return False
        counts = np.asarray(_masked_bincount(cols[leaf.attr], mask, len(vocab)))
        for v, c in zip(vocab, counts):
            if c:
                leaf.counts[v] = leaf.counts.get(v, 0) + int(c)
        return True

    if isinstance(leaf, sk.GroupByStat) and leaf.sub_spec.strip() == "Count()":
        vocab = index.vocabs.get(leaf.attr)
        if vocab is None or leaf.attr not in cols:
            return False
        counts = np.asarray(_masked_bincount(cols[leaf.attr], mask, len(vocab)))
        for v, c in zip(vocab, counts):
            if c:
                sub = leaf.groups.setdefault(v, sk.CountStat())
                sub.observe(int(c))
        return True

    return False


def run_stat(planner, spec: str, f=None, auths=None) -> sk.Stat:
    """Compute a stat spec over matching rows, device reductions first.

    The scan mask is evaluated once (auths fold into it as a visibility-code
    residual, ≙ VisibilityFilter riding the server scan); device-supported
    leaves reduce against it, the rest share one select+observe pass (≙ the
    coprocessor running some aggregations region-side while the client
    computes the rest)."""
    from geomesa_tpu.filter import ir
    from geomesa_tpu.filter.parser import parse_ecql
    from geomesa_tpu.stats.dsl import observe_table, parse_stat

    stat = parse_stat(spec)
    if f is None:
        f = ir.Include()
    elif isinstance(f, str):
        f = parse_ecql(f)

    leaves = stat.stats if isinstance(stat, sk.SeqStat) else [stat]
    restricted = auths is not None and planner.table.visibility is not None
    include = isinstance(f, ir.Include) and not restricted
    plan, mask = planner.scan_mask(f, auths=auths)
    host_leaves = list(leaves)
    if mask is not None:
        host_leaves = [l for l in leaves
                       if not observe_on_device(l, plan.index, mask)]
    if host_leaves:
        # one shared pass for every host-path leaf; INCLUDE observes the
        # master table directly (no select, no copy)
        sub = planner.table if include else \
            planner.table.take(planner.select_indices(f, plan=plan,
                                                      auths=auths))
        for l in host_leaves:
            observe_table(l, sub)
    return stat
