// Native one-pass index-key encoder (the framework's ingest hot loop).
//
// ≙ the reference's per-feature write path Z3IndexKeySpace.toIndexKey
// (/root/reference/geomesa-index-api/src/main/scala/org/locationtech/geomesa/
//  index/index/z3/Z3IndexKeySpace.scala:64-96): BinnedTime split + SFC
// interleave + key assembly. There it runs per feature on the JVM; here it is
// a fused single pass over columnar arrays producing every device plane the
// TPU table needs, so the host never touches the data twice:
//
//   x, y (f64), dtg (i64 ms)  ->  fp62 hi/lo planes (exact device predicates),
//                                 (bin, off) exact binned time,
//                                 z3 Morton key (+ its two u32 sort planes)
//
// Semantics are bit-identical to the numpy reference implementations
// (geomesa_tpu/index/device.py fp62, curves/normalize.py, curves/binnedtime.py,
// curves/zorder.py): same IEEE-754 double operations in the same order. The
// numpy paths remain canonical; parity is pinned by tests/test_native.py.
//
// Built with plain g++ -O3 (no external deps); bound via ctypes. Threaded
// with std::thread — a no-op on single-core hosts, linear speedup elsewhere.

#include <cstdint>
#include <cmath>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kFp62Max = (int64_t(1) << 62) - 1;

// fp62: mirrors device.py fp62() — frac = clip((x-lo)/(hi-lo), 0, 1);
// v = min(floor(ldexp(frac, 62)), 2^62-1); planes (v>>31, v&(2^31-1)).
// Branchless (min/max/ternaries lower to vector blends under -O3); the
// ldexp is an exact power-of-two scale, so a multiply matches it bitwise,
// and frac >= 0 makes int64 truncation identical to floor.
static inline int64_t fp62(double x, double lo, double hi) {
  double frac = (x - lo) / (hi - lo);
  frac = std::min(std::max(frac, 0.0), 1.0);
  int64_t v = (int64_t)(frac * 4611686018427387904.0);  // 2^62
  return std::min(v, kFp62Max);
}

// BitNormalizedDimension.normalize (normalize.py:39-43) with the lenient
// clamp applied first (sfc _check): floor((x - min) * bins/(max-min)),
// x >= max -> max_index. Post-clamp (x - mn) >= 0, so truncation == floor.
static inline int64_t norm_bits(double x, double mn, double mx,
                                double normalizer, int64_t max_index) {
  x = std::max(x, mn);
  int64_t r = (int64_t)((x - mn) * normalizer);
  return x >= mx ? max_index : r;
}

// Morton spreads — same magic masks as curves/zorder.py.
static inline uint64_t spread3(uint64_t x) {
  x &= 0x00000000001FFFFFULL;
  x = (x | (x << 32)) & 0x001F00000000FFFFULL;
  x = (x | (x << 16)) & 0x001F0000FF0000FFULL;
  x = (x | (x << 8)) & 0x100F00F00F00F00FULL;
  x = (x | (x << 4)) & 0x10C30C30C30C30C3ULL;
  x = (x | (x << 2)) & 0x1249249249249249ULL;
  return x;
}

static inline uint64_t spread2(uint64_t x) {
  x &= 0x00000000FFFFFFFFULL;
  x = (x | (x << 16)) & 0x0000FFFF0000FFFFULL;
  x = (x | (x << 8)) & 0x00FF00FF00FF00FFULL;
  x = (x | (x << 4)) & 0x0F0F0F0F0F0F0F0FULL;
  x = (x | (x << 2)) & 0x3333333333333333ULL;
  x = (x | (x << 1)) & 0x5555555555555555ULL;
  return x;
}

static inline int64_t floordiv(int64_t a, int64_t b) {
  int64_t q = a / b;
  q -= (int64_t)((a % b != 0) & ((a < 0) != (b < 0)));
  return q;
}

template <typename F>
void parallel_for(int64_t n, int nthreads, F&& body) {
  if (nthreads <= 1 || n < (1 << 18)) {
    body(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([=, &body] { body(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// period: 0 = day (offset ms), 1 = week (offset seconds). Calendar periods
// (month/year) stay on the numpy path.
//
// Outputs (all length n, caller-allocated):
//   xi/xl/yi/yl : int32 fp62 planes        bin : int16   off : int32
//   xf/yf       : float32 raw coords (aggregation columns)
//   zhi/zlo     : uint32 z3-key sort planes (z >> 31, z & 0x7FFFFFFF)
//   z           : int64 full z3 key (host range pruning)
void gm_z3_encode(const double* x, const double* y, const int64_t* ms,
                  int64_t n, int32_t period, int32_t* xi, int32_t* xl,
                  int32_t* yi, int32_t* yl, float* xf, float* yf,
                  int16_t* bin, int32_t* off,
                  uint32_t* zhi, uint32_t* zlo, int64_t* z, int32_t nthreads) {
  const int64_t period_ms = period == 0 ? 86400000LL : 604800000LL;
  const int64_t off_div = period == 0 ? 1 : 1000;
  const double max_off = period == 0 ? 86400000.0 : 604800.0;
  const double norm_lon = 2097152.0 / 360.0;   // 2^21 / (max-min)
  const double norm_lat = 2097152.0 / 180.0;
  const double norm_t = 2097152.0 / max_off;
  const int64_t max_idx = (1 << 21) - 1;

  parallel_for(n, nthreads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      // lenient clamp (sfc _check) — fp62 clips internally already
      double px = std::min(std::max(x[i], -180.0), 180.0);
      double py = std::min(std::max(y[i], -90.0), 90.0);
      int64_t vx = fp62(px, -180.0, 180.0);
      int64_t vy = fp62(py, -90.0, 90.0);
      xi[i] = (int32_t)(vx >> 31);
      xl[i] = (int32_t)(vx & 0x7FFFFFFF);
      yi[i] = (int32_t)(vy >> 31);
      yl[i] = (int32_t)(vy & 0x7FFFFFFF);
      xf[i] = (float)x[i];
      yf[i] = (float)y[i];

      int64_t b = floordiv(ms[i], period_ms);
      int64_t o = (ms[i] - b * period_ms) / off_div;
      bin[i] = (int16_t)b;
      off[i] = (int32_t)o;

      // Z3Index._sort_keys: t = min(off, time.max), then Z3SFC.index
      double t = (double)o;
      if (t > max_off) t = max_off;
      uint64_t nx = (uint64_t)norm_bits(px, -180.0, 180.0, norm_lon, max_idx);
      uint64_t ny = (uint64_t)norm_bits(py, -90.0, 90.0, norm_lat, max_idx);
      uint64_t nt = (uint64_t)norm_bits(t, 0.0, max_off, norm_t, max_idx);
      uint64_t zz = spread3(nx) | (spread3(ny) << 1) | (spread3(nt) << 2);
      z[i] = (int64_t)zz;
      zhi[i] = (uint32_t)(zz >> 31);
      zlo[i] = (uint32_t)(zz & 0x7FFFFFFF);
    }
  });
}

// Z2 variant: 31-bit normalization, 62-bit Morton key.
void gm_z2_encode(const double* x, const double* y, int64_t n, int32_t* xi,
                  int32_t* xl, int32_t* yi, int32_t* yl, float* xf, float* yf,
                  uint32_t* zhi, uint32_t* zlo, int64_t* z, int32_t nthreads) {
  const double norm_lon = 2147483648.0 / 360.0;  // 2^31 / (max-min)
  const double norm_lat = 2147483648.0 / 180.0;
  const int64_t max_idx = (int64_t(1) << 31) - 1;

  parallel_for(n, nthreads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double px = std::min(std::max(x[i], -180.0), 180.0);
      double py = std::min(std::max(y[i], -90.0), 90.0);
      int64_t vx = fp62(px, -180.0, 180.0);
      int64_t vy = fp62(py, -90.0, 90.0);
      xi[i] = (int32_t)(vx >> 31);
      xl[i] = (int32_t)(vx & 0x7FFFFFFF);
      yi[i] = (int32_t)(vy >> 31);
      yl[i] = (int32_t)(vy & 0x7FFFFFFF);
      xf[i] = (float)x[i];
      yf[i] = (float)y[i];

      uint64_t nx = (uint64_t)norm_bits(px, -180.0, 180.0, norm_lon, max_idx);
      uint64_t ny = (uint64_t)norm_bits(py, -90.0, 90.0, norm_lat, max_idx);
      uint64_t zz = spread2(nx) | (spread2(ny) << 1);
      z[i] = (int64_t)zz;
      zhi[i] = (uint32_t)(zz >> 31);
      zlo[i] = (uint32_t)(zz & 0x7FFFFFFF);
    }
  });
}

// fp62 planes only (extent envelope planes, standalone column encodes).
void gm_fp62(const double* x, int64_t n, double lo, double hi, int32_t* phi,
             int32_t* plo, int32_t nthreads) {
  parallel_for(n, nthreads, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      int64_t v = fp62(x[i], lo, hi);
      phi[i] = (int32_t)(v >> 31);
      plo[i] = (int32_t)(v & 0x7FFFFFFF);
    }
  });
}

// Morton range cover (the query-planning hot loop).
//
// ≙ sfcurve Z2.zranges / Z3.zranges as used by Z3IndexKeySpace.getRanges
// (Z3IndexKeySpace.scala:162-189) — the JVM runs this in single-digit ms and
// it sits on the cold-query path, so the Python BFS (~5ms/cover) moves here
// (~50us). Semantics mirror curves/ranges.py _zranges exactly (parity pinned
// by tests/test_native.py): level-synchronous BFS over the quad/octree,
// contained cells emit tight ranges, the budget/depth stop flushes the live
// frontier as coarse ranges, then sort + adjacent-merge.
//
// blo/bhi: (n_boxes, dims) row-major inclusive int bounds. Returns the
// merged range count written to out_lo/out_hi/out_cont, or -1 if it would
// exceed cap (caller falls back to the Python path).
int64_t gm_zranges(const int64_t* blo, const int64_t* bhi, int64_t n_boxes,
                   int32_t dims, int32_t bits, int64_t max_ranges,
                   int32_t max_levels, int64_t* out_lo, int64_t* out_hi,
                   uint8_t* out_cont, int64_t cap) {
  if (n_boxes == 0) return 0;
  struct ZRange { int64_t lo, hi; uint8_t cont; };
  struct Cell { int64_t c[3]; };
  const int fan = 1 << dims;
  if (max_levels > bits) max_levels = bits;

  std::vector<Cell> cells(1, Cell{{0, 0, 0}});
  std::vector<Cell> live, next;
  std::vector<ZRange> out;
  out.reserve((size_t)std::min<int64_t>(max_ranges + fan, 1 << 20));

  auto emit = [&](const Cell& c, int shift, bool cont) {
    uint64_t z;
    if (dims == 2) {
      z = spread2((uint64_t)(c.c[0] << shift))
          | (spread2((uint64_t)(c.c[1] << shift)) << 1);
    } else {
      z = spread3((uint64_t)(c.c[0] << shift))
          | (spread3((uint64_t)(c.c[1] << shift)) << 1)
          | (spread3((uint64_t)(c.c[2] << shift)) << 2);
    }
    uint64_t span = (shift ? (((uint64_t)1 << (dims * shift)) - 1) : 0);
    out.push_back(ZRange{(int64_t)z, (int64_t)(z + span), (uint8_t)cont});
  };

  int level = 0;
  int64_t emitted = 0;
  while (!cells.empty()) {
    const int shift = bits - level;
    live.clear();
    for (const Cell& c : cells) {
      bool inside = false, touches = false;
      for (int64_t b = 0; b < n_boxes; ++b) {
        bool ins = true, tch = true;
        for (int d = 0; d < dims; ++d) {
          const int64_t clo = c.c[d] << shift;
          const int64_t chi = ((c.c[d] + 1) << shift) - 1;
          const int64_t lo = blo[b * dims + d], hi = bhi[b * dims + d];
          ins &= (lo <= clo) & (chi <= hi);
          tch &= (chi >= lo) & (clo <= hi);
        }
        touches |= tch;
        if (ins) { inside = true; break; }
      }
      if (inside) { emit(c, shift, true); ++emitted; }
      else if (touches) live.push_back(c);
    }
    if (live.empty()) break;
    if (level >= max_levels
        || emitted + (int64_t)live.size() * fan > max_ranges) {
      for (const Cell& c : live) emit(c, shift, false);
      break;
    }
    next.clear();
    next.reserve(live.size() * fan);
    for (const Cell& c : live) {
      for (int ch = 0; ch < fan; ++ch) {
        Cell nc{{0, 0, 0}};
        for (int d = 0; d < dims; ++d)
          nc.c[d] = (c.c[d] << 1) | ((ch >> d) & 1);
        next.push_back(nc);
      }
    }
    cells.swap(next);
    ++level;
  }

  std::sort(out.begin(), out.end(), [](const ZRange& a, const ZRange& b) {
    return a.lo < b.lo || (a.lo == b.lo && a.hi < b.hi);
  });
  int64_t m = 0;
  for (const ZRange& r : out) {
    // hi can be INT64_MAX (root emit): guard the +1 against overflow
    if (m && (out_hi[m - 1] == INT64_MAX || r.lo <= out_hi[m - 1] + 1)) {
      if (r.hi > out_hi[m - 1]) out_hi[m - 1] = r.hi;
      out_cont[m - 1] = out_cont[m - 1] && r.cont;
    } else {
      if (m == cap) return -1;
      out_lo[m] = r.lo;
      out_hi[m] = r.hi;
      out_cont[m] = r.cont;
      ++m;
    }
  }
  return m;
}

}  // extern "C"
