"""Native (C++) runtime kernels for host-side hot loops.

The TPU compute path is JAX/XLA; this package holds the *host* runtime work
that the reference implements on the JVM — the ingest key-encode hot loop
(Z3IndexKeySpace.toIndexKey, SURVEY.md §3.2) — as a fused C++ pass bound via
ctypes (no pybind11 in this image). The shared object compiles on first use
with g++ and is cached next to the source; every entry point has a numpy
fallback, so the package works (slower) without a toolchain.

Parity contract: bit-identical outputs to the numpy paths (device.py fp62,
curves/normalize.py, curves/binnedtime.py, curves/zorder.py), pinned by
tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "encode.cpp")
_SO = os.path.join(_DIR, "_encode.so")

_lib = None
_lock = threading.Lock()
_load_failed = False


def _nthreads() -> int:
    try:
        return max(1, min(os.cpu_count() or 1, 16))
    except Exception:
        return 1


def _src_digest() -> str:
    import hashlib
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _build() -> bool:
    """Compile the shared object (per-process temp name + atomic rename, so
    concurrent first-use from several processes can't install a torn file);
    records the source digest next to it for freshness checks."""
    cxx = os.environ.get("CXX", "g++")
    tmp = f"{_SO}.{os.getpid()}.tmp"
    for flags in (["-O3", "-march=native"], ["-O3"]):  # native may not exist
        cmd = [cxx, *flags, "-shared", "-fPIC", "-std=c++17", "-pthread",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _SO)
            with open(_SO + ".sha", "w") as f:
                f.write(_src_digest())
            return True
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return False


def _load():
    """The compiled library, or None when unavailable (numpy fallback)."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        from geomesa_tpu import config
        if config.NO_NATIVE.get():
            _load_failed = True
            return None
        try:
            digest = _src_digest()
            try:
                with open(_SO + ".sha") as f:
                    fresh = os.path.exists(_SO) and f.read().strip() == digest
            except OSError:
                fresh = False
            if not fresh and not _build():
                _load_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                # stale/foreign binary (different arch or glibc): rebuild once
                if not _build():
                    raise
                lib = ctypes.CDLL(_SO)
            i64, i32, i16, u32, f64, f32 = (
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            )
            lib.gm_z3_encode.argtypes = [
                f64, f64, i64, ctypes.c_int64, ctypes.c_int32,
                i32, i32, i32, i32, f32, f32, i16, i32, u32, u32, i64,
                ctypes.c_int32]
            lib.gm_z2_encode.argtypes = [
                f64, f64, ctypes.c_int64,
                i32, i32, i32, i32, f32, f32, u32, u32, i64, ctypes.c_int32]
            lib.gm_fp62.argtypes = [
                f64, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
                i32, i32, ctypes.c_int32]
            u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            lib.gm_zranges.argtypes = [
                i64, i64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int32, i64, i64, u8, ctypes.c_int64]
            lib.gm_zranges.restype = ctypes.c_int64
            _lib = lib
        except Exception:
            _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


_PERIOD_CODES = {"day": 0, "week": 1}


def z3_encode(x: np.ndarray, y: np.ndarray, ms: np.ndarray, period: str):
    """Fused Z3 build encode. Returns a dict of all build planes, or None
    when the native library or the period (calendar months/years) is
    unsupported — callers fall back to the numpy path."""
    lib = _load()
    code = _PERIOD_CODES.get(str(period).lower())
    if lib is None or code is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    ms = np.ascontiguousarray(ms, dtype=np.int64)
    n = len(x)
    if n:
        # bins ride as int16 (the reference's Short bins, BinnedTime.MAX_BIN);
        # out-of-range epochs (pre-1970 / >2059 for days) take the numpy path
        period_ms = 86_400_000 if code == 0 else 604_800_000
        if not (0 <= int(ms.min()) and int(ms.max()) // period_ms <= 32767):
            return None
    out = {
        "xi": np.empty(n, np.int32), "xl": np.empty(n, np.int32),
        "yi": np.empty(n, np.int32), "yl": np.empty(n, np.int32),
        "xf": np.empty(n, np.float32), "yf": np.empty(n, np.float32),
        "bin16": np.empty(n, np.int16), "off": np.empty(n, np.int32),
        "zhi": np.empty(n, np.uint32), "zlo": np.empty(n, np.uint32),
        "z": np.empty(n, np.int64),
    }
    lib.gm_z3_encode(x, y, ms, n, code, out["xi"], out["xl"], out["yi"],
                     out["yl"], out["xf"], out["yf"], out["bin16"],
                     out["off"], out["zhi"], out["zlo"], out["z"],
                     _nthreads())
    return out


def z2_encode(x: np.ndarray, y: np.ndarray):
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    n = len(x)
    out = {
        "xi": np.empty(n, np.int32), "xl": np.empty(n, np.int32),
        "yi": np.empty(n, np.int32), "yl": np.empty(n, np.int32),
        "xf": np.empty(n, np.float32), "yf": np.empty(n, np.float32),
        "zhi": np.empty(n, np.uint32), "zlo": np.empty(n, np.uint32),
        "z": np.empty(n, np.int64),
    }
    lib.gm_z2_encode(x, y, n, out["xi"], out["xl"], out["yi"], out["yl"],
                     out["xf"], out["yf"], out["zhi"], out["zlo"], out["z"],
                     _nthreads())
    return out


def zranges(blo: np.ndarray, bhi: np.ndarray, dims: int, bits: int,
            max_ranges: int, max_levels: int):
    """Morton range cover (≙ sfcurve zranges on the query-planning path).
    (lo, hi, contained) merged inclusive z-interval arrays, or None for the
    Python fallback. blo/bhi: (n_boxes, dims) inclusive normalized ints."""
    lib = _load()
    if lib is None:
        return None
    blo = np.ascontiguousarray(blo, dtype=np.int64)
    bhi = np.ascontiguousarray(bhi, dtype=np.int64)
    cap = 2 * int(max_ranges) + 4 * (1 << dims)
    lo = np.empty(cap, np.int64)
    hi = np.empty(cap, np.int64)
    cont = np.empty(cap, np.uint8)
    n = lib.gm_zranges(blo, bhi, blo.shape[0], dims, bits, int(max_ranges),
                       int(max_levels), lo, hi, cont, cap)
    if n < 0:
        return None
    return lo[:n], hi[:n], cont[:n].astype(bool)


def fp62_planes(x: np.ndarray, lo: float, hi: float):
    """(hi_plane, lo_plane) int32 — native fp62, or None for numpy fallback."""
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    n = len(x)
    phi = np.empty(n, np.int32)
    plo = np.empty(n, np.int32)
    lib.gm_fp62(x, n, lo, hi, phi, plo, _nthreads())
    return phi, plo
