"""Feature export formats.

≙ reference export surface (tools/export/formats/ExportFormat.scala: arrow/
avro/bin/csv/geojson/gml/json/leaflet/orc/parquet/shp/tsv/wkt) — every
format the reference CLI exports is covered: csv/tsv, geojson, json-lines,
wkt, arrow IPC, parquet, avro, orc, gml, shp (ESRI shapefile), a
self-contained leaflet HTML map, npz (the checkpoint codec), and bin via
aggregates.bin."""

from __future__ import annotations

import csv
import io
import json
from typing import Optional

import numpy as np

from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.table import FeatureTable, StringColumn

FORMATS = ("csv", "tsv", "geojson", "json", "wkt", "arrow", "parquet",
           "avro", "orc", "gml", "shp", "leaflet")


def export(table: FeatureTable, fmt: str, path: Optional[str] = None):
    """Write ``table`` in ``fmt`` to ``path`` (or return a str for text
    formats when path is None)."""
    fmt = fmt.lower()
    if fmt in ("csv", "tsv"):
        return _delimited(table, "," if fmt == "csv" else "\t", path)
    if fmt == "geojson":
        return _geojson(table, path)
    if fmt == "json":
        return _jsonlines(table, path)
    if fmt == "wkt":
        return _wkt(table, path)
    if fmt == "arrow":
        from geomesa_tpu.io.arrow import write_ipc
        if path is None:
            raise ValueError("arrow export requires a path")
        write_ipc(table, path)
        return path
    if fmt == "avro":
        from geomesa_tpu.convert.avro import write_avro
        if path is None:
            raise ValueError("avro export requires a path")
        write_avro(table, path)
        return path
    if fmt == "parquet":
        import pyarrow.parquet as pq
        from geomesa_tpu.io.arrow import to_arrow
        if path is None:
            raise ValueError("parquet export requires a path")
        pq.write_table(to_arrow(table), path)
        return path
    if fmt == "orc":
        from pyarrow import orc
        from geomesa_tpu.io.arrow import to_arrow, orc_compatible
        if path is None:
            raise ValueError("orc export requires a path")
        orc.write_table(orc_compatible(to_arrow(table)), path)
        return path
    if fmt == "gml":
        return _gml(table, path)
    if fmt == "leaflet":
        return _leaflet(table, path)
    if fmt == "shp":
        if path is None:
            raise ValueError("shp export requires a path (base name)")
        return _shapefile(table, path)
    raise ValueError(f"Unknown export format {fmt!r} (have {FORMATS})")


def _out(path: Optional[str]):
    return open(path, "w", newline="") if path else io.StringIO()


def _finish(f, path):
    if path:
        f.close()
        return path
    return f.getvalue()


def _iso(ms: int) -> str:
    return str(np.datetime64(int(ms), "ms")) + "Z"


def _cell(col, attr, i):
    if isinstance(col, GeometryArray):
        return col.wkt(i)
    if isinstance(col, StringColumn):
        return col.vocab[col.codes[i]]
    v = col[i]
    if attr.type_name == "Date":
        return _iso(int(v))
    return v.item() if isinstance(v, np.generic) else v


def _delimited(table: FeatureTable, delim: str, path):
    f = _out(path)
    w = csv.writer(f, delimiter=delim)
    attrs = table.sft.attributes
    w.writerow(["id"] + [a.name for a in attrs])
    cols = [table.columns[a.name] for a in attrs]
    for i in range(len(table)):
        w.writerow([table.fids[i]] + [_cell(c, a, i) for c, a in zip(cols, attrs)])
    return _finish(f, path)


def _geojson_geometry(garr: GeometryArray, i: int) -> dict:
    from geomesa_tpu.features import geometry as geo
    code, data = garr.shape(i)
    return {"type": geo.TYPE_NAMES[code], "coordinates": data}


def _geojson(table: FeatureTable, path):
    garr = table.geometry() if table.sft.geometry_attribute else None
    gname = table.sft.geometry_attribute.name if garr is not None else None
    feats = []
    for i in range(len(table)):
        props = {}
        for a in table.sft.attributes:
            if a.name == gname:
                continue
            props[a.name] = _cell(table.columns[a.name], a, i)
        feats.append({
            "type": "Feature",
            "id": str(table.fids[i]),
            "geometry": None if garr is None else _geojson_geometry(garr, i),
            "properties": props,
        })
    doc = {"type": "FeatureCollection", "features": feats}
    f = _out(path)
    json.dump(doc, f)
    return _finish(f, path)


def _jsonlines(table: FeatureTable, path):
    f = _out(path)
    for row in table.to_dicts():
        json.dump({k: (v.item() if isinstance(v, np.generic) else v)
                   for k, v in row.items()}, f)
        f.write("\n")
    return _finish(f, path)


def _wkt(table: FeatureTable, path):
    garr = table.geometry()
    f = _out(path)
    for i in range(len(table)):
        f.write(garr.wkt(i) + "\n")
    return _finish(f, path)


# -- GML (Geography Markup Language; ≙ ExportFormat.Gml / GML3 encoder) ------


def _gml_coords(pts) -> str:
    return " ".join(f"{float(p[0])!r} {float(p[1])!r}" for p in pts)


def _gml_geometry(code: int, data) -> str:
    from geomesa_tpu.features import geometry as geo
    srs = ' srsName="urn:ogc:def:crs:EPSG::4326"'
    if code == geo.POINT:
        return (f"<gml:Point{srs}><gml:pos>{float(data[0])!r} "
                f"{float(data[1])!r}</gml:pos></gml:Point>")
    if code == geo.LINESTRING:
        return (f"<gml:LineString{srs}><gml:posList>{_gml_coords(data)}"
                "</gml:posList></gml:LineString>")
    if code == geo.POLYGON:
        rings = [f"<gml:{tag}><gml:LinearRing><gml:posList>"
                 f"{_gml_coords(r)}</gml:posList></gml:LinearRing></gml:{tag}>"
                 for r, tag in zip(data, ["exterior"]
                                   + ["interior"] * (len(data) - 1))]
        return f"<gml:Polygon{srs}>{''.join(rings)}</gml:Polygon>"
    if code == geo.MULTIPOINT:
        members = "".join(f"<gml:pointMember>{_gml_geometry(geo.POINT, p)}"
                          "</gml:pointMember>" for p in data)
        return f"<gml:MultiPoint{srs}>{members}</gml:MultiPoint>"
    if code == geo.MULTILINESTRING:
        members = "".join(
            f"<gml:curveMember>{_gml_geometry(geo.LINESTRING, l)}"
            "</gml:curveMember>" for l in data)
        return f"<gml:MultiCurve{srs}>{members}</gml:MultiCurve>"
    if code == geo.MULTIPOLYGON:
        members = "".join(
            f"<gml:surfaceMember>{_gml_geometry(geo.POLYGON, p)}"
            "</gml:surfaceMember>" for p in data)
        return f"<gml:MultiSurface{srs}>{members}</gml:MultiSurface>"
    raise ValueError(f"Unsupported geometry code {code}")


def _gml(table: FeatureTable, path):
    from xml.sax.saxutils import escape, quoteattr
    sft = table.sft
    gname = sft.geometry_attribute.name if sft.geometry_attribute else None
    garr = table.geometry() if gname else None
    f = _out(path)
    f.write('<?xml version="1.0" encoding="UTF-8"?>\n')
    f.write('<gml:FeatureCollection '
            'xmlns:gml="http://www.opengis.net/gml/3.2" '
            'xmlns:gt="urn:geomesa-tpu">\n')
    for i in range(len(table)):
        f.write(f' <gml:featureMember>\n  <gt:{sft.name} '
                f'gml:id={quoteattr(str(table.fids[i]))}>\n')
        for a in sft.attributes:
            if a.name == gname:
                code, data = garr.shape(i)
                f.write(f"   <gt:{a.name}>{_gml_geometry(code, data)}"
                        f"</gt:{a.name}>\n")
            else:
                v = _cell(table.columns[a.name], a, i)
                f.write(f"   <gt:{a.name}>{escape(str(v))}</gt:{a.name}>\n")
        f.write(f"  </gt:{sft.name}>\n </gml:featureMember>\n")
    f.write("</gml:FeatureCollection>\n")
    return _finish(f, path)


# -- ESRI shapefile (.shp/.shx/.dbf; ≙ ExportFormat.Shp) ---------------------
# Wire layouts per the public ESRI whitepaper; the reader counterpart lives
# in convert/formats.py (read_shapefile) and round-trips these files.


def _ring_area(pts) -> float:
    a = 0.0
    for i in range(len(pts) - 1):
        a += pts[i][0] * pts[i + 1][1] - pts[i + 1][0] * pts[i][1]
    return a / 2.0


def _shp_record(code: int, data):
    """(shape_type, content bytes after the type word) for one geometry."""
    import struct
    from geomesa_tpu.features import geometry as geo

    def parts_record(shape_type, parts):
        pts = [p for part in parts for p in part]
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        head = struct.pack("<4d", min(xs), min(ys), max(xs), max(ys))
        head += struct.pack("<ii", len(parts), len(pts))
        off = 0
        for part in parts:
            head += struct.pack("<i", off)
            off += len(part)
        body = b"".join(struct.pack("<dd", float(p[0]), float(p[1]))
                        for p in pts)
        return shape_type, head + body

    if code == geo.POINT:
        return 1, struct.pack("<dd", float(data[0]), float(data[1]))
    if code == geo.MULTIPOINT:
        xs = [p[0] for p in data]
        ys = [p[1] for p in data]
        head = struct.pack("<4d", min(xs), min(ys), max(xs), max(ys))
        head += struct.pack("<i", len(data))
        body = b"".join(struct.pack("<dd", float(p[0]), float(p[1]))
                        for p in data)
        return 8, head + body
    if code == geo.LINESTRING:
        return parts_record(3, [data])
    if code == geo.MULTILINESTRING:
        return parts_record(3, data)
    if code in (geo.POLYGON, geo.MULTIPOLYGON):
        polys = [data] if code == geo.POLYGON else data
        rings = []
        for poly in polys:
            for j, ring in enumerate(poly):
                # spec orientation: exterior clockwise (negative signed
                # area), holes counter-clockwise
                cw = _ring_area(ring) < 0
                want_cw = j == 0
                rings.append(list(ring) if cw == want_cw else list(ring)[::-1])
        return parts_record(5, rings)
    raise ValueError(f"Unsupported geometry code {code} for shapefile")


def _dbf_fields(sft):
    """(name, type, width, decimals, formatter) per non-geometry attr."""
    out = []
    taken = set()
    for a in sft.attributes:
        if a.is_geometry:
            continue
        # DBF names are 10 chars: unique the truncations or the reader
        # merges colliding columns into interleaved garbage. Loop because a
        # renamed candidate can itself collide (attribute1/attribute12)
        base10 = a.name[:10]
        name, k = base10, 0
        while name in taken:
            k += 1
            name = f"{base10[:10 - len(str(k))]}{k}"
        taken.add(name)
        if a.type_name in ("Int", "Integer", "Long"):
            # width 20 holds any int64 incl. the sign; never slice digits
            out.append((name, b"N", 20, 0,
                        lambda v: f"{int(v):>20d}"))
        elif a.type_name in ("Float", "Double"):
            out.append((name, b"F", 19, 11,
                        lambda v: f"{float(v):>19.11g}"[:19].rjust(19)))
        elif a.type_name == "Date":
            out.append((name, b"D", 8, 0,
                        lambda v: str(np.datetime64(int(v), "ms"))[:10]
                        .replace("-", "")))
        elif a.type_name == "Boolean":
            out.append((name, b"L", 1, 0,
                        lambda v: "T" if v else "F"))
        else:
            out.append((name, b"C", 64, 0,
                        lambda v: str(v)[:64].ljust(64)))
    return out


def _shapefile(table: FeatureTable, path: str) -> str:
    """Write ``path``.shp/.shx/.dbf. Geometry column required."""
    import os
    import struct

    base, ext = os.path.splitext(path)
    if ext not in ("", ".shp"):
        base = path
    garr = table.geometry()
    n = len(table)
    records = []
    shape_type = 0
    for i in range(n):
        st, content = _shp_record(*garr.shape(i))
        if shape_type == 0:
            shape_type = st
        elif st != shape_type:
            raise ValueError("shapefile export needs a single shape type "
                             f"(got {shape_type} and {st})")
        records.append(struct.pack("<i", st) + content)

    bbs = garr.bboxes()
    if n:
        bbox = (float(bbs[:, 0].min()), float(bbs[:, 1].min()),
                float(bbs[:, 2].max()), float(bbs[:, 3].max()))
    else:
        bbox = (0.0, 0.0, 0.0, 0.0)

    def header(total_words):
        return (struct.pack(">i", 9994) + b"\x00" * 20
                + struct.pack(">i", total_words)
                + struct.pack("<ii", 1000, shape_type)
                + struct.pack("<4d", *bbox) + struct.pack("<4d", 0, 0, 0, 0))

    shp_words = 50 + sum(4 + len(r) // 2 for r in records)
    with open(base + ".shp", "wb") as f:
        f.write(header(shp_words))
        offset = 50
        offsets = []
        for num, rec in enumerate(records, 1):
            f.write(struct.pack(">ii", num, len(rec) // 2) + rec)
            offsets.append((offset, len(rec) // 2))
            offset += 4 + len(rec) // 2
    with open(base + ".shx", "wb") as f:
        f.write(header(50 + 4 * n))
        for off, words in offsets:
            f.write(struct.pack(">ii", off, words))

    fields = _dbf_fields(table.sft)
    rec_size = 1 + sum(w for _, _, w, _, _ in fields)
    attrs = [a for a in table.sft.attributes if not a.is_geometry]
    with open(base + ".dbf", "wb") as f:
        import datetime
        today = datetime.date.today()
        hdr_size = 32 + 32 * len(fields) + 1
        # header date bytes are (years since 1900, month, day)
        f.write(struct.pack("<BBBBIHH20x", 3, today.year - 1900, today.month,
                            today.day, n, hdr_size, rec_size))
        for name, typ, width, dec, _fmt in fields:
            f.write(name.encode("ascii", "replace")[:11].ljust(11, b"\x00")
                    + typ + b"\x00" * 4
                    + struct.pack("<BB", width, dec) + b"\x00" * 14)
        f.write(b"\x0d")
        for i in range(n):
            row = b" "
            for (name, typ, width, dec, fmt), a in zip(fields, attrs):
                v = _cell(table.columns[a.name], a, i)
                if a.type_name == "Date":
                    v = int(np.asarray(table.columns[a.name])[i])
                row += fmt(v).encode("ascii", "replace")[:width].ljust(width)
            f.write(row)
        f.write(b"\x1a")
    return base + ".shp"


# -- Leaflet map (self-contained HTML; ≙ LeafletMapExporter) -----------------


_LEAFLET_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>geomesa-tpu export</title>
<meta name="viewport" content="width=device-width, initial-scale=1.0"/>
<link rel="stylesheet"
 href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>html, body, #map {{ height: 100%; margin: 0; }}</style>
</head>
<body>
<div id="map"></div>
<script>
var features = {geojson};
var map = L.map('map');
L.tileLayer('https://{{s}}.tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
            {{attribution: '&copy; OpenStreetMap contributors'}}).addTo(map);
var layer = L.geoJSON(features, {{
  pointToLayer: function (f, latlng) {{
    return L.circleMarker(latlng, {{radius: 4}});
  }},
  onEachFeature: function (f, l) {{
    var esc = function (s) {{
      return String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;')
                      .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
    }};
    var rows = Object.entries(f.properties || {{}}).map(
      function (kv) {{ return esc(kv[0]) + ': ' + esc(kv[1]); }});
    if (rows.length) l.bindPopup(rows.join('<br/>'));
  }}
}}).addTo(map);
var b = layer.getBounds();
if (b.isValid()) {{ map.fitBounds(b); }} else {{ map.setView([0, 0], 2); }}
</script>
</body>
</html>
"""


def _leaflet(table: FeatureTable, path):
    """Self-contained HTML map with the features embedded as GeoJSON (the
    tile layer loads from OSM in the viewer's browser, as the reference's
    template does). The embedded JSON escapes '</' so a string value
    containing '</script>' can neither break the document nor inject
    script; popup values HTML-escape browser-side."""
    geojson = _geojson(table, None).replace("</", "<\\/")
    doc = _LEAFLET_HTML.format(geojson=geojson)
    f = _out(path)
    f.write(doc)
    return _finish(f, path)
