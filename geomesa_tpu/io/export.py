"""Feature export formats.

≙ reference export surface (tools/export/formats/ExportFormat.scala: arrow/
avro/bin/csv/geojson/gml/json/leaflet/orc/parquet/shp/tsv/wkt). The formats
that matter for a columnar TPU store: csv/tsv, geojson, json-lines, wkt,
arrow IPC, parquet, npz (the checkpoint codec), bin (aggregates.bin)."""

from __future__ import annotations

import csv
import io
import json
from typing import Optional

import numpy as np

from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.table import FeatureTable, StringColumn

FORMATS = ("csv", "tsv", "geojson", "json", "wkt", "arrow", "parquet", "avro")


def export(table: FeatureTable, fmt: str, path: Optional[str] = None):
    """Write ``table`` in ``fmt`` to ``path`` (or return a str for text
    formats when path is None)."""
    fmt = fmt.lower()
    if fmt in ("csv", "tsv"):
        return _delimited(table, "," if fmt == "csv" else "\t", path)
    if fmt == "geojson":
        return _geojson(table, path)
    if fmt == "json":
        return _jsonlines(table, path)
    if fmt == "wkt":
        return _wkt(table, path)
    if fmt == "arrow":
        from geomesa_tpu.io.arrow import write_ipc
        if path is None:
            raise ValueError("arrow export requires a path")
        write_ipc(table, path)
        return path
    if fmt == "avro":
        from geomesa_tpu.convert.avro import write_avro
        if path is None:
            raise ValueError("avro export requires a path")
        write_avro(table, path)
        return path
    if fmt == "parquet":
        import pyarrow.parquet as pq
        from geomesa_tpu.io.arrow import to_arrow
        if path is None:
            raise ValueError("parquet export requires a path")
        pq.write_table(to_arrow(table), path)
        return path
    raise ValueError(f"Unknown export format {fmt!r} (have {FORMATS})")


def _out(path: Optional[str]):
    return open(path, "w", newline="") if path else io.StringIO()


def _finish(f, path):
    if path:
        f.close()
        return path
    return f.getvalue()


def _iso(ms: int) -> str:
    return str(np.datetime64(int(ms), "ms")) + "Z"


def _cell(col, attr, i):
    if isinstance(col, GeometryArray):
        return col.wkt(i)
    if isinstance(col, StringColumn):
        return col.vocab[col.codes[i]]
    v = col[i]
    if attr.type_name == "Date":
        return _iso(int(v))
    return v.item() if isinstance(v, np.generic) else v


def _delimited(table: FeatureTable, delim: str, path):
    f = _out(path)
    w = csv.writer(f, delimiter=delim)
    attrs = table.sft.attributes
    w.writerow(["id"] + [a.name for a in attrs])
    cols = [table.columns[a.name] for a in attrs]
    for i in range(len(table)):
        w.writerow([table.fids[i]] + [_cell(c, a, i) for c, a in zip(cols, attrs)])
    return _finish(f, path)


def _geojson_geometry(garr: GeometryArray, i: int) -> dict:
    from geomesa_tpu.features import geometry as geo
    code, data = garr.shape(i)
    return {"type": geo.TYPE_NAMES[code], "coordinates": data}


def _geojson(table: FeatureTable, path):
    garr = table.geometry() if table.sft.geometry_attribute else None
    gname = table.sft.geometry_attribute.name if garr is not None else None
    feats = []
    for i in range(len(table)):
        props = {}
        for a in table.sft.attributes:
            if a.name == gname:
                continue
            props[a.name] = _cell(table.columns[a.name], a, i)
        feats.append({
            "type": "Feature",
            "id": str(table.fids[i]),
            "geometry": None if garr is None else _geojson_geometry(garr, i),
            "properties": props,
        })
    doc = {"type": "FeatureCollection", "features": feats}
    f = _out(path)
    json.dump(doc, f)
    return _finish(f, path)


def _jsonlines(table: FeatureTable, path):
    f = _out(path)
    for row in table.to_dicts():
        json.dump({k: (v.item() if isinstance(v, np.generic) else v)
                   for k, v in row.items()}, f)
        f.write("\n")
    return _finish(f, path)


def _wkt(table: FeatureTable, path):
    garr = table.geometry()
    f = _out(path)
    for i in range(len(table)):
        f.write(garr.wkt(i) + "\n")
    return _finish(f, path)
