"""Partitioned file-system storage (FSDS).

≙ the reference's geomesa-fs module (SURVEY.md §2.6): a partition-scheme
directory layout (Z2Scheme / DateTimeScheme / AttributeScheme /
CompositeScheme, fs-storage-common/.../partitions/) over Parquet or ORC
files (fs-storage-parquet / fs-storage-orc), with metadata in a sidecar
file, query-time partition pruning from the filter, projection push-down
on reads, and per-partition compaction
(AbstractFileSystemStorage.scala:395).

Layout:  root/_metadata.json
         root/<partition>/<uuid>.parquet|.orc  (one file per write batch)

Queries read ONLY the partitions the filter can touch (z2 cells from the
bbox extraction, date buckets from the interval extraction, attribute
values from equality predicates), then refine exactly on the host.
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.evaluate import evaluate as _evaluate
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals
from geomesa_tpu.filter.parser import parse_ecql


class PartitionScheme:
    """Row → partition-name mapping + filter → partition pruning."""

    name = "base"

    def partition_of(self, table: FeatureTable) -> np.ndarray:
        raise NotImplementedError

    def matching(self, f: Optional[ir.Filter], sft,
                 present: Sequence[str]) -> List[str]:
        """Subset of ``present`` partitions the filter can match (superset
        semantics — refinement happens after the read)."""
        return list(present)

    def validate(self, sft) -> None:
        """Reject scheme/SFT combinations with unsound pruning."""

    def to_dict(self) -> dict:
        return {"scheme": self.name}

    @staticmethod
    def from_dict(d: dict) -> "PartitionScheme":
        s = d["scheme"]
        if s == "z2":
            return Z2Scheme(d.get("bits", 4))
        if s == "datetime":
            return DateTimeScheme(d.get("period", "day"))
        if s == "attribute":
            return AttributeScheme(d["attribute"])
        if s == "composite":
            return CompositeScheme([PartitionScheme.from_dict(x)
                                    for x in d["parts"]])
        raise ValueError(f"Unknown partition scheme {s!r}")


class Z2Scheme(PartitionScheme):
    """2^bits × 2^bits lon/lat grid cells (≙ fs Z2Scheme).

    POINT layers only: extents would partition by bbox center while pruning
    follows the query bbox, silently missing wide features — the storage
    constructor enforces the restriction."""

    name = "z2"

    def __init__(self, bits: int = 4):
        self.bits = int(bits)

    def validate(self, sft) -> None:
        g = sft.geometry_attribute
        if g is None or g.type_name != "Point":
            raise ValueError("Z2Scheme requires a Point geometry layer")

    def _cells(self, x, y):
        g = 1 << self.bits
        ix = np.clip(((np.asarray(x) + 180.0) * (g / 360.0)).astype(np.int64),
                     0, g - 1)
        iy = np.clip(((np.asarray(y) + 90.0) * (g / 180.0)).astype(np.int64),
                     0, g - 1)
        return ix, iy

    def partition_of(self, table):
        bb = table.geometry().bboxes()
        ix, iy = self._cells((bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2)
        return np.asarray([f"z2_{self.bits}_{a}_{b}"
                           for a, b in zip(ix, iy)], dtype=object)

    def matching(self, f, sft, present):
        geom = sft.geometry_attribute
        if f is None or geom is None:
            return list(present)
        ext = extract_bboxes(f, geom.name)
        if ext.unconstrained:
            return list(present)
        keep = set()
        g = 1 << self.bits
        for xmin, ymin, xmax, ymax in ext.boxes:
            ix0, iy0 = self._cells(np.array([xmin]), np.array([ymin]))
            ix1, iy1 = self._cells(np.array([xmax]), np.array([ymax]))
            for a in range(int(ix0[0]), int(ix1[0]) + 1):
                for b in range(int(iy0[0]), int(iy1[0]) + 1):
                    keep.add(f"z2_{self.bits}_{a}_{b}")
        return [p for p in present if p in keep]

    def to_dict(self):
        return {"scheme": "z2", "bits": self.bits}


class DateTimeScheme(PartitionScheme):
    """Daily/weekly time buckets (≙ fs DateTimeScheme)."""

    name = "datetime"
    _MS = {"day": 86_400_000, "week": 7 * 86_400_000}

    def __init__(self, period: str = "day"):
        if period not in self._MS:
            raise ValueError(f"period must be day|week, got {period!r}")
        self.period = period

    def partition_of(self, table):
        dtg = table.dtg()
        if dtg is None:
            raise ValueError("DateTimeScheme needs a dtg attribute")
        b = np.asarray(dtg, dtype=np.int64) // self._MS[self.period]
        return np.asarray([f"{self.period}_{v}" for v in b], dtype=object)

    def matching(self, f, sft, present):
        dtg = sft.dtg_attribute
        if f is None or dtg is None:
            return list(present)
        iv = extract_intervals(f, dtg.name)
        if iv.unconstrained:
            return list(present)
        ms = self._MS[self.period]
        # test each PRESENT bucket against the intervals (enumerating the
        # interval hangs on open-ended predicates whose sentinel spans
        # ~5e10 buckets)
        prefix = f"{self.period}_"
        out = []
        for p in present:
            if not p.startswith(prefix):
                continue
            try:
                b = int(p[len(prefix):])
            except ValueError:
                continue
            b0, b1 = b * ms, (b + 1) * ms
            if any(int(lo) < b1 and int(hi) >= b0 for lo, hi in iv.intervals):
                out.append(p)
        return out

    def to_dict(self):
        return {"scheme": "datetime", "period": self.period}


class AttributeScheme(PartitionScheme):
    """One partition per attribute value (≙ fs AttributeScheme). Values
    sanitize into a filesystem-safe alphabet (a raw '/..' in a value must
    not escape the storage root or corrupt the directory layout)."""

    name = "attribute"

    def __init__(self, attribute: str):
        self.attribute = attribute

    @staticmethod
    def _safe(v: str) -> str:
        import re as _re
        return _re.sub(r"[^A-Za-z0-9_.:-]", "-", str(v))[:128]

    def partition_of(self, table):
        col = table.columns[self.attribute]
        if isinstance(col, StringColumn):
            vals = col.decode(np.arange(len(col)))
        else:
            vals = [str(v) for v in np.asarray(col)]
        return np.asarray([f"{self.attribute}_{self._safe(v)}" for v in vals],
                          dtype=object)

    def matching(self, f, sft, present):
        if f is None:
            return list(present)
        vals = _equality_values(f, self.attribute)
        if vals is None:
            return list(present)
        keep = {f"{self.attribute}_{self._safe(v)}" for v in vals}
        return [p for p in present if p in keep]

    def to_dict(self):
        return {"scheme": "attribute", "attribute": self.attribute}


class CompositeScheme(PartitionScheme):
    """Nested schemes → nested directories (≙ fs CompositeScheme)."""

    name = "composite"

    def __init__(self, parts: Sequence[PartitionScheme]):
        self.parts = list(parts)

    def validate(self, sft) -> None:
        for p in self.parts:
            p.validate(sft)

    def partition_of(self, table):
        subs = [p.partition_of(table) for p in self.parts]
        return np.asarray(["/".join(row) for row in zip(*subs)], dtype=object)

    def matching(self, f, sft, present):
        split = [p.split("/") for p in present]
        keep = []
        for parts in split:
            ok = True
            for scheme, part in zip(self.parts, parts):
                if not scheme.matching(f, sft, [part]):
                    ok = False
                    break
            if ok:
                keep.append("/".join(parts))
        return keep

    def to_dict(self):
        return {"scheme": "composite",
                "parts": [p.to_dict() for p in self.parts]}


def _equality_values(f: ir.Filter, attr: str) -> Optional[set]:
    """Values `attr` must equal for the filter to match, or None when the
    filter doesn't pin the attribute (AND intersects, OR unions)."""
    if isinstance(f, ir.Cmp) and f.attr == attr and f.op == "=":
        return {str(f.value)}
    if isinstance(f, ir.In) and f.attr == attr:
        return {str(v) for v in f.values}
    if isinstance(f, ir.And):
        vals = None
        for c in f.children:
            v = _equality_values(c, attr)
            if v is not None:
                vals = v if vals is None else (vals & v)
        return vals
    if isinstance(f, ir.Or):
        out = set()
        for c in f.children:
            v = _equality_values(c, attr)
            if v is None:
                return None
            out |= v
        return out
    return None


class FileSystemStorage:
    """Partitioned columnar store (Parquet or ORC files) with pruned reads
    and compaction (≙ geomesa-fs-storage-parquet / -orc,
    OrcFileSystemStorage.scala)."""

    _META = "_metadata.json"
    ENCODINGS = ("parquet", "orc")

    def __init__(self, root: str, sft: Optional[SimpleFeatureType] = None,
                 scheme: Optional[PartitionScheme] = None,
                 encoding: str = "parquet"):
        self.root = root
        os.makedirs(root, exist_ok=True)
        meta_path = os.path.join(root, self._META)
        if os.path.exists(meta_path):
            with open(meta_path) as fh:
                meta = json.load(fh)
            self.sft = SimpleFeatureType.from_spec(meta["name"], meta["spec"])
            self.scheme = PartitionScheme.from_dict(meta["scheme"])
            self.encoding = meta.get("encoding", "parquet")
        else:
            if sft is None or scheme is None:
                raise ValueError("New storage needs sft= and scheme=")
            if encoding not in self.ENCODINGS:
                raise ValueError(f"encoding must be one of {self.ENCODINGS}")
            scheme.validate(sft)
            self.sft = sft
            self.scheme = scheme
            self.encoding = encoding
            with open(meta_path, "w") as fh:
                json.dump({"name": sft.name, "spec": sft.to_spec(),
                           "scheme": scheme.to_dict(),
                           "encoding": encoding}, fh)

    # -- file codec (parquet | orc) ------------------------------------------

    @property
    def _ext(self) -> str:
        return "." + self.encoding

    def _write_file(self, at, path: str) -> None:
        if self.encoding == "orc":
            from pyarrow import orc
            from geomesa_tpu.io.arrow import orc_compatible
            orc.write_table(orc_compatible(at), path)
        else:
            import pyarrow.parquet as pq
            pq.write_table(at, path)

    def _read_file(self, path: str, columns: Optional[List[str]] = None):
        """Arrow table, optionally projected to ``columns`` (both readers
        push column pruning into the file format)."""
        if self.encoding == "orc":
            from pyarrow import orc
            return orc.ORCFile(path).read(columns=columns)
        import pyarrow.parquet as pq
        return pq.read_table(path, columns=columns)

    # -- writes --------------------------------------------------------------

    def write(self, table: FeatureTable) -> Dict[str, int]:
        """Append a batch: rows split by partition, one new file per touched
        partition (compaction merges later)."""
        from geomesa_tpu.io.arrow import to_arrow

        parts = self.scheme.partition_of(table)
        out: Dict[str, int] = {}
        for p in np.unique(parts):
            rows = np.flatnonzero(parts == p)
            sub = table.take(rows)
            pdir = os.path.join(self.root, str(p))
            os.makedirs(pdir, exist_ok=True)
            self._write_file(to_arrow(sub), os.path.join(
                pdir, f"{uuid.uuid4().hex}{self._ext}"))
            out[str(p)] = len(rows)
        return out

    # -- reads ---------------------------------------------------------------

    def partitions(self) -> List[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            if any(f.endswith(self._ext) for f in files):
                out.append(os.path.relpath(dirpath, self.root))
        return sorted(out)

    def files(self, partition: str) -> List[str]:
        pdir = os.path.join(self.root, partition)
        return sorted(os.path.join(pdir, f) for f in os.listdir(pdir)
                      if f.endswith(self._ext))

    def read(self, f=None) -> FeatureTable:
        """Read matching features: partition pruning → column-pruned reads →
        exact host refine (≙ the FSDS query path: prune, columnar scan,
        client filter).

        Projection push-down (≙ ArrowFilterOptimizer / the ORC reader's
        search-argument schemas): only the filter's referenced columns
        hydrate to evaluate the mask; the remaining columns of a file read
        back only for the rows that matched (arrow-level take BEFORE the
        python-side decode, so non-matching rows never pay WKB/dictionary
        conversion)."""
        from geomesa_tpu.io.arrow import from_arrow

        fir = parse_ecql(f) if isinstance(f, str) else f
        unfiltered = fir is None or isinstance(fir, ir.Include)
        fcols = None if unfiltered else ir.attributes_of(fir)
        proj = None
        if fcols:
            proj_attrs = [a for a in self.sft.attributes if a.name in fcols]
            if {a.name for a in proj_attrs} == fcols \
                    and len(proj_attrs) < len(self.sft.attributes):
                proj = SimpleFeatureType(self.sft.name, proj_attrs,
                                         self.sft.user_data)
        parts = self.scheme.matching(fir, self.sft, self.partitions())
        tables = []
        for p in parts:
            for fp in self.files(p):
                if unfiltered:
                    t = from_arrow(self._read_file(fp), self.sft)
                elif proj is not None:
                    pnames = [a.name for a in proj.attributes]
                    at1 = self._read_file(fp, columns=pnames)
                    tf = from_arrow(at1, proj)
                    rows = np.flatnonzero(_evaluate(fir, tf))
                    if len(rows) == 0:
                        continue
                    # phase 2: only the columns phase 1 didn't read — the
                    # already-hydrated filter columns append at arrow level
                    # (never re-read; never decode non-matching rows). Files
                    # always store __fid__ + every attribute (to_arrow), so
                    # the remainder is schema-derived and never empty (proj
                    # is a strict attribute subset and __fid__ remains)
                    rest = [c for c in
                            ["__fid__"] + [a.name for a in self.sft.attributes]
                            if c not in set(pnames)]
                    at = self._read_file(fp, columns=rest).take(rows)
                    for name in pnames:
                        at = at.append_column(at1.schema.field(name),
                                              at1.column(name).take(rows))
                    t = from_arrow(at, self.sft)
                else:
                    # filter needs more than attribute columns (fids) or an
                    # unknown attribute: full hydrate + refine
                    t = from_arrow(self._read_file(fp), self.sft)
                    t = t.take(np.flatnonzero(_evaluate(fir, t)))
                if len(t):
                    tables.append(t)
        if not tables:
            from geomesa_tpu.features.geometry import GeometryArray
            return FeatureTable.build(self.sft, {
                a.name: (GeometryArray.from_shapes([]) if a.is_geometry
                         else [])
                for a in self.sft.attributes})
        return FeatureTable.concat(tables)

    # -- maintenance ---------------------------------------------------------

    def compact(self, partition: Optional[str] = None) -> Dict[str, int]:
        """Merge each partition's files into one (≙ FSDS compaction)."""
        from geomesa_tpu.io.arrow import from_arrow, to_arrow

        targets = [partition] if partition else self.partitions()
        out: Dict[str, int] = {}
        for p in targets:
            files = self.files(p)
            if len(files) <= 1:
                out[p] = len(files)
                continue
            merged = FeatureTable.concat(
                [from_arrow(self._read_file(fp), self.sft) for fp in files])
            tmp = os.path.join(self.root, p, f"{uuid.uuid4().hex}{self._ext}")
            self._write_file(to_arrow(merged), tmp)
            for fp in files:
                os.remove(fp)
            out[p] = 1
        return out
