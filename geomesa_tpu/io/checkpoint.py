"""Datastore checkpoint / restore.

≙ the reference's durable state (SURVEY.md §5 checkpoint/resume): catalog
metadata (GeoMesaMetadata.scala:17 — SFT specs under ``attributes``), persisted
stat sketches (MetadataBackedStats.scala:36), and the feature data itself.
Layout::

    <dir>/catalog.json            # schemas, fid counters, stats sketches
    <dir>/<type>.npz              # columnar payload (numeric cols, string
                                  # codes+vocab, geometry buffers, fids)

Restore rebuilds device indexes from the columns (sort permutations are
cheap relative to load) but reuses the checkpointed sketches instead of
re-observing the table — the same split the reference makes between data
tables and the stats metadata row."""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn

_VERSION = 2


def save_store(store, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    catalog: Dict[str, dict] = {"version": _VERSION, "types": {}}
    for name, sft in store.schemas.items():
        if getattr(store, "flush", None) is not None:
            store.flush(name)  # pending LSM delta runs must persist too
        table = store.tables.get(name)
        entry = {
            "spec": sft.to_spec(),
            "counter": store._counters.get(name, 0),
            # v2: mutation-generation counters persist so a restore
            # continues the sequence monotonically — a restored store's
            # serving caches can never alias a prior incarnation's plans
            # (belt-and-braces with the per-incarnation epoch salt in the
            # scheduler's cache keys)
            "generation": getattr(store, "_generations", {}).get(name, 0),
            "rows": 0 if table is None else len(table),
        }
        stats = store._stats.get(name)
        if stats is not None:
            entry["stats"] = stats.to_dict()
        catalog["types"][name] = entry
        if table is not None:
            _save_table(table, os.path.join(path, f"{name}.npz"))
    with open(os.path.join(path, "catalog.json"), "w") as f:
        json.dump(catalog, f)


def load_store(path: str):
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.stats.store import GeoMesaStats

    with open(os.path.join(path, "catalog.json")) as f:
        catalog = json.load(f)
    store = TpuDataStore()
    for name, entry in catalog["types"].items():
        sft = store.create_schema(SimpleFeatureType.from_spec(name, entry["spec"]))
        store._counters[name] = entry.get("counter", 0)
        npz = os.path.join(path, f"{name}.npz")
        if entry.get("rows", 0):
            if not os.path.exists(npz):
                raise ValueError(
                    f"Corrupt checkpoint: catalog records {entry['rows']} rows "
                    f"for {name!r} but {npz} is missing")
            table = _load_table(sft, npz)
            stats_dict = entry.get("stats")
            cached = None
            if stats_dict is not None:
                cached = GeoMesaStats.from_dict(sft, stats_dict).cached
            store.load(name, table, stats_cached=cached)
        # v2 catalogs: the restore counts as one more mutation on top of the
        # persisted generation. v1 catalogs carry no counters — the store's
        # fresh epoch (salted into every scheduler cache key) already makes
        # cross-incarnation aliasing impossible, so the load-bump suffices.
        stored_gen = entry.get("generation")
        if stored_gen is not None:
            store._generations[name] = max(
                store._generations.get(name, 0), int(stored_gen) + 1)
    return store


# -- columnar table codec ----------------------------------------------------


def table_payload(table: FeatureTable) -> Dict[str, np.ndarray]:
    """The columnar npz payload for one table (shared by checkpoint files,
    durability snapshots, and WAL append/upsert records)."""
    payload: Dict[str, np.ndarray] = {
        "__fids__": np.asarray(table.fids, dtype="U"),
    }
    if table.visibility is not None:
        payload["__vis__:codes"] = table.visibility.codes
        payload["__vis__:vocab"] = np.asarray(table.visibility.vocab, dtype="U")
    for attr in table.sft.attributes:
        col = table.columns[attr.name]
        k = f"col:{attr.name}"
        if isinstance(col, GeometryArray):
            payload[k + ":types"] = col.type_codes
            payload[k + ":geom_off"] = col.geom_offsets
            payload[k + ":part_off"] = col.part_offsets
            payload[k + ":ring_off"] = col.ring_offsets
            payload[k + ":coords"] = col.coords
        elif isinstance(col, StringColumn):
            payload[k + ":codes"] = col.codes
            payload[k + ":vocab"] = np.asarray(col.vocab, dtype="U")
        else:
            payload[k] = np.asarray(col)
    return payload


def _save_table(table: FeatureTable, path: str) -> None:
    np.savez_compressed(path, **table_payload(table))


def table_from_payload(sft: SimpleFeatureType, z) -> FeatureTable:
    """Rebuild a FeatureTable from a ``table_payload`` mapping (an open npz
    or any dict of arrays)."""
    data: Dict[str, object] = {}
    for attr in sft.attributes:
        k = f"col:{attr.name}"
        if attr.is_geometry:
            data[attr.name] = GeometryArray(
                z[k + ":types"], z[k + ":geom_off"], z[k + ":part_off"],
                z[k + ":ring_off"], z[k + ":coords"])
        elif attr.type_name == "String":
            data[attr.name] = StringColumn(
                z[k + ":codes"], [str(v) for v in z[k + ":vocab"]])
        else:
            data[attr.name] = z[k]
    fids = np.asarray([str(v) for v in z["__fids__"]], dtype=object)
    table = FeatureTable.build(sft, data, fids=fids)
    if "__vis__:codes" in z:
        table.visibility = StringColumn(
            z["__vis__:codes"], [str(v) for v in z["__vis__:vocab"]])
    return table


def _load_table(sft: SimpleFeatureType, path: str) -> FeatureTable:
    return table_from_payload(sft, np.load(path, allow_pickle=False))
