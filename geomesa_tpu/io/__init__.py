"""IO layer: Arrow interchange, checkpoint/restore, export formats.

≙ reference `geomesa-arrow` (§2.7), the durable-state machinery (§5
checkpoint/resume), and the export half of `geomesa-tools` (§2.11).

Arrow-dependent names load lazily — checkpoint (npz/json) and text exports
need only numpy, so pyarrow stays an optional extra.
"""

from geomesa_tpu.io.checkpoint import load_store, save_store
from geomesa_tpu.io.export import FORMATS, export

_ARROW_NAMES = ("from_arrow", "read_ipc", "to_arrow", "write_ipc")

__all__ = ["FORMATS", "export", "load_store", "save_store", *_ARROW_NAMES]


def __getattr__(name):
    if name in _ARROW_NAMES:
        from geomesa_tpu.io import arrow
        return getattr(arrow, name)
    raise AttributeError(name)
