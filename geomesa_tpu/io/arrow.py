"""Arrow interchange: FeatureTable ↔ pyarrow Table ↔ IPC files.

≙ reference `geomesa-arrow` (SURVEY.md §2.7 — SimpleFeatureVector.scala:42,
ArrowAttributeWriter/Reader, the IPC writers of io/*.scala). The columnar
FeatureTable is already Arrow-shaped, so the mapping is direct:

  - numeric/bool columns  → matching Arrow primitive arrays (Date → ms
    timestamp)
  - String columns        → dictionary-encoded arrays (≙ ArrowDictionary)
  - point geometry        → struct<x: f64, y: f64> (≙ the fixed-width point
    vectors of arrow-jts)
  - other geometries      → WKB binary column (standard interop: geopandas /
    GDAL read it as-is)

The SFT spec string rides in the schema metadata so IPC files round-trip
schemas without a side channel (≙ the reference embedding the SFT in the
Arrow schema metadata)."""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.ipc as ipc

from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.features.twkb import decode_wkb, encode_wkb

_SFT_KEY = b"geomesa.sft.spec"
_NAME_KEY = b"geomesa.sft.name"


def to_arrow(table: FeatureTable) -> pa.Table:
    arrays, names = [], []
    names.append("__fid__")
    arrays.append(pa.array([str(f) for f in table.fids], type=pa.string()))
    for attr in table.sft.attributes:
        col = table.columns[attr.name]
        names.append(attr.name)
        if isinstance(col, GeometryArray):
            if col.is_points:
                x, y = col.point_xy()
                arrays.append(pa.StructArray.from_arrays(
                    [pa.array(x, pa.float64()), pa.array(y, pa.float64())],
                    ["x", "y"]))
            else:
                arrays.append(pa.array(encode_wkb(col), type=pa.binary()))
        elif isinstance(col, StringColumn):
            arrays.append(pa.DictionaryArray.from_arrays(
                pa.array(col.codes, pa.int32()), pa.array(col.vocab, pa.string())))
        elif attr.type_name == "Date":
            arrays.append(pa.array(np.asarray(col, dtype=np.int64),
                                   pa.timestamp("ms")))
        else:
            arrays.append(pa.array(np.asarray(col)))
    out = pa.table(dict(zip(names, arrays)))
    return out.replace_schema_metadata(
        {_SFT_KEY: table.sft.to_spec().encode(),
         _NAME_KEY: table.sft.name.encode()})


def from_arrow(at: pa.Table, sft: Optional[SimpleFeatureType] = None) -> FeatureTable:
    if sft is None:
        meta = at.schema.metadata or {}
        if _SFT_KEY not in meta:
            raise ValueError("Arrow table has no embedded SFT spec; pass sft=")
        sft = SimpleFeatureType.from_spec(
            meta.get(_NAME_KEY, b"features").decode(), meta[_SFT_KEY].decode())
    fids = None
    if "__fid__" in at.column_names:
        fids = np.asarray(at.column("__fid__").to_pylist(), dtype=object)
    data = {}
    for attr in sft.attributes:
        col = at.column(attr.name)
        if attr.is_geometry:
            typ = col.type
            if pa.types.is_struct(typ):
                combined = col.combine_chunks()
                data[attr.name] = GeometryArray.points(
                    np.asarray(combined.field("x")), np.asarray(combined.field("y")))
            else:
                data[attr.name] = decode_wkb(col.to_pylist())
        elif attr.type_name == "String":
            combined = col.combine_chunks()
            if pa.types.is_dictionary(col.type):
                vocab = [str(v) for v in combined.dictionary.to_pylist()]
                codes = np.asarray(combined.indices, dtype=np.int32)
                if vocab == sorted(vocab) and len(set(vocab)) == len(vocab):
                    data[attr.name] = StringColumn(codes, vocab)
                else:
                    # foreign dictionaries may be unsorted; the attribute
                    # index requires code order == lexicographic order
                    data[attr.name] = StringColumn.encode(
                        np.asarray(vocab, dtype=object)[codes])
            else:
                data[attr.name] = combined.to_pylist()
        elif attr.type_name == "Date":
            data[attr.name] = np.asarray(col.cast(pa.int64()))
        else:
            data[attr.name] = np.asarray(col)
    return FeatureTable.build(sft, data, fids=fids)


def write_ipc(table: FeatureTable, path: str) -> None:
    at = to_arrow(table)
    with ipc.new_file(path, at.schema) as w:
        w.write_table(at)


def read_ipc(path: str, sft: Optional[SimpleFeatureType] = None) -> FeatureTable:
    with ipc.open_file(path) as r:
        return from_arrow(r.read_all(), sft)
