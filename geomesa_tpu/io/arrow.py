"""Arrow interchange: FeatureTable ↔ pyarrow Table ↔ IPC files.

≙ reference `geomesa-arrow` (SURVEY.md §2.7 — SimpleFeatureVector.scala:42,
ArrowAttributeWriter/Reader, the IPC writers of io/*.scala). The columnar
FeatureTable is already Arrow-shaped, so the mapping is direct:

  - numeric/bool columns  → matching Arrow primitive arrays (Date → ms
    timestamp)
  - String columns        → dictionary-encoded arrays (≙ ArrowDictionary)
  - point geometry        → struct<x: f64, y: f64> (≙ the fixed-width point
    vectors of arrow-jts)
  - other geometries      → WKB binary column (standard interop: geopandas /
    GDAL read it as-is)

The SFT spec string rides in the schema metadata so IPC files round-trip
schemas without a side channel (≙ the reference embedding the SFT in the
Arrow schema metadata)."""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.ipc as ipc

from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.features.twkb import decode_wkb, encode_wkb

_SFT_KEY = b"geomesa.sft.spec"
_NAME_KEY = b"geomesa.sft.name"


def _local_dictionary(attr, col):
    """Default string strategy: the column's own dictionary."""
    sc = col if isinstance(col, StringColumn) \
        else StringColumn.encode([str(v) for v in col])
    return pa.DictionaryArray.from_arrays(
        pa.array(sc.codes, pa.int32()), pa.array(sc.vocab, pa.string()))


def _encode_column(attr, col, string_encoder=_local_dictionary):
    """ONE home for the FeatureTable→Arrow column mapping (to_arrow and the
    delta stream writer share it; only the string-dictionary strategy
    differs). Geometry encoding follows the ATTRIBUTE type — a generic
    'Geometry' attribute is WKB even when a particular batch is all points,
    so stream batches stay schema-stable."""
    if attr.is_geometry:
        if attr.type_name == "Point":
            x, y = col.point_xy()
            return pa.StructArray.from_arrays(
                [pa.array(x, pa.float64()), pa.array(y, pa.float64())],
                ["x", "y"])
        return pa.array(encode_wkb(col), type=pa.binary())
    if attr.type_name == "String":
        return string_encoder(attr, col)
    if attr.type_name == "Date":
        return pa.array(np.asarray(col, dtype=np.int64), pa.timestamp("ms"))
    return pa.array(np.asarray(col))


def to_arrow(table: FeatureTable) -> pa.Table:
    arrays, names = [], []
    names.append("__fid__")
    arrays.append(pa.array([str(f) for f in table.fids], type=pa.string()))
    for attr in table.sft.attributes:
        names.append(attr.name)
        arrays.append(_encode_column(attr, table.columns[attr.name]))
    out = pa.table(dict(zip(names, arrays)))
    return out.replace_schema_metadata(
        {_SFT_KEY: table.sft.to_spec().encode(),
         _NAME_KEY: table.sft.name.encode()})


def from_arrow(at: pa.Table, sft: Optional[SimpleFeatureType] = None) -> FeatureTable:
    if sft is None:
        meta = at.schema.metadata or {}
        if _SFT_KEY not in meta:
            raise ValueError("Arrow table has no embedded SFT spec; pass sft=")
        sft = SimpleFeatureType.from_spec(
            meta.get(_NAME_KEY, b"features").decode(), meta[_SFT_KEY].decode())
    fids = None
    if "__fid__" in at.column_names:
        fids = np.asarray(at.column("__fid__").to_pylist(), dtype=object)
    data = {}
    for attr in sft.attributes:
        col = at.column(attr.name)
        if attr.is_geometry:
            typ = col.type
            if pa.types.is_struct(typ):
                combined = col.combine_chunks()
                data[attr.name] = GeometryArray.points(
                    np.asarray(combined.field("x")), np.asarray(combined.field("y")))
            else:
                data[attr.name] = decode_wkb(col.to_pylist())
        elif attr.type_name == "String":
            combined = col.combine_chunks()
            if pa.types.is_dictionary(col.type):
                vocab = [str(v) for v in combined.dictionary.to_pylist()]
                codes = np.asarray(combined.indices, dtype=np.int32)
                if vocab == sorted(vocab) and len(set(vocab)) == len(vocab):
                    data[attr.name] = StringColumn(codes, vocab)
                else:
                    # foreign dictionaries may be unsorted; the attribute
                    # index requires code order == lexicographic order
                    data[attr.name] = StringColumn.encode(
                        np.asarray(vocab, dtype=object)[codes])
            else:
                data[attr.name] = combined.to_pylist()
        elif attr.type_name == "Date":
            # normalize any timestamp unit (ORC reads back as ns) to ms
            if pa.types.is_timestamp(col.type):
                col = col.cast(pa.timestamp("ms"))
            data[attr.name] = np.asarray(col.cast(pa.int64()))
        else:
            data[attr.name] = np.asarray(col)
    return FeatureTable.build(sft, data, fids=fids)


def write_ipc(table: FeatureTable, path: str) -> None:
    at = to_arrow(table)
    with ipc.new_file(path, at.schema) as w:
        w.write_table(at)


def read_ipc(path: str, sft: Optional[SimpleFeatureType] = None) -> FeatureTable:
    with ipc.open_file(path) as r:
        return from_arrow(r.read_all(), sft)


# -- streaming delta batches -------------------------------------------------


def _stream_schema(sft: SimpleFeatureType) -> pa.Schema:
    fields = [pa.field("__fid__", pa.string())]
    for attr in sft.attributes:
        if attr.is_geometry:
            t = pa.struct([("x", pa.float64()), ("y", pa.float64())]) \
                if attr.type_name == "Point" else pa.binary()
        elif attr.type_name == "String":
            t = pa.dictionary(pa.int32(), pa.string())
        elif attr.type_name == "Date":
            t = pa.timestamp("ms")
        else:
            t = pa.from_numpy_dtype(attr.binding)
        fields.append(pa.field(attr.name, t))
    return pa.schema(fields, metadata={
        _SFT_KEY: sft.to_spec().encode(), _NAME_KEY: sft.name.encode()})


class ArrowDeltaWriter:
    """Incremental Arrow IPC stream with dictionary DELTAS.

    ≙ the reference `DeltaWriter` (/root/reference/geomesa-arrow/
    geomesa-arrow-gt/src/main/scala/org/locationtech/geomesa/arrow/io/
    DeltaWriter.scala:53,205): threadsafe incremental record batches whose
    string dictionaries only ever GROW — each batch ships just the new
    dictionary entries (``emit_dictionary_deltas``), so a long-running
    export never re-transmits its vocabularies. Readers merge transparently
    (pyarrow replays deltas); ``merge_deltas`` k-way-merges several streams
    into one sorted stream (the BatchWriter merge-sort step)."""

    def __init__(self, sink, sft: SimpleFeatureType):
        self.sft = sft
        self.schema = _stream_schema(sft)
        self._own = isinstance(sink, str)
        self._sink = open(sink, "wb") if self._own else sink
        self._writer = ipc.new_stream(
            self._sink, self.schema,
            options=ipc.IpcWriteOptions(emit_dictionary_deltas=True))
        # append-only global vocab per string attr (delta requirement)
        self._vocabs: dict = {a.name: {} for a in sft.attributes
                              if a.type_name == "String"}

    def _growing_dictionary(self, attr, col):
        """Delta strategy: codes remap into the APPEND-ONLY global vocab."""
        vocab = self._vocabs[attr.name]
        values = col.decode(np.arange(len(col))) \
            if isinstance(col, StringColumn) else [str(v) for v in col]
        for v in values:
            if v not in vocab:
                vocab[v] = len(vocab)  # append-only growth
        codes = np.fromiter((vocab[v] for v in values), np.int32, len(values))
        return pa.DictionaryArray.from_arrays(
            pa.array(codes, pa.int32()), pa.array(list(vocab), pa.string()))

    def write(self, table: FeatureTable) -> None:
        arrays = [pa.array([str(f) for f in table.fids], pa.string())]
        for attr in self.sft.attributes:
            arrays.append(_encode_column(attr, table.columns[attr.name],
                                         self._growing_dictionary))
        self._writer.write_batch(pa.record_batch(arrays, self.schema))

    def close(self) -> None:
        self._writer.close()
        if self._own:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_stream(path: str,
                sft: Optional[SimpleFeatureType] = None) -> FeatureTable:
    """Read a delta IPC stream back into one FeatureTable (pyarrow replays
    the dictionary deltas; batches concatenate)."""
    with ipc.open_stream(path) as r:
        at = r.read_all()
    return from_arrow(at, sft)


def merge_deltas(paths, out_path: str, sort: Optional[str] = None,
                 batch_rows: int = 1 << 17) -> None:
    """Merge several delta streams into ONE sorted delta stream (≙ the
    client-side DeltaWriter reduce: per-server batches → one sorted IPC)."""
    tables = [read_stream(p) for p in paths]
    merged = FeatureTable.concat(tables)
    if sort is not None:
        from geomesa_tpu.index.shaping import shape_local
        merged = merged.take(shape_local(merged, sort=sort))
    with ArrowDeltaWriter(out_path, merged.sft) as w:
        for lo in range(0, len(merged), batch_rows):
            w.write(merged.take(np.arange(
                lo, min(len(merged), lo + batch_rows))))



def orc_compatible(at: "pa.Table") -> "pa.Table":
    """Arrow table reshaped for the ORC writer: dictionary columns cast to
    their value type (ORC has no dictionary encoding; its RLE recovers the
    compression on disk). Timestamps write as real ORC timestamps so
    external readers (Spark/Hive) see the proper type — EXCEPT columns with
    values outside the ns-representable range (1677..2262; far-future
    sentinels like 9999-12-31 are common), which fall back to int64 ms;
    from_arrow normalizes either representation back to epoch ms."""
    import pyarrow.compute as pc

    ns_lo = -9_223_372_036_854  # ms bounds of the int64-ns epoch range
    ns_hi = 9_223_372_036_854
    for i, f in enumerate(at.schema):
        if pa.types.is_dictionary(f.type):
            at = at.set_column(
                i, pa.field(f.name, f.type.value_type, metadata=f.metadata),
                at.column(i).cast(f.type.value_type))
        elif pa.types.is_timestamp(f.type):
            ms = at.column(i).cast(pa.timestamp("ms")).cast(pa.int64())
            lo = pc.min(ms).as_py()
            hi = pc.max(ms).as_py()
            if lo is not None and (lo < ns_lo or hi > ns_hi):
                at = at.set_column(
                    i, pa.field(f.name, pa.int64(), metadata=f.metadata), ms)
    return at
