"""geomesa-tpu: a TPU-native spatio-temporal indexing & analytics framework.

A from-scratch re-design of GeoMesa's capability surface (see SURVEY.md) for
TPU hardware: features live in an HBM-resident columnar table; space-filling
curve indexes, CQL-style filters and aggregations run as vmapped / pjit-sharded
XLA kernels; query *planning* (filter splitting, index selection, range
decomposition) stays host-side Python, mirroring GeoMesa's split between
planning (client) and scanning (server), where "server" here is the TPU.

Layer map (mirrors reference layers in SURVEY.md §1):
  - ``geomesa_tpu.curves``    ≙ geomesa-z3 (+ the external sfcurve lib)
  - ``geomesa_tpu.features``  ≙ geomesa-utils SimpleFeatureTypes + geomesa-features + geomesa-arrow
  - ``geomesa_tpu.filter``    ≙ geomesa-filter
  - ``geomesa_tpu.index``     ≙ geomesa-index-api (key spaces, planner, scans)
  - ``geomesa_tpu.aggregates``≙ index iterators (density/bin/stats/arrow scans)
  - ``geomesa_tpu.stats``     ≙ geomesa-utils stats + index stats
  - ``geomesa_tpu.parallel``  ≙ backend scan fan-out + geomesa-spark (mesh sharding, joins)
  - ``geomesa_tpu.convert``   ≙ geomesa-convert
  - ``geomesa_tpu.tools``     ≙ geomesa-tools CLI
  - ``geomesa_tpu.datastore`` ≙ GeoMesaDataStore / DataStoreFinder entry point
"""

__version__ = "0.1.0"

from geomesa_tpu.features.sft import SimpleFeatureType  # noqa: F401
from geomesa_tpu.datastore import DataStoreFinder  # noqa: F401
