"""System-property registry: typed, documented runtime knobs.

≙ the reference's three-tier config system (SURVEY.md §5): this is tier 1,
``GeoMesaSystemProperties`` (/root/reference/geomesa-utils/src/main/scala/org/
locationtech/geomesa/utils/conf/GeoMesaSystemProperties.scala:19) — a central
registry of typed properties with environment-variable override and a
programmatic ``set``/``unset`` for tests. Tier 2 (per-datastore params) lives
on TpuDataStore(params); tier 3 (per-type config) rides in SFT user-data
strings (``geomesa.indices``, ``geomesa.z3.interval`` …).

Every property reads its env var on EACH access (late-bound, so tests and
operators can flip knobs at runtime), falling back to a programmatic override
then the default. ``describe()`` lists everything for the CLI/docs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class SystemProperty:
    """One typed knob: ``prop.get()`` → env override → set() value → default."""

    name: str                       # env var name
    default: object
    parse: Callable[[str], object]
    doc: str
    _override: object = field(default=None, repr=False)

    def get(self):
        raw = os.environ.get(self.name)
        if raw is not None:
            try:
                return self.parse(raw)
            except (TypeError, ValueError):
                pass  # malformed env values fall back (reference behavior)
        if self._override is not None:
            return self._override
        return self.default

    def set(self, value) -> None:
        self._override = value

    def unset(self) -> None:
        self._override = None


_REGISTRY: Dict[str, SystemProperty] = {}


def _register(name: str, default, parse, doc: str) -> SystemProperty:
    prop = SystemProperty(name, default, parse, doc)
    _REGISTRY[name] = prop
    return prop


def _parse_bool(s: str) -> bool:
    return s.strip().lower() not in ("0", "false", "no", "off", "")


# -- the knobs ---------------------------------------------------------------

SCAN_RANGES_TARGET = _register(
    "GEOMESA_TPU_SCAN_RANGES_TARGET", 2000, int,
    "Target key ranges per query cover (geomesa.scan.ranges.target, "
    "QueryProperties.scala:22).")

PRUNE_BLOCK = _register(
    "GEOMESA_TPU_PRUNE_BLOCK", 4096, int,
    "Rows per gather block for range-pruned scans.")

PRUNE_MAX_FRACTION = _register(
    "GEOMESA_TPU_PRUNE_MAX_FRAC", 0.25, float,
    "Above this candidate fraction a full-table fused scan beats block "
    "gathering (full-table-scan avoidance threshold).")

PRUNE_ENABLED = _register(
    "GEOMESA_TPU_PRUNE", True, _parse_bool,
    "Master switch for range-pruned scan execution.")

DEVICE_SORT_MIN = _register(
    "GEOMESA_TPU_DEVICE_SORT_MIN", 2_000_000, int,
    "Row count above which index sorts run on the accelerator.")

BUILD_STREAM_CHUNK = _register(
    "GEOMESA_TPU_BUILD_STREAM_CHUNK", 16_777_216, int,
    "Rows per chunk for the streamed native build: the C++ encoder works "
    "on chunk i+1 while chunk i uploads in a background thread (encode and "
    "host->device transfer overlap instead of summing).")

LSM_MAX_FRACTION = _register(
    "GEOMESA_TPU_LSM_MAX_FRAC", 0.02, float,
    "Delta-run flush threshold as a fraction of the main table.")

NO_NATIVE = _register(
    "GEOMESA_TPU_NO_NATIVE", False, _parse_bool,
    "Disable the native C++ encode path (numpy fallback). NB boolean "
    "semantics: '0'/'false'/'no'/'off' mean NOT disabled (earlier releases "
    "treated any non-empty value as disabling).")

JOIN_DEVICE_MIN_PAIRS = _register(
    "GEOMESA_TPU_JOIN_DEVICE_MIN_PAIRS", 32_768, int,
    "Candidate-pair count above which the extent join's exact refine runs "
    "on the device band kernel (below it, host f64 soups win — each device "
    "dispatch pays the tunnel round trip).")

DENSITY_PACK = _register(
    "GEOMESA_TPU_DENSITY_PACK", "auto", str,
    "Density grid readback encoding: auto (cheapest faithful of sparse/u8/"
    "fp16 by wire size), sparse, u8 (unweighted only), fp16, or none (raw "
    "f32 grid). Unknown values fall back to auto. ≙ the reference's sparse "
    "kryo density grids (DensityScan.scala:95).")

BENCH_N = _register(
    "GEOMESA_TPU_BENCH_N", 100_000_000, int,
    "bench.py corpus size.")

SCHED_ENABLED = _register(
    "GEOMESA_TPU_SCHEDULER", True, _parse_bool,
    "Master switch for the micro-batching query scheduler on the serving "
    "path (web /count coalescing). Off: every request plans and dispatches "
    "individually.")

SCHED_FLUSH_SIZE = _register(
    "GEOMESA_TPU_SCHED_FLUSH_SIZE", 64, int,
    "Max queries fused into one batched device dispatch (flush-at-B). "
    "Matches the batched scan kernel's sweet spot (BENCH cfg1 batch64).")

SCHED_WINDOW_US = _register(
    "GEOMESA_TPU_SCHED_WINDOW_US", 1500, int,
    "Max micro-batch collection window in microseconds (flush-at-T). The "
    "scheduler adapts the live window between SCHED_MIN_WINDOW_US and this "
    "cap from observed batch sizes; lone queries never wait the full cap.")

SCHED_MIN_WINDOW_US = _register(
    "GEOMESA_TPU_SCHED_MIN_WINDOW_US", 100, int,
    "Floor of the adaptive collection window (latency bound at low traffic).")

SCHED_PLAN_CACHE = _register(
    "GEOMESA_TPU_SCHED_PLAN_CACHE", 512, int,
    "Plan-cache capacity (normalized filter + generation + auths -> plan). "
    "0 disables plan caching.")

SCHED_COVER_CACHE = _register(
    "GEOMESA_TPU_SCHED_COVER_CACHE", 256, int,
    "Cover-cache capacity (boxes/windows -> candidate gather blocks). "
    "0 disables cover caching.")

WAL_FSYNC = _register(
    "GEOMESA_TPU_WAL_FSYNC", "batch", str,
    "Write-ahead-log fsync policy: off (OS page cache only — survives "
    "process death, not power loss), batch (group commit: one fsync per "
    "commit window, bounded data-at-risk; default), always (every append "
    "durable before it returns; concurrent appenders share one fsync).")

WAL_SEGMENT_BYTES = _register(
    "GEOMESA_TPU_WAL_SEGMENT_BYTES", 64 * 1024 * 1024, int,
    "WAL segment size before rotation; old segments garbage-collect once a "
    "snapshot covers them.")

WAL_INTERVAL_MS = _register(
    "GEOMESA_TPU_WAL_INTERVAL_MS", 20.0, float,
    "Group-commit window for WAL fsync policy 'batch': the background "
    "syncer fsyncs at most once per window (the max unsynced-data age).")

SNAPSHOT_ROWS = _register(
    "GEOMESA_TPU_SNAPSHOT_ROWS", 500_000, int,
    "Rows logged since the last snapshot that trigger a new incremental "
    "snapshot (which rotates the WAL and GCs covered segments).")

SNAPSHOT_WAL_BYTES = _register(
    "GEOMESA_TPU_SNAPSHOT_WAL_BYTES", 256 * 1024 * 1024, int,
    "WAL payload bytes since the last snapshot that trigger a new one "
    "(bounds replay time after a crash).")

SNAPSHOT_KEEP = _register(
    "GEOMESA_TPU_SNAPSHOT_KEEP", 2, int,
    "Installed snapshots retained; older ones are pruned after each "
    "successful install (keep >= 2 tolerates one corrupt newest snapshot).")

KERNEL_CACHE = _register(
    "GEOMESA_TPU_KERNEL_CACHE", 128, int,
    "Max compiled scan kernels retained per index (LRU). Long-lived servers "
    "with many residual structures stay bounded; evicted signatures "
    "recompile on next use.")

# -- query-lifecycle resilience (serve/resilience/) ---------------------------

DEADLINE_DEFAULT_MS = _register(
    "GEOMESA_TPU_DEADLINE_DEFAULT_MS", 0.0, float,
    "Default per-request deadline the web layer attaches when the client "
    "sends none (X-Deadline-Ms header / ?deadline_ms=). 0 disables the "
    "implicit deadline; production serving should set ~30000.")

DEADLINE_MAX_MS = _register(
    "GEOMESA_TPU_DEADLINE_MAX_MS", 300_000.0, float,
    "Hard cap on client-requested deadlines (a client cannot hold serving "
    "resources longer than this).")

DEADLINE_DEGRADE_MS = _register(
    "GEOMESA_TPU_DEADLINE_DEGRADE_MS", 25.0, float,
    "Graceful degradation floor: when a deadlined count reaches dispatch "
    "with less than this many ms remaining, an eligible query returns the "
    "stats-estimator approximation (flagged) instead of risking a device "
    "round trip it cannot afford. 0 disables degradation (expired queries "
    "then fail with deadline-exceeded only).")

ADMIT_ENABLED = _register(
    "GEOMESA_TPU_ADMIT", True, _parse_bool,
    "Master switch for serving-path admission control (bounded in-flight "
    "work per priority class; excess sheds with 429 + Retry-After).")

ADMIT_INTERACTIVE = _register(
    "GEOMESA_TPU_ADMIT_INTERACTIVE", 512, int,
    "Max in-flight (queued + executing) interactive-class queries before "
    "new ones shed. Sized so a full queue drains within a typical "
    "interactive deadline at the measured batch throughput.")

ADMIT_BATCH = _register(
    "GEOMESA_TPU_ADMIT_BATCH", 128, int,
    "Max in-flight analytics/batch-class queries (the lower bound keeps "
    "background scans from starving interactive traffic; the scheduler "
    "queue additionally serves interactive requests first).")

ADMIT_RETRY_AFTER_S = _register(
    "GEOMESA_TPU_ADMIT_RETRY_AFTER_S", 1.0, float,
    "Retry-After seconds returned with shed (429) responses.")

BREAKER_THRESHOLD = _register(
    "GEOMESA_TPU_BREAKER_THRESHOLD", 5, int,
    "Consecutive device-dispatch failures that open the circuit breaker "
    "(while open, eligible counts degrade to the stats estimator and "
    "other queries fail fast with 503 instead of queueing onto a sick "
    "device path).")

BREAKER_COOLDOWN_MS = _register(
    "GEOMESA_TPU_BREAKER_COOLDOWN_MS", 1000.0, float,
    "How long an open breaker waits before letting half-open probe "
    "traffic through.")

BREAKER_PROBES = _register(
    "GEOMESA_TPU_BREAKER_PROBES", 2, int,
    "Consecutive half-open probe successes required to close the breaker "
    "(any probe failure re-opens and restarts the cooldown).")

BREAKER_DEGRADE = _register(
    "GEOMESA_TPU_BREAKER_DEGRADE", True, _parse_bool,
    "When the breaker is open, serve eligible counts from the stats "
    "estimator (flagged approximate) instead of failing fast.")

RETRY_ATTEMPTS = _register(
    "GEOMESA_TPU_RETRY_ATTEMPTS", 3, int,
    "Max attempts for the device-dispatch retry wrapper (capped "
    "exponential backoff with full jitter between attempts).")

RETRY_BASE_MS = _register(
    "GEOMESA_TPU_RETRY_BASE_MS", 5.0, float,
    "Backoff base: attempt i sleeps uniform(0, min(cap, base * 2^i)) ms.")

RETRY_CAP_MS = _register(
    "GEOMESA_TPU_RETRY_CAP_MS", 100.0, float,
    "Backoff ceiling per retry sleep.")

RETRY_WAL_FSYNC = _register(
    "GEOMESA_TPU_RETRY_WAL_FSYNC", 1, int,
    "Attempts for a failing WAL group-commit fsync before the error "
    "propagates (transient EIO/disk-pressure absorption). 1 = no retry, "
    "the strict policy the durability tests pin.")

# -- replicated serving fleet (replication/ + serve/router.py) ----------------

REPL_HEARTBEAT_MS = _register(
    "GEOMESA_TPU_REPL_HEARTBEAT_MS", 100.0, float,
    "Primary -> follower heartbeat interval: the shipper sends its last "
    "WAL seq at least this often even when no new frames exist, so a "
    "follower can measure replication lag during write silence.")

REPL_STALENESS_MS = _register(
    "GEOMESA_TPU_REPL_STALENESS_MS", 1000.0, float,
    "Bounded-staleness budget: a replica whose replication lag exceeds "
    "this many ms is DEMOTED by the router (served only when nothing "
    "healthier is up) and spends the replication-staleness SLO's error "
    "budget.")

REPL_ACK_EVERY = _register(
    "GEOMESA_TPU_REPL_ACK_EVERY", 32, int,
    "Follower acks at least every N applied frames (plus on every "
    "heartbeat and on idle); the primary resumes a reconnecting follower "
    "from its last acked seq.")

REPL_RECONNECT_MS = _register(
    "GEOMESA_TPU_REPL_RECONNECT_MS", 200.0, float,
    "Follower reconnect backoff after a dropped/rejected replication "
    "connection (a CRC-rejected shipped frame resyncs after this pause).")

REPL_SLO_TARGET = _register(
    "GEOMESA_TPU_REPL_SLO_TARGET", 0.999, float,
    "Target fraction of staleness checks inside the bounded-staleness "
    "budget for the replication SLO a follower registers (burn-rate "
    "alerting via obs/slo.py rides the standard windows).")

REPL_PROBE_TTL_MS = _register(
    "GEOMESA_TPU_REPL_PROBE_TTL_MS", 250.0, float,
    "Router health-probe cache TTL: endpoint health (overload state, "
    "breaker, replication lag) refreshes at most this often on the "
    "request path.")

REPL_FAILOVER_BUDGET_MS = _register(
    "GEOMESA_TPU_REPL_FAILOVER_BUDGET_MS", 5000.0, float,
    "Deadline budget for a router-driven failover (drain + promote-by-"
    "highest-acked-seq); the fleet drills assert promotion completes "
    "inside it.")

# -- request-centric observability (obs/) -------------------------------------

OBS_ENABLED = _register(
    "GEOMESA_TPU_OBS", True, _parse_bool,
    "Master switch for the request-centric observability layer (flight "
    "recorder wide events, tail-based trace sampling, per-kernel device "
    "attribution). Off: trace close pays nothing beyond the base ring.")

OBS_RING = _register(
    "GEOMESA_TPU_OBS_RING", 2048, int,
    "Flight-recorder ring capacity (wide events retained in memory for "
    "GET /events and `debug events`).")

OBS_TRACE_RING = _register(
    "GEOMESA_TPU_OBS_TRACE_RING", 256, int,
    "Tail-sampled trace ring capacity: retained traces (errors, deadline/"
    "shed/degrade outcomes, slow outliers, probabilistic sample) that "
    "/metrics exemplars link to.")

OBS_SAMPLE = _register(
    "GEOMESA_TPU_OBS_SAMPLE", 0.02, float,
    "Probabilistic retention rate for ordinary traces (errors and slow "
    "outliers are ALWAYS retained — tail-based sampling keeps the "
    "interesting tail at full fidelity and this fraction of the rest).")

OBS_SLOW_MS = _register(
    "GEOMESA_TPU_OBS_SLOW_MS", 0.0, float,
    "Slow-trace retention threshold in ms. 0 = adaptive: retain anything "
    "over the rolling p99 of recent root-trace durations.")

OBS_JSONL = _register(
    "GEOMESA_TPU_OBS_JSONL", "", str,
    "Path for the flight recorder's JSONL sink (one wide event per line, "
    "size-rotated). Empty = in-memory ring only.")

OBS_JSONL_MAX_BYTES = _register(
    "GEOMESA_TPU_OBS_JSONL_MAX_BYTES", 64 * 1024 * 1024, int,
    "Rotation threshold for the flight-recorder JSONL sink (keep-one-"
    "previous, shared durability/rotation.py policy).")

# -- workload intelligence plane (obs/workload.py + obs/sketches.py) ---------

WORKLOAD_ENABLED = _register(
    "GEOMESA_TPU_WORKLOAD", True, _parse_bool,
    "Master switch for the workload-analytics plane (windowed rollups, "
    "heavy-hitter sketches, hot-set feed, per-tenant metering). The hot "
    "path pays one bounded deque append per event; aggregation is "
    "deferred to read time.")

WORKLOAD_WINDOWS = _register(
    "GEOMESA_TPU_WORKLOAD_WINDOWS", 6, int,
    "Windows retained per rollup tier (10s/1m/10m rings): the newest N "
    "wall-clock-aligned windows; older windows rotate out with their "
    "event counts folded into retired_events.")

WORKLOAD_SKETCH_K = _register(
    "GEOMESA_TPU_WORKLOAD_SKETCH_K", 64, int,
    "SpaceSaving sketch capacity (counters tracked) for the plan-hash, "
    "tenant and hot-cell heavy-hitter summaries. Any key with frequency "
    "above total/capacity is guaranteed tracked.")

WORKLOAD_HOTSET_K = _register(
    "GEOMESA_TPU_WORKLOAD_HOTSET_K", 10, int,
    "Entries returned by hot_set() per dimension (top plan hashes, top "
    "cells) — the feed a result cache would key its admission on.")

WORKLOAD_CELL_BITS = _register(
    "GEOMESA_TPU_WORKLOAD_CELL_BITS", 6, int,
    "Resolution of the hot-cell grid: queries map to a coarse Morton "
    "cell on a 2^bits x 2^bits lon/lat grid (6 -> 64x64 world cells, "
    "~5.6 x 2.8 degrees at the equator).")

WORKLOAD_PENDING = _register(
    "GEOMESA_TPU_WORKLOAD_PENDING", 65536, int,
    "Bound on the workload plane's pending-event queue; events past the "
    "bound are counted dropped rather than blocking the hot path.")

SLO_LATENCY_MS = _register(
    "GEOMESA_TPU_SLO_LATENCY_MS", 250.0, float,
    "Latency objective threshold for the default serving SLO: a count "
    "is 'good' when it lands under this many ms.")

SLO_TARGET = _register(
    "GEOMESA_TPU_SLO_TARGET", 0.999, float,
    "Target good-fraction for the default latency SLO (error budget = "
    "1 - target, the quantity burn rates are measured against).")

SLO_AVAIL_TARGET = _register(
    "GEOMESA_TPU_SLO_AVAIL_TARGET", 0.999, float,
    "Target success-fraction for the default availability SLO (sheds, "
    "deadline cancellations and worker deaths spend its budget).")

# -- device profiling + perf regression watch (obs/profiling, obs/perfwatch) --

PROFILING_ENABLED = _register(
    "GEOMESA_TPU_PROFILING", True, _parse_bool,
    "Master switch for device-level kernel profiling: per-kernel XLA "
    "cost_analysis (flops/bytes gauges), compile telemetry, recompile "
    "detection (kernels.recompiles + flight events), and index-build "
    "phase progress. All costs land at compile/build time — the "
    "steady-state dispatch path pays one wrapper call.")

PERFWATCH_K = _register(
    "GEOMESA_TPU_PERFWATCH_K", 4.0, float,
    "Noise threshold for bench regression gating: a metric flags only "
    "past baseline median + k*MAD (in its bad direction). CI perf-smoke "
    "runs with the looser k=3 plus the relative floor.")

PERFWATCH_MIN_REL = _register(
    "GEOMESA_TPU_PERFWATCH_MIN_REL", 0.10, float,
    "Relative noise floor for regression gating: deltas under this "
    "fraction of the baseline median never flag, even when k*MAD is "
    "smaller (few-sample baselines can have MAD ~0).")

BENCH_MINI_N = _register(
    "GEOMESA_TPU_BENCH_MINI_N", 200_000, int,
    "Corpus size for bench.py --mini (the CI-runnable deterministic "
    "mini-bench the perf-smoke regression gate measures).")

# -- fleet-wide observability (obs/federation.py + trace propagation) ---------

NODE_ID = _register(
    "GEOMESA_TPU_NODE_ID", "", str,
    "Stable node identity for fleet observability (the `node` label on "
    "federated metrics, the node dimension on traces/flight events, the "
    "/healthz + BENCH_summary attribution). Empty = derived "
    "hostname-pid-suffix, unique per process incarnation.")

FED_PROPAGATE = _register(
    "GEOMESA_TPU_FED_PROPAGATE", True, _parse_bool,
    "Master switch for cross-process trace propagation: the router "
    "injects X-Trace-Id/X-Span-Id/X-Trace-Node/X-Trace-Sampled on "
    "proxied queries and the web layer opens the request trace as a "
    "child of the remote parent. Off: every process traces in "
    "isolation (the pre-fleet behavior).")

FED_TTL_MS = _register(
    "GEOMESA_TPU_FED_TTL_MS", 1000.0, float,
    "Metrics-federation scrape cache TTL: the federator re-scrapes each "
    "node's /healthz + /metrics?format=state at most this often; reads "
    "inside the window serve the cached merge.")

FED_TIMEOUT_S = _register(
    "GEOMESA_TPU_FED_TIMEOUT_S", 2.0, float,
    "Per-node scrape timeout for the metrics federator; a node that "
    "cannot answer inside it is reported down in /fleet rather than "
    "stalling the whole merged surface.")

REPL_TRACE_EVERY = _register(
    "GEOMESA_TPU_REPL_TRACE_EVERY", 64, int,
    "Replication-pipeline exemplar cadence: every Nth applied frame on "
    "a follower runs under a retained root trace whose id rides the ack "
    "back to the primary and lands as the exemplar on the fleet "
    "repl.e2e histogram (fleet p99 -> exemplar -> remote apply trace). "
    "0 disables the traced applies (timers still populate).")

# -- fleet doctor: anomaly detectors + incidents (obs/doctor, obs/incidents) --

DOCTOR_ENABLED = _register(
    "GEOMESA_TPU_DOCTOR", True, _parse_bool,
    "Master switch for the fleet doctor: rule-driven anomaly detectors "
    "(SLO burn, replication lag, recompile churn, shed storm, breaker "
    "flapping, WAL fsync stall, hot-set skew) evaluated on read/tick — "
    "the query hot path never pays for it.")

DOCTOR_WINDOW_S = _register(
    "GEOMESA_TPU_DOCTOR_WINDOW_S", 60.0, float,
    "Observation window for the doctor's rate detectors (recompile "
    "churn, shed storm, breaker flapping): counter deltas older than "
    "this are forgotten, so a burst must sustain inside the window to "
    "keep an incident active.")

DOCTOR_LAG_MS = _register(
    "GEOMESA_TPU_DOCTOR_LAG_MS", 1000.0, float,
    "Replication-lag detector threshold on the decay-based "
    "replication.lag_ms gauge; a follower above it opens a "
    "replication_lag incident.")

DOCTOR_LAG_SEQS = _register(
    "GEOMESA_TPU_DOCTOR_LAG_SEQS", 64, int,
    "Replication-lag detector threshold on sequence backlog "
    "(replication.lag_seqs): a follower this many WAL frames behind "
    "fires even when the time-based gauge has decayed.")

DOCTOR_RECOMPILES_PER_MIN = _register(
    "GEOMESA_TPU_DOCTOR_RECOMPILES_PER_MIN", 6.0, float,
    "Recompile-churn detector threshold: kernels.recompiles advancing "
    "faster than this (rate normalized to per-minute over the doctor "
    "window) opens an incident naming the most-recompiled kernel.")

DOCTOR_SHED_PER_MIN = _register(
    "GEOMESA_TPU_DOCTOR_SHED_PER_MIN", 30.0, float,
    "Shed-storm detector threshold: admission.shed advancing faster "
    "than this per minute over the doctor window opens an incident "
    "naming the dominant shed priority class.")

DOCTOR_BREAKER_FLAPS = _register(
    "GEOMESA_TPU_DOCTOR_BREAKER_FLAPS", 3, int,
    "Breaker-flapping detector threshold: this many open/close "
    "transition edges on one breaker inside the doctor window opens a "
    "breaker_flapping incident.")

DOCTOR_FSYNC_ERRORS = _register(
    "GEOMESA_TPU_DOCTOR_FSYNC_ERRORS", 1, int,
    "WAL fsync-stall detector threshold: this many new wal.fsync_errors "
    "(or fsync retries) inside the doctor window opens an incident — "
    "durability faults page immediately by default.")

DOCTOR_SKEW_FRACTION = _register(
    "GEOMESA_TPU_DOCTOR_SKEW_FRACTION", 0.6, float,
    "Hot-set skew detector threshold: a single plan/cell/tenant whose "
    "guaranteed (at_least) share of the workload window exceeds this "
    "fraction opens a hot_skew incident naming it.")

DOCTOR_SKEW_MIN = _register(
    "GEOMESA_TPU_DOCTOR_SKEW_MIN", 200, int,
    "Minimum events in the workload window before the skew detector "
    "may fire (tiny samples always look skewed).")

DOCTOR_CLEAR_TICKS = _register(
    "GEOMESA_TPU_DOCTOR_CLEAR_TICKS", 2, int,
    "Consecutive clear evaluations required before an active incident "
    "closes with a resolution record (debounces detectors oscillating "
    "around their threshold).")

DOCTOR_JOURNAL = _register(
    "GEOMESA_TPU_DOCTOR_JOURNAL", "", str,
    "Path of the incident journal: every incident open/close appends a "
    "JSONL record with its correlated timeline. Empty disables the "
    "journal (incidents stay queryable in memory).")

DOCTOR_JOURNAL_MAX_BYTES = _register(
    "GEOMESA_TPU_DOCTOR_JOURNAL_MAX_BYTES", 16 * 1024 * 1024, int,
    "Size cap for the incident journal before rotation (keeps one "
    "rotated predecessor, .1, via the durability rotation helper).")

DOCTOR_TIMELINE_EVENTS = _register(
    "GEOMESA_TPU_DOCTOR_TIMELINE_EVENTS", 8, int,
    "Correlated flight events snapshotted into each incident timeline "
    "(matched with the flight recorder's shared predicate, newest "
    "first).")

DOCTOR_REINDEX_PER_MIN = _register(
    "GEOMESA_TPU_DOCTOR_REINDEX_PER_MIN", 3.0, float,
    "reindex_churn bar: background-build aborts + failed installs per "
    "minute over the doctor window before an incident opens (a build "
    "that keeps losing its race with ingest never converges). "
    "0 disables the detector.")

DOCTOR_MERGE_BREACHES_PER_MIN = _register(
    "GEOMESA_TPU_DOCTOR_MERGE_BREACHES_PER_MIN", 6.0, float,
    "merge_fraction_breach bar: incremental merge-builds falling back "
    "to the full rebuild (delta over GEOMESA_TPU_MERGE_MAX_FRACTION) "
    "per minute before the reindex_churn rule flags the ingest shape. "
    "0 disables the cause.")

# -- self-optimizing serving: result cache / affinity / QoS (ISSUE 12) --------

RESULT_CACHE_ENABLED = _register(
    "GEOMESA_TPU_RESULT_CACHE", True, _parse_bool,
    "Master switch for the scheduled-count result cache: hot queries "
    "(admitted by the workload plane's hot_set at_least counts) resolve "
    "from memory without touching the device. Entries are keyed by the "
    "same (epoch, type, generation, filter, auths) tuple that salts the "
    "plan cache, so every mutation path invalidates them exactly.")

RESULT_CACHE_SIZE = _register(
    "GEOMESA_TPU_RESULT_CACHE_SIZE", 2048, int,
    "Entry bound for the result cache (LRU past it). Each entry is one "
    "int plus its key, so memory stays O(entries).")

RESULT_CACHE_MIN_AT_LEAST = _register(
    "GEOMESA_TPU_RESULT_CACHE_MIN_AT_LEAST", 3, int,
    "Admission threshold: a result is cached only when its plan hash or "
    "query cell appears in hot_set() with a guaranteed (at_least) count "
    ">= this, so cold one-off queries never pollute the cache. 0 admits "
    "everything (useful in tests).")

RESULT_CACHE_HOTSET_TTL_S = _register(
    "GEOMESA_TPU_RESULT_CACHE_HOTSET_TTL_S", 1.0, float,
    "How long the cache's view of hot_set() admission keys may be "
    "reused before re-reading the workload plane (bounds the per-miss "
    "admission cost to a dict lookup).")

QOS_ENABLED = _register(
    "GEOMESA_TPU_QOS", True, _parse_bool,
    "Master switch for weighted-fair tenant QoS inside admission "
    "control: each tenant's in-flight share of a priority class is "
    "bounded, so a noisy tenant saturates its own share and sheds 429 "
    "while other tenants' latency holds.")

QOS_TENANT_SHARE = _register(
    "GEOMESA_TPU_QOS_TENANT_SHARE", 0.5, float,
    "Maximum fraction of a priority class's in-flight limit one tenant "
    "may hold while other tenants are active (a lone tenant may use "
    "the full class limit — work-conserving, not a hard quota).")

QOS_TENANT_MIN = _register(
    "GEOMESA_TPU_QOS_TENANT_MIN", 2, int,
    "Floor on the per-tenant in-flight share: fairness never starves a "
    "tenant below this many slots regardless of the share fraction.")

QOS_ACTIVE_S = _register(
    "GEOMESA_TPU_QOS_ACTIVE_S", 2.0, float,
    "How long a tenant counts as active after its last admitted request. "
    "The per-tenant share cap engages only while >= 2 tenants are active "
    "in a class (work-conserving: a lone tenant is never throttled), so "
    "this window is how fast a quiet tenant's claim on fairness decays.")

AFFINITY_ENABLED = _register(
    "GEOMESA_TPU_AFFINITY", True, _parse_bool,
    "Master switch for cell-affinity routing: the router stamps each "
    "query's Morton cell and consistently prefers the same healthy "
    "replica for a hot cell, keeping that replica's result/plan/cover "
    "caches warm. Cold cells and freshness=strong fall back to the "
    "health/lag-aware rotation unchanged.")

AFFINITY_MIN_AT_LEAST = _register(
    "GEOMESA_TPU_AFFINITY_MIN_AT_LEAST", 3, int,
    "A query cell counts as hot for affinity routing once the workload "
    "plane guarantees (at_least) this many hits on it in the current "
    "window. 0 pins every cell (useful in tests).")

# -- incremental / mesh-parallel index builds + online reindex (ISSUE 13) -----

MERGE_BUILD = _register(
    "GEOMESA_TPU_MERGE_BUILD", True, _parse_bool,
    "Master switch for delta-incremental merge builds: an LSM delta-tier "
    "flush merges the already-sorted resident run with the freshly-sorted "
    "delta run (merge-by-key; block metadata rebuilt from the merge, not "
    "a re-sort) instead of re-sorting the full table. Destructive paths "
    "(remove/update/upsert-collision/age-off drops/schema change) always "
    "fall back to a full rebuild.")

MERGE_MAX_FRACTION = _register(
    "GEOMESA_TPU_MERGE_MAX_FRACTION", 0.25, float,
    "Largest delta-to-resident row fraction the merge build accepts; a "
    "flush above it (bulk load through the delta tier) takes the full "
    "rebuild, whose O(n log n) sort amortizes better at that scale.")

SHARD_SORT = _register(
    "GEOMESA_TPU_SHARD_SORT", True, _parse_bool,
    "Master switch for the mesh-sharded index-key sort: shards the build "
    "sort across jax.devices() (per-shard lax.sort + sample splitter "
    "exchange + per-partition merge sort), falling back to the "
    "single-device sort on a 1-device mesh. Bitwise-identical permutation "
    "either way.")

SHARD_SORT_MIN = _register(
    "GEOMESA_TPU_SHARD_SORT_MIN", 500_000, int,
    "Row threshold for the mesh-sharded sort: below it the splitter "
    "exchange + cross-device copies cost more than the single-device "
    "sort saves.")

SHARD_SORT_DEVICES = _register(
    "GEOMESA_TPU_SHARD_SORT_DEVICES", 0, int,
    "Device count for the mesh-sharded sort (0 = every local device). "
    "1 disables sharding regardless of GEOMESA_TPU_SHARD_SORT.")

SHARD_SORT_SAMPLES = _register(
    "GEOMESA_TPU_SHARD_SORT_SAMPLES", 64, int,
    "Sorted-key samples drawn per shard for the splitter exchange; more "
    "samples = better partition balance at a few KB extra download.")

REINDEX_THROTTLE_MS = _register(
    "GEOMESA_TPU_REINDEX_THROTTLE_MS", 0.0, float,
    "Sleep between background-reindex build stages, yielding the device "
    "and the GIL to serving queries. 0 builds flat out.")

REINDEX_SNAPSHOT = _register(
    "GEOMESA_TPU_REINDEX_SNAPSHOT", True, _parse_bool,
    "Write a durability snapshot right after a reindex generation "
    "installs (when the store is durable), so followers converge to the "
    "rebuilt generation through the ordinary snapshot catch-up path "
    "instead of waiting for the next threshold crossing.")

# -- fleet soak scoreboard (ISSUE 14) -----------------------------------------

SOAK_PHASE_S = _register(
    "GEOMESA_TPU_SOAK_PHASE_S", 6.0, float,
    "Wall-clock drive window for the fleet soak's steady and recovery "
    "phases (fault phases run event-driven: inject, wait for the "
    "incident, wait for resolution). The full nightly soak multiplies "
    "this; --mini keeps it.")

SOAK_WAIT_S = _register(
    "GEOMESA_TPU_SOAK_WAIT_S", 60.0, float,
    "Per-condition timeout inside the fleet soak (node healthy, "
    "incident open, incident resolved, catch-up complete). A blown "
    "wait fails that phase's checks instead of hanging the run.")

SOAK_FOLLOWERS = _register(
    "GEOMESA_TPU_SOAK_FOLLOWERS", 2, int,
    "Follower count in the soak fleet (primary + N replicas + router, "
    "each a real subprocess over localhost shipping sockets). The "
    "chaos timeline needs at least 2: one to kill, one to promote.")

SOAK_CATCHUP_BUDGET_S = _register(
    "GEOMESA_TPU_SOAK_CATCHUP_BUDGET_S", 30.0, float,
    "Budget for a restarted/re-pointed replica to fully catch up "
    "(applied seq == primary WAL seq). Scored per fault phase as "
    "catchup_s; a breach fails the phase, not the process.")

SOAK_STRETCH = _register(
    "GEOMESA_TPU_SOAK_STRETCH", 1.0, float,
    "Multiplier on the injected chaos magnitudes (lag-spike delay per "
    "frame and frame count). The perfwatch gate self-test runs the "
    "soak with a stretch > 1 and requires the cfg11 check to flag the "
    "regressed catch-up/burn metrics — proving the fleet gate trips.")


# -- multi-process cluster runtime (ISSUE 15) ---------------------------------

CLUSTER = _register(
    "GEOMESA_TPU_CLUSTER", False, _parse_bool,
    "Master switch for the multi-process cluster runtime: when true (or "
    "when GEOMESA_TPU_CLUSTER_COORDINATOR is set) the process joins a "
    "jax.distributed cluster and the feature table is PARTITIONED by "
    "Morton key range across processes instead of replicated — counts/"
    "density psum to the exact global answer on every process, selects "
    "stream per-process matches through a host-side ordered merge.")

CLUSTER_COORDINATOR = _register(
    "GEOMESA_TPU_CLUSTER_COORDINATOR", "", str,
    "Coordinator address host:port for jax.distributed.initialize. "
    "Every process in the cluster passes the SAME address; the process "
    "with id 0 binds it. Setting this implies GEOMESA_TPU_CLUSTER=1.")

CLUSTER_NUM_PROCESSES = _register(
    "GEOMESA_TPU_CLUSTER_NUM_PROCESSES", 1, int,
    "Total process count in the cluster (jax.distributed num_processes). "
    "Must match across every process.")

CLUSTER_PROCESS_ID = _register(
    "GEOMESA_TPU_CLUSTER_PROCESS_ID", 0, int,
    "This process's rank in [0, num_processes) — also its Morton "
    "key-range shard ownership slot (rank order == key order).")

CLUSTER_LOCAL_DEVICES = _register(
    "GEOMESA_TPU_CLUSTER_LOCAL_DEVICES", 0, int,
    "Local device count hint passed to jax.distributed.initialize on "
    "backends that need it (CPU dryruns). 0 lets jax/XLA decide "
    "(XLA_FLAGS --xla_force_host_platform_device_count still applies).")

CLUSTER_TOPOLOGY = _register(
    "GEOMESA_TPU_CLUSTER_TOPOLOGY", "auto", str,
    "Mesh topology policy: 'auto' builds a hybrid ICI x DCN mesh "
    "(create_hybrid_device_mesh) when >1 slice is detected and a flat "
    "process-contiguous 'rows' mesh otherwise; 'flat' forces the flat "
    "mesh (CPU dryruns); 'hybrid' requires multi-slice and raises "
    "without it (fail loudly instead of silently degrading).")

CLUSTER_INIT_TIMEOUT_S = _register(
    "GEOMESA_TPU_CLUSTER_INIT_TIMEOUT_S", 120.0, float,
    "Bound on jax.distributed.initialize rendezvous (a missing peer "
    "fails the bring-up instead of hanging the fleet).")

CLUSTER_WEB_REGISTER = _register(
    "GEOMESA_TPU_CLUSTER_WEB_REGISTER", True, _parse_bool,
    "When a cluster process starts its web surface, exchange the bound "
    "address across processes and install a Federator over ALL of them "
    "on every rank — cluster nodes appear in /fleet with no manual "
    "--addr lists.")


# -- shard balance observatory (ISSUE 16) -------------------------------------

SHARDWATCH_ENABLED = _register(
    "GEOMESA_TPU_SHARDWATCH", True, _parse_bool,
    "Master switch for the per-shard load ledger (obs/shardwatch.py): "
    "joins the workload plane's hot Morton cells against cluster "
    "key-range ownership into per-shard load shares, an imbalance "
    "score, and projected split points. Off: balance surfaces report "
    "inactive and the workload fold hook is skipped.")

SHARDWATCH_TOP_CELLS = _register(
    "GEOMESA_TPU_SHARDWATCH_TOP_CELLS", 32, int,
    "How many hot cells the ledger joins per balance report (the k "
    "passed to workload hot_set). Must stay at or below "
    "GEOMESA_TPU_WORKLOAD_SKETCH_K for the at_least guarantees to "
    "cover every joined cell.")

SHARDWATCH_SPLIT_PARTS = _register(
    "GEOMESA_TPU_SHARDWATCH_SPLIT_PARTS", 2, int,
    "How many pieces a projected split divides the hottest shard into "
    "(parts - 1 boundaries). The boundaries are the candidate split "
    "points ROADMAP item 2's split/migrate plane will consume.")

SHARDWATCH_CELL_STATS = _register(
    "GEOMESA_TPU_SHARDWATCH_CELL_STATS", 256, int,
    "Capacity of the per-cell rows-scanned/device-ms accumulator table "
    "fed by the workload drain hook. Cells past the capacity count "
    "toward the ledger's drop counter instead of growing the table.")

DOCTOR_IMBALANCE_RATIO = _register(
    "GEOMESA_TPU_DOCTOR_IMBALANCE_RATIO", 1.5, float,
    "shard_imbalance bar: the doctor opens an incident when the "
    "GUARANTEED (at_least-based) max-over-mean per-shard load ratio "
    "reaches this value — undercount-proof, so sketch error can never "
    "fake an imbalance.")

DOCTOR_IMBALANCE_MIN = _register(
    "GEOMESA_TPU_DOCTOR_IMBALANCE_MIN", 200, int,
    "Total guaranteed hot-cell load floor below which shard_imbalance "
    "never fires (a handful of queries is not a skew signal).")

DOCTOR_STRAGGLER_MS = _register(
    "GEOMESA_TPU_DOCTOR_STRAGGLER_MS", 50.0, float,
    "Per-round straggler bar: a collective round whose slowest-rank "
    "spread exceeds this many milliseconds charges one straggler count "
    "against that rank (cluster.collective.straggler.rank<p>).")

DOCTOR_STRAGGLER_ROUNDS = _register(
    "GEOMESA_TPU_DOCTOR_STRAGGLER_ROUNDS", 5, int,
    "collective_straggler bar: incidents open when one rank accumulates "
    "this many over-bar straggler rounds inside the doctor window.")


# -- single-dispatch query compilation (ISSUE 17) -----------------------------

FUSED_QUERY = _register(
    "GEOMESA_TPU_FUSED_QUERY", True, _parse_bool,
    "Master switch for single-dispatch query compilation "
    "(index/compiled.py): qualifying plan shapes lower the filter IR "
    "into ONE jitted program (cover + scan + residual + aggregate, one "
    "host->device round trip) and repeat shapes bind through the recipe "
    "fast path without replanning. Off: every query runs the staged "
    "planner/scan path.")

PALLAS_REFINE = _register(
    "GEOMESA_TPU_PALLAS_REFINE", False, _parse_bool,
    "Use the Pallas tiling of the point-in-polygon certainty-band "
    "classifier inside fused refine programs (interpret mode off-TPU). "
    "A one-time probe falls back to the jnp band kernel on any backend "
    "where Pallas lowering fails, so this can never break correctness.")

FUSED_SHAPE_CACHE = _register(
    "GEOMESA_TPU_FUSED_SHAPE_CACHE", 256, int,
    "LRU capacity of the per-planner (filter shape, auths) -> recipe "
    "cache that lets repeat shapes skip planning entirely. Compiled "
    "program bodies are bounded separately by GEOMESA_TPU_KERNEL_CACHE.")

ROUTER_CELL_MEMO = _register(
    "GEOMESA_TPU_ROUTER_CELL_MEMO", 4096, int,
    "LRU capacity of the router's cql -> Morton-cell affinity memo. "
    "Bounds memory under high-cardinality filter streams; size is "
    "exported as the router.cell_memo.size gauge. <= 0 disables "
    "memoization.")


# -- geometry function catalog (ISSUE 18) ------------------------------------

GEOM_KERNELS = _register(
    "GEOMESA_TPU_GEOM_KERNELS", True, _parse_bool,
    "Evaluate st_* residual predicates through the vmapped device "
    "kernels (geom/catalog.py: certainty-banded classify + f64 host "
    "refine of the uncertain sliver — results stay exact). Off: every "
    "Func residual evaluates on the pure-numpy host oracle.")

GEOM_FUSE = _register(
    "GEOMESA_TPU_GEOM_FUSE", True, _parse_bool,
    "Allow eligible Func residuals (st_contains/st_intersects polygon "
    "literals, st_distance < r point literals, on the index geometry of "
    "a point sft) to lower INTO the single-dispatch fused program. Off: "
    "Func queries stage (still kernel-evaluated when GEOM_KERNELS is "
    "on).")

GEOM_CHUNK = _register(
    "GEOMESA_TPU_GEOM_CHUNK", 4_000_000, int,
    "Element budget for the catalog kernels' pairwise tables "
    "(feature-segment x literal-segment); predicate/distance batches "
    "are chunked so B*S*L stays under it.")


# -- shard cells: replicated write cells + shard-aware serving (ISSUE 19) -----

CELL_ENFORCE = _register(
    "GEOMESA_TPU_CELL_ENFORCE", True, _parse_bool,
    "When this node is registered as a member of a shard cell "
    "(cluster/cells.py), refuse ingests whose routing keys fall outside "
    "the cell's Morton key range (HTTP 409 naming the owning shard). "
    "Off: the gate logs a metric but accepts — migration escape hatch.")

CELL_SHARD_BUDGET_FRACTION = _register(
    "GEOMESA_TPU_CELL_SHARD_BUDGET_FRACTION", 0.45, float,
    "Fraction of the REMAINING request deadline carved out as one "
    "shard attempt's deadline budget in the router's scatter-gather "
    "(passed downstream as deadline_ms). < 0.5 leaves room for one "
    "follower retry against the same shard inside the request deadline.")

CELL_SHARD_MIN_BUDGET_MS = _register(
    "GEOMESA_TPU_CELL_SHARD_MIN_BUDGET_MS", 50.0, float,
    "Floor on a per-shard deadline budget: a nearly-spent request "
    "deadline still gives each shard attempt at least this much, so "
    "budget carving degrades to bounded attempts instead of zero-ms "
    "budgets that can never succeed.")

CELL_RETRY_FOLLOWERS = _register(
    "GEOMESA_TPU_CELL_RETRY_FOLLOWERS", True, _parse_bool,
    "On a shard primary failure mid-scatter, retry that shard against "
    "its remaining cell members (the demoted-not-dropped tier) before "
    "declaring the shard missing in the partial-result envelope.")

CELL_KNN_MAX_ROUNDS = _register(
    "GEOMESA_TPU_CELL_KNN_MAX_ROUNDS", 8, int,
    "Hard cap on cluster-knn radius-exchange collective rounds. The "
    "bounded-radius algorithm is exact in 2 (kth-distance psum + "
    "candidate gather); the cap is the runaway guard the dryrun check "
    "pins against.")

CELL_HANDOFF_DRAIN_S = _register(
    "GEOMESA_TPU_CELL_HANDOFF_DRAIN_S", 10.0, float,
    "Ownership handoff budget for draining the old cell owner and "
    "waiting for the successor to reach the old owner's WAL head "
    "before the epoch bump fences the old owner.")

CELL_GEO_KEY_BITS = _register(
    "GEOMESA_TPU_CELL_GEO_KEY_BITS", 8, int,
    "Per-axis bits of the coarse Z2 routing key used to assign "
    "features to shard cells on the serving write path (the dryrun's "
    "table partition uses the exact z3-derived keys instead).")


# -- telemetry history plane + forensic bundles (ISSUE 20) --------------------

HISTORY_ENABLED = _register(
    "GEOMESA_TPU_HISTORY", True, _parse_bool,
    "Master switch for the telemetry-history sampler: selected registry "
    "series (counter rates, gauges, timer p50/p99 bucket deltas) are "
    "snapshotted into wall-clock-aligned ring tiers on the registry "
    "pre-drain hook, so producers pay nothing and readers pay at most "
    "one snapshot per finest-tier interval.")

HISTORY_TIERS = _register(
    "GEOMESA_TPU_HISTORY_TIERS", "2:300,30:240", str,
    "History ring tiers as comma-separated interval_s:slots pairs. The "
    "default keeps 2s resolution for 10 minutes and 30s resolution for "
    "2 hours; memory stays knob-bounded at slots x tracked series.")

HISTORY_SERIES = _register(
    "GEOMESA_TPU_HISTORY_SERIES", "", str,
    "Extra registry series for the history sampler beyond the built-in "
    "set (comma-separated counter/gauge/timer names; prefix match with "
    "a trailing '.'). The built-ins cover scheduler traffic, sheds, "
    "recompiles, replication lag and the query.count timer.")

HISTORY_MAX_SERIES = _register(
    "GEOMESA_TPU_HISTORY_MAX_SERIES", 64, int,
    "Hard cap on distinct series the history sampler tracks per tier "
    "(memory bound; series beyond the cap are dropped and counted "
    "under history.series_dropped).")

HISTORY_SLICE_S = _register(
    "GEOMESA_TPU_HISTORY_SLICE_S", 120.0, float,
    "Width of the history slice (seconds before the firing) captured "
    "into a forensic bundle when the doctor opens an incident — the "
    "timeline window an operator replays around the page.")

FORENSICS_ENABLED = _register(
    "GEOMESA_TPU_FORENSICS", True, _parse_bool,
    "Capture a forensic bundle (history slices, matching flight events, "
    "retained trace gids, replication/cell state, workload hot_set) "
    "when the doctor opens an incident. Bundles stay fetchable in "
    "memory at GET /incidents/{id}/bundle; a directory makes them "
    "durable.")

FORENSICS_DIR = _register(
    "GEOMESA_TPU_FORENSICS_DIR", "", str,
    "Directory for durable forensic bundles (atomic tmp+rename install, "
    "newest GEOMESA_TPU_FORENSICS_KEEP kept). Empty keeps bundles "
    "in-memory only.")

FORENSICS_KEEP = _register(
    "GEOMESA_TPU_FORENSICS_KEEP", 16, int,
    "Size rotation for the forensic bundle directory: all but this many "
    "newest bundle files are deleted after each capture (forensics.gc "
    "counts the drops).")

DOCTOR_TREND = _register(
    "GEOMESA_TPU_DOCTOR_TREND", True, _parse_bool,
    "Enable the predictive doctor rules: slo_trend (burn-rate slope "
    "projects a page before slo_burn fires) and capacity_trend "
    "(per-shard load growth slope projects time-to-imbalance).")

DOCTOR_TREND_LEAD_S = _register(
    "GEOMESA_TPU_DOCTOR_TREND_LEAD_S", 120.0, float,
    "slo_trend projection horizon: an objective whose 5m burn rate, "
    "extrapolated along its fitted slope this many seconds ahead, "
    "crosses the page bar opens a predictive incident while the "
    "current burn is still under it.")

DOCTOR_TREND_MIN_POINTS = _register(
    "GEOMESA_TPU_DOCTOR_TREND_MIN_POINTS", 5, int,
    "Minimum history samples inside the doctor window before either "
    "trend rule may fire (two points always fit a line; a trend is "
    "only evidence once it persists).")

DOCTOR_CAPACITY_LEAD_S = _register(
    "GEOMESA_TPU_DOCTOR_CAPACITY_LEAD_S", 600.0, float,
    "capacity_trend horizon: a shard whose guaranteed max-over-mean "
    "load ratio is growing fast enough to cross the imbalance bar "
    "within this many seconds opens a predictive incident carrying "
    "the projected time-to-imbalance.")

JOURNAL_KEEP = _register(
    "GEOMESA_TPU_JOURNAL_KEEP", 1, int,
    "Rotated generations kept for the incident and flight-recorder "
    "JSONL journals (path.1 .. path.N). The default keeps one rotated "
    "predecessor, matching the historical rotate-once discipline; long "
    "soaks raise it and rely on the keep-N GC (journal.gc counts "
    "dropped generations) to bound disk.")


def describe() -> Dict[str, dict]:
    """name → {value, default, doc} for every registered property
    (the CLI `config` listing / docs surface)."""
    return {
        name: {"value": p.get(), "default": p.default, "doc": p.doc}
        for name, p in sorted(_REGISTRY.items())
    }


def get(name: str) -> SystemProperty:
    return _REGISTRY[name]
