"""Merged and routed multi-store views.

≙ reference `index.view` (SURVEY.md §2.4: MergedDataStoreView.scala:33 —
scatter-gather a query across several stores and concatenate;
RoutedDataStoreView + RouteSelector.scala:17 — send each query to exactly
one store chosen by the filter's attributes)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Union

import numpy as np

from geomesa_tpu.features.table import FeatureTable
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql


def _filter_attributes(f: ir.Filter) -> Set[str]:
    out: Set[str] = set()

    def walk(node):
        if isinstance(node, (ir.And, ir.Or)):
            for c in node.children:
                walk(c)
        elif isinstance(node, ir.Not):
            walk(node.child)
        elif hasattr(node, "attr"):
            out.add(node.attr)

    walk(f)
    return out


class MergedDataStoreView:
    """Scatter-gather across stores sharing a schema (≙ MergedQueryRunner:
    each store queried with the same filter, results concatenated; counts
    sum)."""

    def __init__(self, stores: Sequence[object], type_name: str):
        if not stores:
            raise ValueError("MergedDataStoreView requires at least one store")
        self.stores = list(stores)
        self.type_name = type_name
        specs = {s.get_schema(type_name).to_spec() for s in self.stores}
        if len(specs) > 1:
            raise ValueError(f"Stores disagree on schema for {type_name!r}")

    def count(self, f: Union[str, ir.Filter] = "INCLUDE", auths=None) -> int:
        return sum(s.count(self.type_name, f, auths=auths) for s in self.stores)

    def query(self, f: Union[str, ir.Filter] = "INCLUDE",
              auths=None) -> FeatureTable:
        parts = [s.query(self.type_name, f, auths=auths).table
                 for s in self.stores]
        parts = [p for p in parts if len(p)]
        if not parts:
            return self.stores[0].query(self.type_name, "EXCLUDE").table
        return FeatureTable.concat(parts) if len(parts) > 1 else parts[0]


class RouteSelectorByAttribute:
    """Route on which attributes the filter references (≙
    RouteSelectorByAttribute): first route whose attribute set covers the
    filter's attributes wins; ``default`` catches the rest."""

    def __init__(self, routes: Sequence[tuple],
                 default: Optional[int] = None):
        """routes: (store_index, {attribute names}) pairs."""
        self.routes = [(i, set(attrs)) for i, attrs in routes]
        self.default = default

    def route(self, f: ir.Filter) -> Optional[int]:
        attrs = _filter_attributes(f)
        if attrs:
            for i, route_attrs in self.routes:
                if attrs <= route_attrs:
                    return i
        return self.default


class RoutedDataStoreView:
    """Route each query to exactly ONE store (≙ RoutedDataStoreView —
    merged views scan all stores; routed views pick one)."""

    def __init__(self, stores: Sequence[object], type_name: str, selector):
        self.stores = list(stores)
        self.type_name = type_name
        self.selector = selector

    def _store(self, f):
        i = self.selector.route(f)
        if i is None:
            raise ValueError(
                f"No route for query {f} (and no default configured)")
        return self.stores[i]

    def count(self, f: Union[str, ir.Filter] = "INCLUDE", auths=None) -> int:
        f = parse_ecql(f) if isinstance(f, str) else f
        return self._store(f).count(self.type_name, f, auths=auths)

    def query(self, f: Union[str, ir.Filter] = "INCLUDE", auths=None):
        f = parse_ecql(f) if isinstance(f, str) else f
        return self._store(f).query(self.type_name, f, auths=auths)
