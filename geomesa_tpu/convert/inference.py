"""Type inference for schema-less ingest.

≙ reference `TypeInference` (geomesa-convert/convert2/TypeInference.scala,
477 LoC): sample the input, infer per-column attribute types, name a
geometry. Heuristics mirror the reference: numeric widening Int → Long →
Double, ISO dates, lat/lon column-name pairing into a Point.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_LAT_NAMES = {"lat", "latitude", "y"}
_LON_NAMES = {"lon", "lng", "long", "longitude", "x"}
_ISO_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([T ]\d{2}:\d{2}(:\d{2}(\.\d+)?)?Z?)?$")


def _infer_one(values: Sequence[str]) -> str:
    vals = [str(v).strip() for v in values if str(v).strip() != ""]
    if not vals:
        return "String"
    try:
        ints = [int(v) for v in vals]
        if all(-(1 << 31) <= i < (1 << 31) for i in ints):
            return "Int"
        return "Long"
    except ValueError:
        pass
    try:
        [float(v) for v in vals]
        return "Double"
    except ValueError:
        pass
    if all(_ISO_RE.match(v) for v in vals):
        return "Date"
    if all(v.lower() in ("true", "false") for v in vals):
        return "Boolean"
    if all(re.match(r"^(POINT|LINESTRING|POLYGON|MULTI)", v.upper()) for v in vals):
        m = re.match(r"^(\w+)", vals[0].upper())
        return {"POINT": "Point", "LINESTRING": "LineString",
                "POLYGON": "Polygon", "MULTIPOINT": "MultiPoint",
                "MULTILINESTRING": "MultiLineString",
                "MULTIPOLYGON": "MultiPolygon"}.get(m.group(1), "Geometry")
    return "String"


def infer_schema(names: List[str], sample_rows: Sequence[Sequence[str]],
                 type_name: str = "features") -> Tuple[str, Dict[str, str]]:
    """(sft spec string, field-name → transform expression map).

    The transforms feed a converter config directly: numeric/date columns get
    to*/isoDateTime casts, a detected (lon, lat) pair becomes ``point()``,
    WKT columns become ``geometry()``.
    """
    cols = list(zip(*sample_rows)) if sample_rows else [[] for _ in names]
    types = {n: _infer_one(c) for n, c in zip(names, cols)}

    lat = next((n for n in names if n.lower() in _LAT_NAMES
                and types[n] in ("Double", "Int", "Long")), None)
    lon = next((n for n in names if n.lower() in _LON_NAMES
                and types[n] in ("Double", "Int", "Long")), None)

    attrs, transforms = [], {}
    geom_done = False
    for n in names:
        t = types[n]
        safe = re.sub(r"\W", "_", n)
        if n in (lat, lon) and lat and lon:
            continue  # folded into the point
        if t in ("Point", "LineString", "Polygon", "MultiPoint",
                 "MultiLineString", "MultiPolygon", "Geometry"):
            star = "" if geom_done else "*"
            attrs.append(f"{star}{safe}:{t}")
            transforms[safe] = f"geometry(${{{n}}})"
            geom_done = True
            continue
        attrs.append(f"{safe}:{t}")
        transforms[safe] = {
            "Int": f"toInt(${{{n}}})", "Long": f"toLong(${{{n}}})",
            "Double": f"toDouble(${{{n}}})", "Date": f"isoDateTime(${{{n}}})",
            "Boolean": f"toBoolean(${{{n}}})",
        }.get(t, f"toString(${{{n}}})")
    if lat and lon and not geom_done:
        attrs.append("*geom:Point")
        transforms["geom"] = f"point(${{{lon}}}, ${{{lat}}})"
    return ",".join(attrs), transforms


def converter_config_from_inference(spec: str, transforms: Dict[str, str],
                                    fmt: str = "delimited-text") -> dict:
    return {
        "type": fmt,
        "fields": [{"name": n, "transform": t} for n, t in transforms.items()],
    }
