"""Avro Object Container File reader (ingest format).

≙ the reference's Avro support (geomesa-feature-avro serializer + the
geomesa-convert-avro ingest module). This is a self-contained reader for the
public Avro 1.x container spec — no avro library ships in this image:

  - header: magic 'Obj\\x01', metadata map (avro.schema JSON, avro.codec),
    16-byte sync marker
  - blocks: [record count, byte length, payload, sync]; null/deflate codecs
  - binary encoding: zigzag varints (int/long), little-endian float/double,
    length-prefixed bytes/string, index-prefixed unions, arrays/maps in
    blocks

Supported schema subset for columnar ingest: a top-level record of
primitives (null/boolean/int/long/float/double/bytes/string), nullable
unions of those, enums, and logicalType timestamp-millis — the shapes the
reference's converter consumes. Output: field name → numpy object column,
ready for the shared converter pipeline.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import BinaryIO, Dict, List, Tuple

import numpy as np

_MAGIC = b"Obj\x01"


def _read_long(buf: BinaryIO) -> int:
    """Zigzag varint."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _read_bytes(buf: BinaryIO) -> bytes:
    n = _read_long(buf)
    return buf.read(n)


def _read_value(buf: BinaryIO, schema):
    if isinstance(schema, list):  # union: index-prefixed
        idx = _read_long(buf)
        return _read_value(buf, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)  # block byte size (skippable form)
                    n = -n
                out.extend(_read_value(buf, schema["items"]) for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    out[_read_bytes(buf).decode()] = _read_value(
                        buf, schema["values"])
            return out
        if t == "fixed":
            return buf.read(schema["size"])
        return _read_value(buf, t)  # annotated primitive (logicalType rides)
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"Unsupported Avro schema {schema!r}")


def read_avro_records(path_or_bytes) -> Tuple[List[dict], dict]:
    """Container file → (records, schema dict)."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        f = io.BytesIO(path_or_bytes)
    else:
        f = open(path_or_bytes, "rb")
    try:
        if f.read(4) != _MAGIC:
            raise ValueError("Not an Avro container file")
        meta: Dict[str, bytes] = {}
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                key = _read_bytes(f).decode()
                meta[key] = _read_bytes(f)
        sync = f.read(16)
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode()
        if schema.get("type") != "record":
            raise ValueError("Top-level Avro schema must be a record")
        fields = schema["fields"]
        records: List[dict] = []
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, 1)
            count = _read_long(f)
            size = _read_long(f)
            payload = f.read(size)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec != "null":
                raise ValueError(f"Unsupported Avro codec {codec!r}")
            if f.read(16) != sync:
                raise ValueError("Avro sync marker mismatch")
            b = io.BytesIO(payload)
            for _ in range(count):
                records.append({fd["name"]: _read_value(b, fd["type"])
                                for fd in fields})
        return records, schema
    finally:
        f.close()


def read_avro_columns(path_or_bytes) -> Dict[str, np.ndarray]:
    """Container file → field columns (object arrays; timestamp-millis
    logical values stay as int64 epoch millis — the Date convention)."""
    records, schema = read_avro_records(path_or_bytes)
    names = [fd["name"] for fd in schema["fields"]]
    return {name: np.asarray([r.get(name) for r in records], dtype=object)
            for name in names}
