"""Avro Object Container File reader (ingest format).

≙ the reference's Avro support (geomesa-feature-avro serializer + the
geomesa-convert-avro ingest module). This is a self-contained reader for the
public Avro 1.x container spec — no avro library ships in this image:

  - header: magic 'Obj\\x01', metadata map (avro.schema JSON, avro.codec),
    16-byte sync marker
  - blocks: [record count, byte length, payload, sync]; null/deflate codecs
  - binary encoding: zigzag varints (int/long), little-endian float/double,
    length-prefixed bytes/string, index-prefixed unions, arrays/maps in
    blocks

Supported schema subset for columnar ingest: a top-level record of
primitives (null/boolean/int/long/float/double/bytes/string), nullable
unions of those, enums, and logicalType timestamp-millis — the shapes the
reference's converter consumes. Output: field name → numpy object column,
ready for the shared converter pipeline.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import BinaryIO, Dict, List, Optional, Tuple

import numpy as np

_MAGIC = b"Obj\x01"


def _read_long(buf: BinaryIO) -> int:
    """Zigzag varint."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _read_exact(buf: BinaryIO, n: int) -> bytes:
    """Read exactly n bytes — corrupt/negative lengths must fail loudly, not
    consume the rest of the stream and mis-frame every later read."""
    if n < 0:
        raise ValueError(f"negative Avro length {n} (corrupt file)")
    data = buf.read(n)
    if len(data) != n:
        raise ValueError(f"truncated Avro data: wanted {n} bytes, "
                         f"got {len(data)}")
    return data


def _read_bytes(buf: BinaryIO) -> bytes:
    return _read_exact(buf, _read_long(buf))


def _read_value(buf: BinaryIO, schema):
    if isinstance(schema, list):  # union: index-prefixed
        idx = _read_long(buf)
        return _read_value(buf, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)  # block byte size (skippable form)
                    n = -n
                out.extend(_read_value(buf, schema["items"]) for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    out[_read_bytes(buf).decode()] = _read_value(
                        buf, schema["values"])
            return out
        if t == "fixed":
            return _read_exact(buf, schema["size"])
        return _read_value(buf, t)  # annotated primitive (logicalType rides)
    if schema == "null":
        return None
    if schema == "boolean":
        return _read_exact(buf, 1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", _read_exact(buf, 4))[0]
    if schema == "double":
        return struct.unpack("<d", _read_exact(buf, 8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"Unsupported Avro schema {schema!r}")


def resolve_schema(records: List[dict], writer: dict,
                   reader: dict) -> List[dict]:
    """Avro schema resolution (the reader-schema half of the spec's schema
    evolution rules; ≙ geomesa-feature-avro's version-mismatch readers):
    fields match by name or reader ``aliases``; reader-only fields take
    their ``default`` (required by the spec — missing default raises);
    writer-only fields drop; numeric promotions int→long→float→double and
    string↔bytes apply."""
    if reader.get("type") != "record":
        raise ValueError("Reader schema must be a record")
    wtypes = {fd["name"]: fd["type"] for fd in writer.get("fields", [])}
    plan = []  # (out_name, source_name | None, promote, default)
    for fd in reader["fields"]:
        names = [fd["name"]] + list(fd.get("aliases", []))
        src = next((nm for nm in names if nm in wtypes), None)
        if src is None:
            if "default" not in fd:
                raise ValueError(
                    f"Reader field {fd['name']!r} absent from writer "
                    "schema and has no default")
            plan.append((fd["name"], None, None, fd["default"]))
            continue
        plan.append((fd["name"], src,
                     _promotion(wtypes[src], fd["type"]), None))
    out = []
    for rec in records:
        out.append({name: (default if src is None
                           else promote(rec[src]) if promote
                           else rec[src])
                    for name, src, promote, default in plan})
    return out


def _base(t):
    if isinstance(t, dict):
        t = t.get("type")
    return t


def _promotion(wt, rt):
    """Value promotion fn for (writer type, reader type), or None.

    Unions resolve per the spec: a writer union's datum resolves against
    its matching branch (values here are already decoded, so the ubiquitous
    nullable pattern ["null", T] maps null→null when the reader accepts
    null, and promotes non-null data via the T branch)."""
    w, r = _base(wt), _base(rt)
    if isinstance(w, list):
        wbranches = [_base(b) for b in w]
        rbranches = [_base(b) for b in r] if isinstance(r, list) else [r]
        if set(wbranches) <= set(rbranches):
            return None  # every writer branch acceptable as-is
        nonnull = [b for b in wbranches if b != "null"]
        if len(nonnull) != 1:
            raise ValueError(
                f"Cannot resolve writer union {wbranches} to reader {r!r}")
        null_ok = "null" in rbranches
        target = next((b for b in rbranches if b != "null"), None)
        inner = _promotion(nonnull[0], target)

        def resolve(v, _inner=inner, _null_ok=null_ok):
            if v is None:
                if _null_ok:
                    return None
                raise ValueError(
                    "null datum cannot resolve to a non-nullable reader type")
            return _inner(v) if _inner else v

        return resolve
    if isinstance(r, list):
        rbranches = [_base(b) for b in r]
        if w in rbranches:
            return None
        for b in rbranches:  # first promotable branch wins (spec order)
            if b == "null":
                continue
            try:
                return _promotion(w, b)
            except ValueError:
                continue
        raise ValueError(f"Cannot resolve writer {w!r} to reader union {r!r}")
    if w == r or not isinstance(r, str):
        return None
    if w == "int" and r == "long":
        return int
    if w in ("int", "long") and r in ("float", "double"):
        return float
    if w == "float" and r == "double":
        return float
    if w == "string" and r == "bytes":
        return lambda v: v.encode("utf-8")
    if w == "bytes" and r == "string":
        return lambda v: v.decode("utf-8")
    raise ValueError(f"Cannot resolve writer type {w!r} to reader {r!r}")


def read_avro_records(path_or_bytes,
                      reader_schema: Optional[dict] = None
                      ) -> Tuple[List[dict], dict]:
    """Container file → (records, schema dict). With ``reader_schema``,
    records project through Avro schema resolution (evolution: renamed/
    added/removed fields, numeric promotions) and the reader schema is
    returned."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        f = io.BytesIO(path_or_bytes)
    else:
        f = open(path_or_bytes, "rb")
    try:
        if f.read(4) != _MAGIC:
            raise ValueError("Not an Avro container file")
        meta: Dict[str, bytes] = {}
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                key = _read_bytes(f).decode()
                meta[key] = _read_bytes(f)
        sync = f.read(16)
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode()
        if schema.get("type") != "record":
            raise ValueError("Top-level Avro schema must be a record")
        fields = schema["fields"]
        records: List[dict] = []
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, 1)
            count = _read_long(f)
            size = _read_long(f)
            payload = f.read(size)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec != "null":
                raise ValueError(f"Unsupported Avro codec {codec!r}")
            if f.read(16) != sync:
                raise ValueError("Avro sync marker mismatch")
            b = io.BytesIO(payload)
            for _ in range(count):
                records.append({fd["name"]: _read_value(b, fd["type"])
                                for fd in fields})
        if reader_schema is not None:
            return resolve_schema(records, schema, reader_schema), \
                reader_schema
        return records, schema
    finally:
        f.close()


def _write_long(out: bytearray, v: int) -> None:
    u = (v << 1) ^ (v >> 63)
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _write_str(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    _write_long(out, len(b))
    out += b


_AVRO_TYPES = {
    "String": "string", "Int": "int", "Integer": "int", "Long": "long",
    "Float": "float", "Double": "double", "Boolean": "boolean",
}


def write_avro(table, path: str, codec: str = "deflate") -> None:
    """FeatureTable → Avro container file (the export side, ≙ the
    geomesa-feature-avro serializer + the CLI avro export format).

    Schema: record of the SFT's attributes — primitives map directly, Date
    becomes long timestamp-millis, geometries become WKB ``bytes``; the fid
    rides as a ``__fid__`` string field (round-trips through
    read_avro_columns)."""
    from geomesa_tpu.features.table import StringColumn
    from geomesa_tpu.features.twkb import encode_wkb

    sft = table.sft
    fields = [{"name": "__fid__", "type": "string"}]
    writers = []  # (write_fn, per-row values)
    n = len(table)
    fids = [str(f) for f in table.fids]
    for attr in sft.attributes:
        col = table.columns[attr.name]
        if attr.is_geometry:
            fields.append({"name": attr.name, "type": "bytes"})
            vals = encode_wkb(col)
            writers.append(("bytes", vals))
        elif attr.type_name == "Date":
            fields.append({"name": attr.name,
                           "type": {"type": "long",
                                    "logicalType": "timestamp-millis"}})
            writers.append(("long", np.asarray(col, dtype=np.int64)))
        elif attr.type_name in _AVRO_TYPES:
            t = _AVRO_TYPES[attr.type_name]
            fields.append({"name": attr.name, "type": t})
            if isinstance(col, StringColumn):
                writers.append(("string", col.decode(np.arange(n))))
            else:
                writers.append((t, np.asarray(col)))
        else:
            raise ValueError(f"Cannot export {attr.type_name} to Avro")
    import re as _re
    # Avro name grammar: [A-Za-z_][A-Za-z0-9_]* — sanitize SFT/attr names so
    # spec-compliant readers (Java Avro, fastavro) accept the file
    def _avro_name(s: str) -> str:
        s = _re.sub(r"[^A-Za-z0-9_]", "_", str(s) or "feature")
        return s if _re.match(r"[A-Za-z_]", s) else "_" + s
    for fd in fields:
        fd["name"] = _avro_name(fd["name"]) if fd["name"] != "__fid__" else "__fid__"
    schema = {"type": "record", "name": _avro_name(sft.name),
              "fields": fields}

    body = bytearray()
    for i in range(n):
        _write_str(body, fids[i])
        for t, vals in writers:
            v = vals[i]
            if t == "string":
                _write_str(body, str(v))
            elif t == "bytes":
                b = bytes(v)
                _write_long(body, len(b))
                body += b
            elif t in ("int", "long"):
                _write_long(body, int(v))
            elif t == "float":
                body += struct.pack("<f", float(v))
            elif t == "double":
                body += struct.pack("<d", float(v))
            elif t == "boolean":
                body.append(1 if v else 0)
    payload = bytes(body)
    if codec == "deflate":
        c = zlib.compressobj(wbits=-15)
        payload = c.compress(payload) + c.flush()
    elif codec != "null":
        raise ValueError(f"Unsupported Avro codec {codec!r}")

    out = bytearray(_MAGIC)
    _write_long(out, 2)
    _write_str(out, "avro.schema")
    sb = json.dumps(schema).encode()
    _write_long(out, len(sb))
    out += sb
    _write_str(out, "avro.codec")
    _write_str(out, codec)
    _write_long(out, 0)
    sync = b"geomesa-tpu-sync"  # any 16 bytes
    out += sync
    _write_long(out, n)
    _write_long(out, len(payload))
    out += payload + sync
    with open(path, "wb") as f:
        f.write(bytes(out))


def read_avro_columns(path_or_bytes,
                      reader_schema: Optional[dict] = None
                      ) -> Dict[str, np.ndarray]:
    """Container file → field columns (object arrays; timestamp-millis
    logical values stay as int64 epoch millis — the Date convention).
    ``reader_schema`` engages schema resolution (see read_avro_records)."""
    records, schema = read_avro_records(path_or_bytes, reader_schema)
    names = [fd["name"] for fd in schema["fields"]]
    return {name: np.asarray([r.get(name) for r in records], dtype=object)
            for name in names}
