"""SimpleFeatureConverter: raw records → FeatureTable.

≙ reference `convert2.AbstractConverter` (AbstractConverter.scala:50 —
parse → transform → validate pipeline with error modes) and the converter
config surface (type, id-field, fields with transforms, options). Columnar:
the format frontend produces whole columns ($1..$N / named), every field
transform is one vectorized expression evaluation, validation is a mask.

Config (dict / JSON, mirroring the reference's HOCON layout)::

    {
      "type": "delimited-text" | "json",
      "id-field": "md5($1)",                 # optional; default = row number
      "fields": [
        {"name": "dtg",  "transform": "isoDateTime($2)"},
        {"name": "geom", "transform": "point($4, $3)"},
        ...
      ],
      "options": {"error-mode": "skip-bad-records" | "raise-errors",
                  "validators": ["index"]}
    }
"""

from __future__ import annotations

import csv as _csv
import io
import json as _json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from geomesa_tpu.convert.expression import PointPair, parse_expression
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable


@dataclass
class ConverterConfig:
    type: str
    fields: List[dict]
    id_field: Optional[str] = None
    options: Dict[str, object] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "ConverterConfig":
        return cls(type=d.get("type", "delimited-text"),
                   fields=list(d["fields"]),
                   id_field=d.get("id-field"),
                   options=dict(d.get("options", {})))


class SimpleFeatureConverter:
    """One converter instance per (config, sft) — reusable across batches."""

    def __init__(self, config: Union[dict, ConverterConfig], sft: SimpleFeatureType):
        self.config = config if isinstance(config, ConverterConfig) \
            else ConverterConfig.from_dict(config)
        self.sft = sft
        self._transforms = {
            f["name"]: parse_expression(f["transform"]) for f in self.config.fields
        }
        self._id_expr = parse_expression(self.config.id_field) \
            if self.config.id_field else None
        missing = [a.name for a in sft.attributes if a.name not in self._transforms]
        if missing:
            raise ValueError(f"Converter defines no transform for {missing}")
        self.error_mode = str(self.config.options.get(
            "error-mode", "skip-bad-records"))
        self.skipped = 0   # running count of dropped records (metrics)

    # -- frontends -----------------------------------------------------------

    def convert_delimited(self, text_or_path: str, delimiter: str = ",",
                          header: bool = True) -> FeatureTable:
        """CSV/TSV → table. Columns surface as $1..$N and, with a header,
        also by name (≙ DelimitedTextConverter)."""
        if _looks_like_path(text_or_path):
            with open(text_or_path, newline="") as fh:
                rows = list(_csv.reader(fh, delimiter=delimiter))
        else:
            rows = list(_csv.reader(io.StringIO(text_or_path), delimiter=delimiter))
        if not rows:
            return self._empty()
        names = None
        if header:
            names, rows = rows[0], rows[1:]
        if not rows:
            return self._empty()
        ncol = max(len(r) for r in rows)
        mat = np.full((len(rows), ncol), "", dtype=object)
        for i, r in enumerate(rows):
            mat[i, : len(r)] = r
        fields = {str(i + 1): mat[:, i] for i in range(ncol)}
        if names:
            for i, nm in enumerate(names[:ncol]):
                fields[nm.strip()] = mat[:, i]
        return self._convert(fields, len(rows))

    def convert_json(self, text_or_path: str) -> FeatureTable:
        """JSON array or JSON-lines → table; field refs are top-level keys,
        dotted paths reach nested objects (≙ the JsonConverter's json-path
        subset)."""
        if _looks_like_path(text_or_path):
            with open(text_or_path) as fh:
                raw = fh.read()
        else:
            raw = text_or_path
        raw = raw.strip()
        if raw.startswith("["):
            records = _json.loads(raw)
        else:
            records = [_json.loads(line) for line in raw.splitlines() if line.strip()]
        if not records:
            return self._empty()

        def walk(obj, path):
            for p in path.split("."):
                if not isinstance(obj, dict) or p not in obj:
                    return None
                obj = obj[p]
            return obj

        paths = set()
        for e in self._transforms.values():
            _collect_refs(e, paths)
        if self._id_expr is not None:
            _collect_refs(self._id_expr, paths)
        fields = {p: np.asarray([walk(r, p) for r in records], dtype=object)
                  for p in paths}
        return self._convert(fields, len(records))

    def convert_columns(self, columns: Dict[str, np.ndarray]) -> FeatureTable:
        """Pre-parsed columnar input (the fast path for e.g. pandas/pyarrow
        CSV frontends)."""
        n = len(next(iter(columns.values())))
        return self._convert({k: np.asarray(v, dtype=object)
                              for k, v in columns.items()}, n)

    def convert_parquet(self, path: str) -> FeatureTable:
        """Parquet ingest (≙ geomesa-convert-parquet): columns become field
        refs by name; the expression pipeline applies as for any format."""
        from geomesa_tpu.convert.formats import read_parquet_columns
        cols = read_parquet_columns(path)
        if not cols:
            return self._empty()
        return self.convert_columns(cols)

    def convert_avro(self, path_or_bytes) -> FeatureTable:
        """Avro container-file ingest (≙ geomesa-convert-avro): record
        fields become field refs by name."""
        from geomesa_tpu.convert.avro import read_avro_columns
        cols = read_avro_columns(path_or_bytes)
        if not cols:
            return self._empty()
        return self._convert(cols, len(next(iter(cols.values()))))

    def convert_xml(self, text_or_path: str, record_tag: str) -> FeatureTable:
        """XML ingest (≙ geomesa-convert-xml): one feature per
        ``record_tag`` element; child elements and @attributes are fields."""
        from geomesa_tpu.convert.formats import read_xml_records
        cols = read_xml_records(text_or_path, record_tag)
        if not cols:
            return self._empty()
        return self._convert(cols, len(next(iter(cols.values()))))

    def convert_osm(self, text_or_path: str,
                    element: str = "node") -> FeatureTable:
        """OpenStreetMap XML ingest (≙ geomesa-convert-osm): nodes as
        points (id/lon/lat/user/timestamp/tags fields) or ways as resolved
        LineString WKT in a ``geometry`` field; ``tags`` is JSON text for
        the jsonPath expression function."""
        from geomesa_tpu.convert.formats import read_osm
        cols = read_osm(text_or_path, element)
        if not cols or not len(next(iter(cols.values()))):
            return self._empty()
        return self._convert(cols, len(next(iter(cols.values()))))

    def convert_jdbc(self, conn_or_path, sql: str) -> FeatureTable:
        """SQL ingest (≙ geomesa-convert-jdbc): result-set columns become
        field refs by name. ``conn_or_path``: sqlite3 path / jdbc:sqlite:
        URL, or any DB-API connection."""
        from geomesa_tpu.convert.formats import read_jdbc
        cols = read_jdbc(conn_or_path, sql)
        if not cols or not len(next(iter(cols.values()))):
            return self._empty()
        return self._convert(cols, len(next(iter(cols.values()))))

    def convert_fixed_width(self, text_or_path: str,
                            fields) -> FeatureTable:
        """Fixed-width text ingest (≙ geomesa-convert-fixedwidth).
        ``fields``: (name, start, width) byte slices per column."""
        from geomesa_tpu.convert.formats import read_fixed_width
        cols = read_fixed_width(text_or_path, fields)
        if not cols:
            return self._empty()
        return self._convert(cols, len(next(iter(cols.values()))))

    # -- core ----------------------------------------------------------------

    def _convert(self, fields: Dict[str, np.ndarray], n: int) -> FeatureTable:
        if self.error_mode == "raise-errors":
            return self._convert_strict(fields, n)
        try:
            return self._convert_strict(fields, n)
        except Exception:
            # batch-level failure → per-row fallback: convert singletons and
            # drop the bad ones (≙ skip-bad-records; batch-first keeps the
            # columnar fast path for clean data)
            good_rows = []
            for i in range(n):
                row = {k: v[i: i + 1] for k, v in fields.items()}
                try:
                    self._convert_strict(row, 1)
                    good_rows.append(i)
                except Exception:
                    self.skipped += 1
            idx = np.asarray(good_rows, dtype=np.int64)
            return self._convert_strict({k: v[idx] for k, v in fields.items()},
                                        len(idx))

    def _convert_strict(self, fields: Dict[str, np.ndarray], n: int) -> FeatureTable:
        data: Dict[str, object] = {}
        for attr in self.sft.attributes:
            out = self._transforms[attr.name].eval(fields, n)
            if isinstance(out, PointPair):
                data[attr.name] = (out.x, out.y)
            else:
                data[attr.name] = out
        fids = None
        if self._id_expr is not None:
            fids = [str(v) for v in self._id_expr.eval(fields, n)]
        return FeatureTable.build(self.sft, data, fids=fids)

    def _empty(self) -> FeatureTable:
        return FeatureTable.build(
            self.sft, {a.name: (np.empty(0), np.empty(0)) if a.is_geometry
                       else np.empty(0, dtype=object)
                       for a in self.sft.attributes})


def _looks_like_path(s: str) -> bool:
    """Disambiguate path vs inline content: an existing file wins; otherwise
    content (a missing file named like data would silently convert as one
    record, so a path-looking string that does not exist raises)."""
    import os
    if os.path.exists(s):
        return True
    if "\n" not in s and s.endswith((".csv", ".tsv", ".txt", ".json", ".jsonl", ".xml", ".dat", ".fw")):
        raise FileNotFoundError(f"No such file: {s}")
    return False


def _collect_refs(expr, out: set) -> None:
    from geomesa_tpu.convert.expression import Call, FieldRef
    if isinstance(expr, FieldRef):
        out.add(expr.name)
    elif isinstance(expr, Call):
        for a in expr.args:
            _collect_refs(a, out)
