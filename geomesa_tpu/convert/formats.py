"""Additional ingest formats: Parquet, XML, fixed-width, ESRI Shapefile.

≙ the reference's format modules under geomesa-convert-* (SURVEY.md §2.10:
text/CSV, JSON, XML, Avro, Parquet, shapefile, fixed-width …). Each format
lands raw fields as numpy columns and runs the shared converter pipeline
(expression transforms + validation in convert/converter.py), exactly as
every reference format funnels through AbstractConverter.scala:50.

The shapefile reader is self-contained (the .shp/.dbf binary layouts are
small public specs) — points, multipoints, polylines and polygons, with
attributes from the sidecar dBASE file.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo


# -- parquet -----------------------------------------------------------------


def read_parquet_columns(path: str) -> Dict[str, np.ndarray]:
    """Parquet file → raw field columns (strings as object arrays)."""
    import pyarrow.parquet as pq

    at = pq.read_table(path)
    out: Dict[str, np.ndarray] = {}
    for name in at.column_names:
        col = at.column(name).combine_chunks()
        import pyarrow as pa
        if pa.types.is_dictionary(col.type):
            col = col.cast(col.type.value_type)
        if pa.types.is_string(col.type) or pa.types.is_large_string(col.type) \
                or pa.types.is_binary(col.type):
            out[name] = np.asarray(col.to_pylist(), dtype=object)
        elif pa.types.is_timestamp(col.type):
            # normalize to epoch MILLIS regardless of the file's unit
            out[name] = np.asarray(col.cast(pa.timestamp("ms")).cast("int64"))
        else:
            out[name] = np.asarray(col)
    return out


# -- xml ---------------------------------------------------------------------


def read_xml_records(text_or_path: str, record_tag: str) -> Dict[str, np.ndarray]:
    """XML → columns: one record per ``record_tag`` element; fields are the
    record's child-element texts and attributes (attribute keys prefixed
    ``@``). ≙ the XPath field extraction of geomesa-convert-xml."""
    import xml.etree.ElementTree as ET

    from geomesa_tpu.convert.converter import _looks_like_path

    if _looks_like_path(text_or_path):
        root = ET.parse(text_or_path).getroot()
    else:
        root = ET.fromstring(text_or_path)
    records = root.iter(record_tag)
    rows: List[Dict[str, str]] = []
    for rec in records:
        row: Dict[str, str] = dict((f"@{k}", v) for k, v in rec.attrib.items())
        for child in rec:
            row[child.tag] = (child.text or "").strip()
        rows.append(row)
    names = sorted({k for r in rows for k in r})
    return {name: np.asarray([r.get(name, "") for r in rows], dtype=object)
            for name in names}


# -- fixed width -------------------------------------------------------------


def read_fixed_width(text_or_path: str, fields: Sequence[Tuple[str, int, int]]
                     ) -> Dict[str, np.ndarray]:
    """Fixed-width text → columns. fields: (name, start, width) per column
    (0-based byte offsets; values strip whitespace)."""
    from geomesa_tpu.convert.converter import _looks_like_path

    if _looks_like_path(text_or_path):
        with open(text_or_path) as f:
            lines = [l.rstrip("\n") for l in f if l.strip()]
    else:
        lines = [l for l in text_or_path.splitlines() if l.strip()]
    out: Dict[str, np.ndarray] = {}
    for name, start, width in fields:
        out[name] = np.asarray(
            [l[start:start + width].strip() for l in lines], dtype=object)
    return out


# -- shapefile ---------------------------------------------------------------

_SHP_POINT, _SHP_POLYLINE, _SHP_POLYGON, _SHP_MULTIPOINT = 1, 3, 5, 8


def read_shapefile(path: str):
    """ESRI shapefile → (GeometryArray, attribute columns from the .dbf).

    Supports Point (1), PolyLine (3), Polygon (5) and MultiPoint (8) records
    (plus their Z/M variants, ignoring Z/M). Null shapes become empty
    geometries are skipped along with their attribute rows."""
    base, _ = os.path.splitext(path)
    shapes: List[tuple] = []
    keep_rows: List[int] = []
    with open(base + ".shp", "rb") as f:
        header = f.read(100)
        if struct.unpack(">i", header[:4])[0] != 9994:
            raise ValueError("Not a shapefile (bad magic)")
        rec = 0
        while True:
            rh = f.read(8)
            if len(rh) < 8:
                break
            (_num, length) = struct.unpack(">ii", rh)
            content = f.read(length * 2)
            raw_type = struct.unpack("<i", content[:4])[0]
            # fold the documented Z/M variants onto the base types; anything
            # else (MultiPatch=31, ...) is unsupported and skips the record
            shape_type = raw_type % 10 if raw_type in (
                1, 3, 5, 8, 11, 13, 15, 18, 21, 23, 25, 28) else -1
            if shape_type == _SHP_POINT:
                x, y = struct.unpack("<dd", content[4:20])
                shapes.append((geo.POINT, [x, y]))
                keep_rows.append(rec)
            elif shape_type in (_SHP_POLYLINE, _SHP_POLYGON):
                nparts, npoints = struct.unpack("<ii", content[36:44])
                parts = struct.unpack(f"<{nparts}i", content[44:44 + 4 * nparts])
                pts_off = 44 + 4 * nparts
                pts = np.frombuffer(
                    content[pts_off:pts_off + 16 * npoints],
                    dtype="<f8").reshape(npoints, 2)
                bounds = list(parts) + [npoints]
                rings = [pts[bounds[i]:bounds[i + 1]].tolist()
                         for i in range(nparts)]
                if shape_type == _SHP_POLYGON:
                    shapes.append((geo.POLYGON, rings))
                elif nparts == 1:
                    shapes.append((geo.LINESTRING, rings[0]))
                else:
                    shapes.append((geo.MULTILINESTRING, rings))
                keep_rows.append(rec)
            elif shape_type == _SHP_MULTIPOINT:
                npoints = struct.unpack("<i", content[36:40])[0]
                pts = np.frombuffer(content[40:40 + 16 * npoints],
                                    dtype="<f8").reshape(npoints, 2)
                shapes.append((geo.MULTIPOINT, pts.tolist()))
                keep_rows.append(rec)
            # shape_type 0 (null) and unsupported types skip the record
            rec += 1
    garr = geo.GeometryArray.from_shapes(shapes)
    attrs = {}
    if os.path.exists(base + ".dbf"):
        attrs = _read_dbf(base + ".dbf")
        attrs = {k: v[np.asarray(keep_rows, dtype=np.int64)]
                 for k, v in attrs.items()}
    return garr, attrs


def _read_dbf(path: str) -> Dict[str, np.ndarray]:
    """dBASE III attribute table → object columns (numeric fields parse to
    float/int where clean)."""
    with open(path, "rb") as f:
        header = f.read(32)
        n_records = struct.unpack("<i", header[4:8])[0]
        header_len, record_len = struct.unpack("<hh", header[8:12])
        fields = []
        while True:
            fd = f.read(32)
            if fd[0:1] == b"\r" or len(fd) < 32:
                break
            name = fd[:11].split(b"\x00")[0].decode("ascii", "replace")
            ftype = fd[11:12].decode("ascii")
            size = fd[16]
            fields.append((name, ftype, size))
        f.seek(header_len)
        raw: Dict[str, list] = {name: [] for name, _, _ in fields}
        for _ in range(n_records):
            rec = f.read(record_len)
            if len(rec) < record_len or rec[0:1] == b"\x1a":
                break
            pos = 1  # deletion flag
            for name, ftype, size in fields:
                val = rec[pos:pos + size].decode("latin-1").strip()
                raw[name].append(val)
                pos += size
    out: Dict[str, np.ndarray] = {}
    for name, ftype, _ in fields:
        vals = raw[name]
        if ftype in ("N", "F"):
            def num(v):
                try:
                    fv = float(v)
                    return int(fv) if fv.is_integer() else fv
                except ValueError:
                    return 0
            out[name] = np.asarray([num(v) for v in vals], dtype=object)
        else:
            out[name] = np.asarray(vals, dtype=object)
    return out


def read_osm(text_or_path: str, element: str = "node") -> Dict[str, np.ndarray]:
    """OpenStreetMap XML → columns (≙ geomesa-convert-osm's osm4j frontend).

    element='node': one row per node — id, lon, lat, user, timestamp, and
    ``tags`` as a JSON text column (individual keys reach transforms via
    the jsonPath expression function).
    element='way': one row per way — id, user, timestamp, tags, and
    ``geometry`` as LineString WKT resolved from the way's node refs
    (ways referencing unknown nodes are dropped, as the reference does
    when its node cache misses).
    """
    import json as _json
    import xml.etree.ElementTree as ET

    if os.path.exists(text_or_path):
        root = ET.parse(text_or_path).getroot()
    else:
        root = ET.fromstring(text_or_path)
    if element not in ("node", "way"):
        raise ValueError("element must be 'node' or 'way'")

    def tags_of(el):
        return _json.dumps({t.get("k"): t.get("v")
                            for t in el.findall("tag")})

    cols: Dict[str, list] = {k: [] for k in
                             ("id", "user", "timestamp", "tags")}
    if element == "node":
        cols["lon"] = []
        cols["lat"] = []
        for nd in root.findall("node"):
            cols["id"].append(nd.get("id", ""))
            cols["lon"].append(float(nd.get("lon", "nan")))
            cols["lat"].append(float(nd.get("lat", "nan")))
            cols["user"].append(nd.get("user", ""))
            cols["timestamp"].append(nd.get("timestamp", ""))
            cols["tags"].append(tags_of(nd))
    else:
        nodes = {nd.get("id"): (nd.get("lon"), nd.get("lat"))
                 for nd in root.findall("node")}
        cols["geometry"] = []
        for way in root.findall("way"):
            refs = [nd.get("ref") for nd in way.findall("nd")]
            pts = [nodes.get(r) for r in refs]
            if len(pts) < 2 or any(p is None for p in pts):
                continue  # unresolvable way: node cache miss
            cols["id"].append(way.get("id", ""))
            cols["user"].append(way.get("user", ""))
            cols["timestamp"].append(way.get("timestamp", ""))
            cols["tags"].append(tags_of(way))
            cols["geometry"].append(
                "LINESTRING (" + ", ".join(f"{x} {y}" for x, y in pts) + ")")
    out: Dict[str, np.ndarray] = {}
    for k, v in cols.items():
        out[k] = np.asarray(v, dtype=np.float64 if k in ("lon", "lat")
                            else object)
    return out


def read_jdbc(conn_or_path, sql: str) -> Dict[str, np.ndarray]:
    """SQL query → columns (≙ geomesa-convert-jdbc, which executes a
    statement per input and feeds rows through the converter; the bundled
    driver here is the stdlib sqlite3 — pass a Connection for anything
    DB-API compatible)."""
    import sqlite3

    close = False
    if isinstance(conn_or_path, (str, os.PathLike)):
        path = str(conn_or_path)
        if path.startswith("jdbc:sqlite:"):
            path = path[len("jdbc:sqlite:"):]
        conn = sqlite3.connect(path)
        close = True
    else:
        conn = conn_or_path
    try:
        cur = conn.cursor()  # DB-API form (Connection.execute is sqlite-only)
        try:
            cur.execute(sql)
            if cur.description is None:
                raise ValueError(
                    f"Statement returned no result set (ingest needs a "
                    f"SELECT): {sql[:80]!r}")
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            cur.close()
    finally:
        if close:
            conn.close()
    out: Dict[str, np.ndarray] = {}
    for i, name in enumerate(names):
        out[name] = np.asarray([r[i] for r in rows], dtype=object)
    return out
