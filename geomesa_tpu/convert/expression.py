"""Transform expression DSL — columnar.

≙ reference converter `Expression` DSL (geomesa-convert/convert2/
transforms/Expression.scala + the function factories: DateFunctionFactory,
GeometryFunctionFactory, StringFunctionFactory, MathFunctionFactory,
IdFunctionFactory). Same surface — ``$1``/``$name`` field refs, nested
function calls, literals — but every expression evaluates VECTORIZED over
whole numpy columns instead of per-record: one ingest batch is one pass of
array ops, which is what keeps a 100M-row CSV load columnar end to end.

    point($lon, $lat)          geometry($wkt)
    dateTime($d, '%Y-%m-%d')   isoDateTime($d)     millisToDate($ms)
    toInt($1)  toLong  toFloat toDouble  toString  toBoolean
    concat($1, '-', $2)        trim  lowercase  uppercase
    substring($1, 0, 4)        regexReplace($1, 'a+', 'b')
    add  subtract  multiply  divide
    md5($1)   uuid()   literal('x')
"""

from __future__ import annotations

import hashlib
import re
import uuid as _uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Sequence

import numpy as np


@dataclass
class PointPair:
    """Marker a geometry field returns for point(x, y) — the table builder
    turns it into the (x, y) fast path."""
    x: np.ndarray
    y: np.ndarray


# -- parsing -----------------------------------------------------------------


_TOKEN = re.compile(r"""
    \s*(?:
      (?P<lparen>\() | (?P<rparen>\)) | (?P<comma>,)
    | (?P<str>'(?:[^'\\]|\\.)*')
    | (?P<num>-?\d+\.\d+|-?\d+)
    | (?P<field>\$\{[^}]+\}|\$[A-Za-z_0-9.]+)
    | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
    )""", re.VERBOSE)


class Expr:
    def eval(self, fields: Dict[str, np.ndarray], n: int):
        raise NotImplementedError


@dataclass
class Lit(Expr):
    value: object

    def eval(self, fields, n):
        return np.full(n, self.value, dtype=object) \
            if isinstance(self.value, str) else np.full(n, self.value)


@dataclass
class FieldRef(Expr):
    name: str

    def eval(self, fields, n):
        if self.name not in fields:
            raise KeyError(f"No input field {self.name!r} "
                           f"(have {sorted(fields)})")
        return fields[self.name]


@dataclass
class Call(Expr):
    fn: str
    args: List[Expr]

    def eval(self, fields, n):
        if self.fn not in FUNCTIONS:
            raise ValueError(f"Unknown transform function {self.fn!r}")
        return FUNCTIONS[self.fn](*[a.eval(fields, n) for a in self.args], n=n)


def parse_expression(text: str) -> Expr:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"Bad expression at {text[pos:pos+20]!r}")
        tokens.append(m)
        pos = m.end()

    idx = 0

    def peek(kind):
        return idx < len(tokens) and tokens[idx].lastgroup == kind

    def take():
        nonlocal idx
        t = tokens[idx]
        idx += 1
        return t

    def parse_one() -> Expr:
        if peek("str"):
            raw = take().group("str")[1:-1]
            return Lit(raw.replace("\\'", "'").replace("\\\\", "\\"))
        if peek("num"):
            raw = take().group("num")
            return Lit(float(raw) if "." in raw else int(raw))
        if peek("field"):
            raw = take().group("field")[1:]
            name = raw[1:-1] if raw.startswith("{") else raw
            return FieldRef(name)
        if peek("name"):
            fn = take().group("name")
            args: List[Expr] = []
            if not peek("lparen"):
                raise ValueError(f"Expected '(' after {fn!r}")
            take()
            if not peek("rparen"):
                args.append(parse_one())
                while peek("comma"):
                    take()
                    args.append(parse_one())
            if not peek("rparen"):
                raise ValueError(f"Unclosed call {fn!r}")
            take()
            return Call(fn, args)
        raise ValueError(f"Unexpected token in expression: {text!r}")

    out = parse_one()
    if idx != len(tokens):
        raise ValueError(f"Trailing input in expression: {text!r}")
    return out


# -- function registry (vectorized) ------------------------------------------


def _as_f64(a):
    return np.asarray(a, dtype=np.float64)


def _str(a):
    arr = np.asarray(a)
    if arr.dtype.kind in "OU":
        return arr.astype(object)
    return np.asarray([str(v) for v in arr], dtype=object)


FUNCTIONS: Dict[str, Callable] = {}


def register(name):
    def inner(fn):
        FUNCTIONS[name] = fn
        return fn
    return inner


@register("point")
def _point(x, y, n=0):
    return PointPair(_as_f64(x), _as_f64(y))


@register("geometry")
def _geometry(wkt, n=0):
    return _str(wkt)  # table builder parses WKT columns


@register("dateTime")
def _datetime(col, fmt, n=0):
    from datetime import datetime, timezone
    f = fmt[0]
    out = np.empty(len(col), dtype=np.int64)
    for i, v in enumerate(col):
        dt = datetime.strptime(str(v).strip(), f)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        out[i] = int(dt.timestamp() * 1000)
    return out


@register("isoDateTime")
@register("isoDate")
def _isodate(col, n=0):
    vals = np.asarray([str(v).strip().rstrip("Z") for v in col], dtype="datetime64[ms]")
    if np.isnat(vals).any():
        bad = [str(v) for v, isnat in zip(col, np.isnat(vals)) if isnat][:3]
        raise ValueError(f"Unparseable ISO dates: {bad}")
    return vals.astype(np.int64)


@register("millisToDate")
def _millis(col, n=0):
    return np.asarray(col, dtype=np.int64)


@register("secsToDate")
def _secs(col, n=0):
    return np.asarray(col, dtype=np.int64) * 1000


def _as_i64(col) -> np.ndarray:
    """Integer parse without a float64 round-trip (which silently corrupts
    values above 2^53 — snowflake ids, ns timestamps)."""
    arr = np.asarray(col)
    if arr.dtype.kind in "iu":
        return arr.astype(np.int64)
    if arr.dtype.kind == "f":
        return arr.astype(np.int64)
    out = np.empty(len(arr), dtype=np.int64)
    for i, v in enumerate(arr):
        s = str(v).strip()
        out[i] = int(s) if ("." not in s and "e" not in s.lower()) else int(float(s))
    return out


@register("toInt")
@register("toInteger")
def _toint(col, n=0):
    return _as_i64(col).astype(np.int32)


@register("toLong")
def _tolong(col, n=0):
    return _as_i64(col)


@register("toFloat")
def _tofloat(col, n=0):
    return _as_f64(col).astype(np.float32)


@register("toDouble")
def _todouble(col, n=0):
    return _as_f64(col)


@register("toBoolean")
def _tobool(col, n=0):
    arr = np.asarray(col)
    if arr.dtype.kind == "b":
        return arr
    return np.asarray([str(v).strip().lower() in ("true", "1", "t", "yes")
                       for v in arr])


@register("toString")
def _tostring(col, n=0):
    return _str(col)


@register("concat")
def _concat(*cols, n=0):
    parts = [_str(c) for c in cols]
    out = parts[0].copy()
    for p in parts[1:]:
        out = np.asarray([a + b for a, b in zip(out, p)], dtype=object)
    return out


@register("trim")
def _trim(col, n=0):
    return np.asarray([s.strip() for s in _str(col)], dtype=object)


@register("lowercase")
def _lower(col, n=0):
    return np.asarray([s.lower() for s in _str(col)], dtype=object)


@register("uppercase")
def _upper(col, n=0):
    return np.asarray([s.upper() for s in _str(col)], dtype=object)


@register("substring")
def _substring(col, start, end, n=0):
    s0, e0 = int(start[0]), int(end[0])
    return np.asarray([s[s0:e0] for s in _str(col)], dtype=object)


@register("regexReplace")
def _regex_replace(col, pattern, repl, n=0):
    rx = re.compile(str(pattern[0]))
    rp = str(repl[0])
    return np.asarray([rx.sub(rp, s) for s in _str(col)], dtype=object)


@register("jsonPath")
def _json_path(path, col, n=0):
    """Extract a json-path value from JSON-document strings.

    ≙ the reference's json-path property access into serialized JSON
    attributes (KryoJsonSerialization.scala + JsonPathParser,
    geomesa-features/feature-kryo/.../json/). Supported path subset:
    ``$.a.b[0].c`` — dotted keys and integer array indexes. Missing paths
    and invalid documents yield None."""
    from geomesa_tpu.features.jsonpath import extract_path

    p = str(path[0])
    return np.asarray([extract_path(s, p) for s in _str(col)], dtype=object)


@register("add")
def _add(a, b, n=0):
    return _as_f64(a) + _as_f64(b)


@register("subtract")
def _sub(a, b, n=0):
    return _as_f64(a) - _as_f64(b)


@register("multiply")
def _mul(a, b, n=0):
    return _as_f64(a) * _as_f64(b)


@register("divide")
def _div(a, b, n=0):
    return _as_f64(a) / _as_f64(b)


@register("md5")
def _md5(col, n=0):
    return np.asarray([hashlib.md5(str(s).encode()).hexdigest()
                       for s in _str(col)], dtype=object)


@register("uuid")
def _uuid_fn(n=0):
    return np.asarray([str(_uuid.uuid4()) for _ in range(n)], dtype=object)


@register("literal")
def _literal(col, n=0):
    return col


@register("withDefault")
def _with_default(col, default, n=0):
    arr = np.asarray(col, dtype=object)
    miss = np.asarray([v is None or (isinstance(v, str) and v == "")
                       for v in arr])
    arr = arr.copy()
    arr[miss] = default[0] if len(default) else None
    return arr
