"""Ingest/converter layer: transform DSL, format frontends, type inference.

≙ reference `geomesa-convert` (SURVEY.md §2.10).
"""

from geomesa_tpu.convert.converter import ConverterConfig, SimpleFeatureConverter
from geomesa_tpu.convert.expression import FUNCTIONS, parse_expression
from geomesa_tpu.convert.inference import converter_config_from_inference, infer_schema

__all__ = ["ConverterConfig", "FUNCTIONS", "SimpleFeatureConverter",
           "converter_config_from_inference", "infer_schema",
           "parse_expression"]
