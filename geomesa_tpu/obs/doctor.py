"""Fleet doctor: rule-driven anomaly detectors over the telemetry planes.

PRs 5-10 made the fleet visible — stitched traces, federated metrics,
SLO burn rates, recompile profiling, replication telemetry, the workload
hot-set feed — but nothing INTERPRETED any of it. The doctor runs a
fixed rule set over the local registry (and, when a Federator is
configured, the fleet-merged state) on the same injectable clock as
``obs/slo.py``, turning raw counters into attributed incidents:

  slo_burn          multi-window burn-rate page/ticket decisions, reusing
                    the unmodified SloEngine policies (local + fleet; the
                    fleet evaluation suppresses pages computed from a
                    partial merge — see Federator.slo)
  replication_lag   decay-based ``replication.lag_ms`` gauge over its
                    threshold OR a sequence backlog (``lag_seqs``) — the
                    stalled/dead-follower signal
  recompile_churn   ``kernels.recompiles`` advancing faster than the
                    per-minute bar inside the window; the suspect kernel
                    is named from the recompile flight events, with the
                    perfwatch baseline compile counts as context
  shed_storm        ``admission.shed`` rate over the bar; the dominant
                    shed priority class is the suspect
  breaker_flapping  open/close transition EDGES on one breaker inside
                    the window (state thrash, not steady open)
  wal_fsync_stall   new ``wal.fsync_errors``/retries — durability faults
                    page immediately by default
  hot_skew          one plan/cell/tenant whose GUARANTEED (at_least)
                    share of the workload window exceeds the bar
  shard_imbalance   the shardwatch ledger's GUARANTEED max-over-mean
                    per-shard load ratio over the bar — names the hot
                    shard and carries its projected split keys
  collective_straggler
                    one rank repeatedly the slowest arriver in cluster
                    collective rounds (over-bar spread counts charged
                    by cluster/runtime.py straggler attribution)

Every firing opens (or dedups into) an incident via ``obs/incidents.py``
with a correlated timeline snapshot; detectors that stay clear close
their incident with a resolution record. Evaluation happens ONLY on
read/tick surfaces (``/alerts``, ``/incidents``, the CLI) — the query
hot path never pays for the doctor (the <5% obs-overhead guard holds
with it enabled at defaults).

Import discipline (obs/__init__ rule): config/metrics/trace/obs.* only.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu import trace as _trace
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.obs.history import SeriesStore
from geomesa_tpu.obs.incidents import IncidentStore

# rule -> (severity default, one-line description — the CLI/docs table)
RULES: Dict[str, Tuple[str, str]] = {
    "slo_burn": ("page", "multi-window SLO burn over page/ticket policy"),
    "replication_lag": ("page", "follower lag_ms/seq backlog over bar"),
    "recompile_churn": ("ticket", "kernels.recompiles rate over bar"),
    "shed_storm": ("page", "admission.shed rate over bar"),
    "breaker_flapping": ("ticket", "breaker open/close edges in window"),
    "wal_fsync_stall": ("page", "new WAL fsync errors/retries"),
    "hot_skew": ("ticket", "single plan/cell/tenant dominates window"),
    "reindex_churn": ("ticket", "build aborts/failed installs or "
                                "merge-fraction breaches over bar"),
    "shard_imbalance": ("ticket", "guaranteed per-shard load "
                                  "max-over-mean ratio over bar"),
    "collective_straggler": ("ticket", "one rank repeatedly slowest in "
                                       "collective rounds"),
    "shard_dark": ("page", "a shard cell with ZERO serving endpoints "
                           "in the router topology"),
    "slo_trend": ("page", "burn-rate slope projects a page within the "
                          "lead horizon before slo_burn fires"),
    "capacity_trend": ("ticket", "per-shard load growth slope projects "
                                 "imbalance within the lead horizon"),
}


class DoctorEngine:
    """The rule evaluator. All collaborators are injectable (registry,
    clock, SLO engine, federator, workload plane, incident store) so
    tests drive it deterministically; the process-global ``DOCTOR``
    late-binds every one of them to the process globals."""

    def __init__(self, registry=None, clock=time.monotonic,
                 slo_engine=None, store: Optional[IncidentStore] = None,
                 journal_path: Optional[str] = None,
                 federator=None, workload=None, shardwatch=None,
                 router=None, forensics=None):
        self._reg = registry if registry is not None else _metrics
        self._clock = clock
        self._slo = slo_engine          # None -> late-bind slo.ENGINE
        self._federator = federator     # None -> late-bind federation
        self._workload = workload       # None -> late-bind WORKLOAD
        self._shardwatch = shardwatch   # None -> late-bind WATCH
        self._router = router           # shard_dark: the routing view
        self.store = store if store is not None else IncidentStore(
            journal_path=journal_path, registry=self._reg,
            node=_trace.node_id())
        self._lock = threading.RLock()
        self._forensics = forensics     # None -> late-bind FORENSICS;
        #                                 False -> capture disabled
        # per-counter retained series for the windowed rate detectors and
        # the predictive trend rules (obs/history.py SeriesStore — each
        # engine owns ONE, so a fresh doctor never fires on preexisting
        # totals and tests stay isolated)
        self.history = SeriesStore()

    # -- late-bound collaborators ---------------------------------------------

    def _slo_engine(self):
        if self._slo is not None:
            return self._slo
        from geomesa_tpu.obs import slo as _slo
        return _slo.ENGINE

    def _fed(self):
        if self._federator is False:    # fleet checks explicitly disabled
            return None
        if self._federator is not None:
            return self._federator
        from geomesa_tpu.obs import federation as _fed
        return _fed.federator()

    def _wl(self):
        if self._workload is not None:
            return self._workload
        from geomesa_tpu.obs import workload as _wl
        return _wl.WORKLOAD

    def _sw(self):
        if self._shardwatch is not None:
            return self._shardwatch
        from geomesa_tpu.obs import shardwatch as _shardwatch
        return _shardwatch.WATCH

    def _fstore(self):
        if self._forensics is False:    # capture explicitly disabled
            return None
        if self._forensics is not None:
            return self._forensics
        from geomesa_tpu.obs import forensics as _forensics
        return _forensics.FORENSICS

    # -- windowed counter deltas ----------------------------------------------

    def _delta(self, key: str, value: float, now: float,
               window_s: float) -> Tuple[float, float]:
        """(per-minute rate, absolute delta) of a counter over the
        trailing window, backed by the engine's retained SeriesStore
        (obs/history.py) — the same store the predictive trend rules
        query, replacing the ad-hoc per-detector deques. The first
        sighting of a counter contributes no delta, so a fresh doctor
        never fires on preexisting totals."""
        self.history.observe(key, value, now, window_s=window_s)
        return self.history.window(key, now, window_s)

    # -- detectors (each returns a list of alert dicts) -----------------------

    def _check_slo(self, now: float) -> List[dict]:
        alerts = []
        engine = self._slo_engine()
        local = engine.evaluate() if engine else {}
        scopes = [("local", local)]
        fed = self._fed()
        if fed is not None:
            try:
                scopes.append(("fleet", fed.slo()))
            except Exception:
                self._reg.inc("doctor.detector_errors")
        for scope, res in scopes:
            for name, obj in sorted((res or {}).items()):
                if not isinstance(obj, dict):
                    continue
                status = obj.get("status")
                if status not in ("page", "ticket"):
                    continue
                detail = {"scope": scope,
                          "burn_rates": obj.get("burn_rates"),
                          "compliance": obj.get("compliance"),
                          "error_budget": obj.get("error_budget")}
                if obj.get("page_suppressed"):
                    detail["page_suppressed"] = True
                alerts.append({
                    "rule": "slo_burn", "severity": status,
                    "cause": f"{scope}-slo:{name}",
                    "detail": detail,
                    "suspect": {"objective": name, "scope": scope},
                    "match": {"slow_ms": config.SLO_LATENCY_MS.get()},
                })
        alerts.extend(self._check_slo_trend(now, local))
        return alerts

    def _check_slo_trend(self, now: float, results: dict) -> List[dict]:
        """slo_trend: the PREDICTIVE page. Every evaluation feeds each
        objective's 5m burn rate into the engine's retained series; a
        positive fitted slope whose projection crosses the page bar
        within DOCTOR_TREND_LEAD_S fires while the current burn is still
        under it — the trend page leads the slo_burn page by design
        (proven by the ramped-handicap drill in obs/trenddrill.py). An
        objective already at page status stays slo_burn's: prediction
        never shadows the fact."""
        trend_on = bool(config.DOCTOR_TREND.get())
        window = float(config.DOCTOR_WINDOW_S.get())
        lead = float(config.DOCTOR_TREND_LEAD_S.get())
        min_pts = max(2, int(config.DOCTOR_TREND_MIN_POINTS.get()))
        from geomesa_tpu.obs.slo import PAGE_BURN
        alerts: List[dict] = []
        for name, obj in sorted((results or {}).items()):
            if not isinstance(obj, dict):
                continue
            burn = (obj.get("burn_rates") or {}).get("5m")
            if burn is None:
                continue            # no traffic in the window: no signal
            key = f"slo.burn5m.{name}"
            # the series samples every tick (not just near the bar) so
            # the fit has a baseline by the time a ramp starts
            self.history.observe(key, float(burn), now, window_s=window)
            if not trend_on:
                continue
            current = float(burn)
            if current >= PAGE_BURN or obj.get("status") == "page":
                continue
            if self.history.points(key, now, window) < min_pts:
                continue
            slope = self.history.slope(key, now, window)
            if slope <= 0.0:
                continue
            projected = current + slope * lead
            if projected < PAGE_BURN:
                continue
            eta_s = (PAGE_BURN - current) / slope
            alerts.append({
                "rule": "slo_trend", "severity": "page",
                "cause": f"trend-slo:{name}",
                "detail": {"burn_5m": round(current, 3),
                           "slope_per_s": round(slope, 5),
                           "projected": round(projected, 3),
                           "page_bar": PAGE_BURN,
                           "lead_s": lead,
                           "eta_s": round(eta_s, 1)},
                "suspect": {"objective": name,
                            "page_projected_in_s": round(eta_s, 1)},
                "match": {"slow_ms": config.SLO_LATENCY_MS.get()},
            })
        return alerts

    def _check_replication(self, now: float, gauges: dict) -> List[dict]:
        try:
            lag_ms = float(gauges.get("replication.lag_ms") or 0.0)
            lag_seqs = int(gauges.get("replication.lag_seqs") or 0)
        except (TypeError, ValueError):
            return []
        bar_ms = float(config.DOCTOR_LAG_MS.get())
        bar_seqs = int(config.DOCTOR_LAG_SEQS.get())
        over_ms = bar_ms > 0 and lag_ms > bar_ms
        over_seqs = bar_seqs > 0 and lag_seqs >= bar_seqs
        if not (over_ms or over_seqs):
            return []
        why = "lag_ms" if over_ms else "lag_seqs"
        return [{
            "rule": "replication_lag", "severity": "page",
            "cause": f"replication:{why}",
            "detail": {"lag_ms": round(lag_ms, 1), "lag_seqs": lag_seqs,
                       "bar_ms": bar_ms, "bar_seqs": bar_seqs},
            "suspect": {"role": _trace.node_role(), "signal": why},
            "match": {"kind": "repl.apply"},
        }]

    def _check_recompiles(self, now: float, counters: dict) -> List[dict]:
        v = counters.get("kernels.recompiles", 0)
        window = float(config.DOCTOR_WINDOW_S.get())
        rate, delta = self._delta("kernels.recompiles", v, now, window)
        bar = float(config.DOCTOR_RECOMPILES_PER_MIN.get())
        if bar <= 0 or delta <= 0 or rate < bar:
            return []
        suspect: dict = {}
        try:
            from geomesa_tpu.obs.flight import RECORDER
            kernels: Dict[str, int] = {}
            for e in RECORDER.recent(limit=64, kind="kernel.recompile"):
                k = str(e.get("kernel") or e.get("name") or "?")
                kernels[k] = kernels.get(k, 0) + 1
            if kernels:
                top = max(kernels.items(), key=lambda kv: kv[1])
                suspect = {"kernel": top[0], "recent_recompiles": top[1]}
        except Exception:
            pass
        baseline = None
        try:
            import os
            from geomesa_tpu.obs import perfwatch as _pw
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "perf", "baselines.json")
            kb = _pw.load_baselines(path).get("kernels") or {}
            baseline = sum(int((m or {}).get("compiles", 0))
                           for m in kb.values()) or None
        except Exception:
            pass
        return [{
            "rule": "recompile_churn", "severity": "ticket",
            "cause": "kernels:recompiles",
            "detail": {"rate_per_min": round(rate, 2), "delta": delta,
                       "bar_per_min": bar, "total": int(v),
                       "baseline_compiles": baseline},
            "suspect": suspect,
            "match": {"kind": "kernel.recompile"},
        }]

    def _check_shed(self, now: float, counters: dict) -> List[dict]:
        window = float(config.DOCTOR_WINDOW_S.get())
        rate, delta = self._delta("admission.shed",
                                  counters.get("admission.shed", 0),
                                  now, window)
        # per-class deltas ride along so the dominant class is nameable
        classes = {}
        for k, v in counters.items():
            if k.startswith("admission.shed."):
                _r, d = self._delta(k, v, now, window)
                if d > 0:
                    classes[k[len("admission.shed."):]] = d
        bar = float(config.DOCTOR_SHED_PER_MIN.get())
        if bar <= 0 or delta <= 0 or rate < bar:
            return []
        suspect = {}
        if classes:
            top = max(classes.items(), key=lambda kv: kv[1])
            suspect = {"priority": top[0], "shed_in_window": int(top[1])}
        return [{
            "rule": "shed_storm", "severity": "page",
            "cause": "admission:shed",
            "detail": {"rate_per_min": round(rate, 2), "delta": delta,
                       "bar_per_min": bar,
                       "by_class": {k: int(v) for k, v in classes.items()}},
            "suspect": suspect,
            "match": {"errors": True},
        }]

    def _top_type(self, counters: dict, families: Tuple[str, ...],
                  now: float, window: float) -> dict:
        """Dominant per-type delta across the given counter families —
        the suspect names the TYPE whose builds are churning."""
        types: Dict[str, float] = {}
        for fam in families:
            prefix = fam + "."
            for k, v in counters.items():
                if k.startswith(prefix):
                    _r, d = self._delta(k, v, now, window)
                    if d > 0:
                        t = k[len(prefix):]
                        types[t] = types.get(t, 0) + d
        if not types:
            return {}
        top = max(types.items(), key=lambda kv: kv[1])
        return {"type": top[0], "events_in_window": int(top[1])}

    def _check_reindex(self, now: float, counters: dict) -> List[dict]:
        """reindex_churn: the background build machinery is spinning
        without converging — repeated build aborts / failed installs
        (reindex:churn), or the incremental merge path falling back to
        full rebuilds every flush (build:merge_fraction_breach)."""
        window = float(config.DOCTOR_WINDOW_S.get())
        alerts: List[dict] = []
        # per-type deltas sample every tick (not just on firing) so the
        # suspect's baseline exists by the time a bar is crossed
        churn_suspect = self._top_type(
            counters, ("reindex.aborts", "reindex.failures"), now, window)
        breach_suspect = self._top_type(
            counters, ("ingest.merge_fraction_breaches",), now, window)
        churn = counters.get("reindex.aborts", 0) \
            + counters.get("reindex.failures", 0)
        rate, delta = self._delta("reindex.churn", churn, now, window)
        bar = float(config.DOCTOR_REINDEX_PER_MIN.get())
        if bar > 0 and delta > 0 and rate >= bar:
            alerts.append({
                "rule": "reindex_churn", "severity": "ticket",
                "cause": "reindex:churn",
                "detail": {"rate_per_min": round(rate, 2),
                           "delta": int(delta), "bar_per_min": bar,
                           "aborts": int(counters.get("reindex.aborts", 0)),
                           "failures": int(
                               counters.get("reindex.failures", 0))},
                "suspect": churn_suspect,
                "match": {"kind": "reindex"},
            })
        breaches = counters.get("ingest.merge_fraction_breaches", 0)
        rate, delta = self._delta("ingest.merge_fraction_breaches",
                                  breaches, now, window)
        bar = float(config.DOCTOR_MERGE_BREACHES_PER_MIN.get())
        if bar > 0 and delta > 0 and rate >= bar:
            alerts.append({
                "rule": "reindex_churn", "severity": "ticket",
                "cause": "build:merge_fraction_breach",
                "detail": {"rate_per_min": round(rate, 2),
                           "delta": int(delta), "bar_per_min": bar,
                           "max_fraction":
                               float(config.MERGE_MAX_FRACTION.get())},
                "suspect": breach_suspect,
                "match": {"kind": "reindex"},
            })
        return alerts

    def _check_breakers(self, now: float, counters: dict) -> List[dict]:
        window = float(config.DOCTOR_WINDOW_S.get())
        bar = int(config.DOCTOR_BREAKER_FLAPS.get())
        edges: Dict[str, float] = {}
        for k, v in counters.items():
            if not k.startswith("breaker."):
                continue
            if k.endswith(".opened") or k.endswith(".closed"):
                name = k[len("breaker."):k.rfind(".")]
                _r, d = self._delta(k, v, now, window)
                edges[name] = edges.get(name, 0.0) + max(0.0, d)
        alerts = []
        for name, flaps in sorted(edges.items()):
            if bar <= 0 or flaps < bar:
                continue
            alerts.append({
                "rule": "breaker_flapping", "severity": "ticket",
                "cause": f"breaker:{name}",
                "detail": {"edges_in_window": int(flaps), "bar": bar,
                           "window_s": window},
                "suspect": {"breaker": name},
                "match": {"errors": True},
            })
        return alerts

    def _check_wal(self, now: float, counters: dict) -> List[dict]:
        window = float(config.DOCTOR_WINDOW_S.get())
        bar = int(config.DOCTOR_FSYNC_ERRORS.get())
        _r, errs = self._delta("wal.fsync_errors",
                               counters.get("wal.fsync_errors", 0),
                               now, window)
        _r, retries = self._delta("wal.fsync_retries",
                                  counters.get("wal.fsync_retries", 0),
                                  now, window)
        faults = errs + retries
        if bar <= 0 or faults < bar:
            return []
        return [{
            "rule": "wal_fsync_stall", "severity": "page",
            "cause": "wal:fsync",
            "detail": {"errors_in_window": int(errs),
                       "retries_in_window": int(retries), "bar": bar},
            "suspect": {"path": "wal"},
            "match": {"errors": True},
        }]

    def _check_skew(self, now: float) -> List[dict]:
        try:
            wl = self._wl()
            hs = wl.hot_set()
            tenants = wl.top_tenants()
        except Exception:
            return []
        total = int(hs.get("total") or 0)
        if total < int(config.DOCTOR_SKEW_MIN.get()):
            return []
        bar = float(config.DOCTOR_SKEW_FRACTION.get())
        if bar <= 0:
            return []
        alerts = []
        dims = [("plan", hs.get("plans") or []),
                ("cell", hs.get("cells") or []),
                ("tenant", tenants or [])]
        for dim, entries in dims:
            if not entries:
                continue
            e = entries[0]
            key = e.get("key", e.get("tenant"))
            at_least = e.get("at_least")
            if at_least is None:
                at_least = max(0, int(e.get("count", 0))
                               - int(e.get("error", 0)))
            share = float(at_least) / float(total)
            if share < bar:
                continue
            suspect = {dim: key, "share_at_least": round(share, 3)}
            if "bbox" in e:
                suspect["bbox"] = e["bbox"]
            alerts.append({
                "rule": "hot_skew", "severity": "ticket",
                "cause": f"skew:{dim}:{key}",
                "detail": {"dimension": dim, "at_least": int(at_least),
                           "window_total": total, "bar_fraction": bar},
                "suspect": suspect,
                "match": {},
            })
        return alerts

    def _check_shard_imbalance(self, now: float) -> List[dict]:
        """shard_imbalance: the shardwatch ledger's GUARANTEED
        (at_least-based) max-over-mean per-shard load ratio over the bar
        with enough guaranteed load to mean anything — the suspect names
        the hot shard and carries its projected split keys (the exact
        boundaries the split/migrate plane will consume)."""
        try:
            rep = self._sw().balance()
        except Exception:
            return []
        if not rep.get("active"):
            return []
        alerts: List[dict] = []
        for tname, tr in sorted((rep.get("types") or {}).items()):
            sc = tr.get("score") or {}
            if not sc.get("over_bar"):
                continue
            hot = sc.get("hot_shard")
            boundaries = (tr.get("splits") or {}).get("boundaries") or []
            hot_row = (tr.get("shards") or {}).get(hot) or {}
            alerts.append({
                "rule": "shard_imbalance", "severity": "ticket",
                "cause": f"shard:{tname}:{hot}",
                "detail": {
                    "type": tname,
                    "max_over_mean": sc.get("max_over_mean"),
                    "max_over_mean_est": sc.get("max_over_mean_est"),
                    "top_cell_fraction": sc.get("top_cell_fraction"),
                    "imbalance": sc.get("imbalance"),
                    "bar": sc.get("bar"),
                    "guaranteed_total": sc.get("guaranteed_total"),
                    "split_keys": [b["key"] for b in boundaries]},
                "suspect": {"type": tname, "shard": hot,
                            "load_share": hot_row.get("load_share"),
                            "key_range": hot_row.get("key_range")},
                "match": {},
            })
        return alerts

    def _check_capacity_trend(self, now: float) -> List[dict]:
        """capacity_trend: the leading signal the split/merge loop will
        consume. Every evaluation feeds each type's GUARANTEED
        max-over-mean shard-load ratio (the shardwatch ledger's honest
        lower bound) into the retained series; a positive slope whose
        projected bar-crossing lands within DOCTOR_CAPACITY_LEAD_S opens
        a predictive ticket naming the hot shard and the projected
        time-to-imbalance. A type already over the bar stays
        shard_imbalance's."""
        trend_on = bool(config.DOCTOR_TREND.get())
        try:
            rep = self._sw().balance()
        except Exception:
            return []
        if not rep.get("active"):
            return []
        window = float(config.DOCTOR_WINDOW_S.get())
        lead = float(config.DOCTOR_CAPACITY_LEAD_S.get())
        min_pts = max(2, int(config.DOCTOR_TREND_MIN_POINTS.get()))
        alerts: List[dict] = []
        for tname, tr in sorted((rep.get("types") or {}).items()):
            sc = tr.get("score") or {}
            mom = sc.get("max_over_mean")
            bar = sc.get("bar")
            if mom is None or bar is None:
                continue
            key = f"shard.mom.{tname}"
            self.history.observe(key, float(mom), now, window_s=window)
            if not trend_on or sc.get("over_bar"):
                continue
            if self.history.points(key, now, window) < min_pts:
                continue
            slope = self.history.slope(key, now, window)
            if slope <= 0.0:
                continue
            eta_s = (float(bar) - float(mom)) / slope
            if eta_s > lead:
                continue
            hot = sc.get("hot_shard")
            hot_row = (tr.get("shards") or {}).get(hot) or {}
            alerts.append({
                "rule": "capacity_trend", "severity": "ticket",
                "cause": f"trend-shard:{tname}",
                "detail": {"type": tname,
                           "max_over_mean": round(float(mom), 3),
                           "slope_per_s": round(slope, 6),
                           "bar": float(bar),
                           "lead_s": lead,
                           "eta_s": round(eta_s, 1)},
                "suspect": {"type": tname, "shard": hot,
                            "load_share": hot_row.get("load_share"),
                            "imbalance_projected_in_s": round(eta_s, 1)},
                "match": {},
            })
        return alerts

    def attach_router(self, router) -> None:
        """Bind the shard-aware router whose topology the shard_dark
        detector should watch (RouterApi does this on startup)."""
        with self._lock:
            self._router = router

    def _check_shard_dark(self, now: float) -> List[dict]:
        """shard_dark: a shard cell with ZERO serving endpoints in the
        router's topology — every read scatter answers partial and every
        owned write has nowhere to land. One deduped incident per shard,
        naming the dark key range and its last-known cell members (the
        page carries exactly what the operator must respawn)."""
        router = self._router
        if router is None or getattr(router, "topology", None) is None:
            return []
        try:
            health = router.shard_health()
        except Exception:
            return []
        alerts: List[dict] = []
        for sid, row in sorted(health.items()):
            if int(row.get("serving", 0)) > 0:
                continue
            alerts.append({
                "rule": "shard_dark", "severity": "page",
                "cause": f"shard:{sid}",
                "detail": {
                    "key_range": row.get("key_range"),
                    "members": row.get("members"),
                    "healthy": int(row.get("healthy", 0))},
                "suspect": {"shard": sid,
                            "key_range": row.get("key_range"),
                            "members": sorted(
                                (row.get("members") or {}).keys())},
                "match": {},
            })
        return alerts

    def _check_straggler(self, now: float, counters: dict) -> List[dict]:
        """collective_straggler: cluster/runtime.py charges one count
        against the slowest rank of every collective round whose spread
        crosses GEOMESA_TPU_DOCTOR_STRAGGLER_MS; a rank accumulating
        DOCTOR_STRAGGLER_ROUNDS of them inside the window is named."""
        window = float(config.DOCTOR_WINDOW_S.get())
        bar = int(config.DOCTOR_STRAGGLER_ROUNDS.get())
        prefix = "cluster.collective.straggler.rank"
        per_rank: Dict[str, float] = {}
        for k, v in counters.items():
            if k.startswith(prefix):
                _r, d = self._delta(k, v, now, window)
                if d > 0:
                    per_rank[k[len(prefix):]] = d
        if bar <= 0 or not per_rank:
            return []
        alerts: List[dict] = []
        for rank, d in sorted(per_rank.items()):
            if d < bar:
                continue
            try:
                rank_id = int(rank)
            except ValueError:
                rank_id = rank
            alerts.append({
                "rule": "collective_straggler", "severity": "ticket",
                "cause": f"collective:rank{rank}",
                "detail": {
                    "over_bar_rounds_in_window": int(d), "bar": bar,
                    "window_s": window,
                    "spread_bar_ms":
                        float(config.DOCTOR_STRAGGLER_MS.get()),
                    "rounds_total": int(
                        counters.get("cluster.collective.rounds", 0))},
                "suspect": {"rank": rank_id},
                "match": {"kind": "collective"},
            })
        return alerts

    # -- the correlated timeline ----------------------------------------------

    def _timeline(self, alert: dict, counters: dict) -> dict:
        cap = max(0, int(config.DOCTOR_TIMELINE_EVENTS.get()))
        match = dict(alert.get("match") or {})
        events: List[dict] = []
        gids: List[str] = []
        try:
            from geomesa_tpu.obs.flight import RECORDER
            events = RECORDER.recent(limit=cap, **match) if cap else []
        except Exception:
            pass
        try:
            from geomesa_tpu.obs.sampling import SAMPLER
            for t in SAMPLER.recent(cap):
                g = t.get("global_id")
                if g and g not in gids:
                    gids.append(str(g))
        except Exception:
            pass
        demotions = {k: int(v) for k, v in counters.items()
                     if k.startswith("router.demotions")}
        drills = {k: int(v) for k, v in counters.items()
                  if k.startswith("drill.")}
        return {"events": events, "trace_gids": gids,
                "router_demotions": demotions, "drills": drills}

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, tick: bool = True) -> dict:
        """Run every detector, reconcile with the incident store, and
        return ``{alerts, incidents}``. Read/tick surfaces only — never
        called from the query hot path."""
        if not config.DOCTOR_ENABLED.get():
            return {"enabled": False, "alerts": [],
                    "incidents": self.store.active()}
        with self._lock:
            now = self._clock()
            snap = self._reg.snapshot()
            counters = snap.get("counters") or {}
            gauges = snap.get("gauges") or {}
            alerts: List[dict] = []
            for check in (lambda: self._check_slo(now),
                          lambda: self._check_replication(now, gauges),
                          lambda: self._check_recompiles(now, counters),
                          lambda: self._check_shed(now, counters),
                          lambda: self._check_breakers(now, counters),
                          lambda: self._check_wal(now, counters),
                          lambda: self._check_reindex(now, counters),
                          lambda: self._check_skew(now),
                          lambda: self._check_shard_imbalance(now),
                          lambda: self._check_capacity_trend(now),
                          lambda: self._check_shard_dark(now),
                          lambda: self._check_straggler(now, counters)):
                try:
                    alerts.extend(check())
                except Exception:
                    # one broken detector must not take down the surface
                    self._reg.inc("doctor.detector_errors")
            self._reg.inc("doctor.evaluations")
            firing = set()
            for a in alerts:
                self._reg.inc(f"doctor.alerts.{a['rule']}")
                key = (a["rule"], str(a.get("cause", "")))
                firing.add(key)
                timeline = None
                if key not in {(i["rule"], i["cause"])
                               for i in self.store.active()}:
                    timeline = self._timeline(a, counters)
                inc = self.store.open_or_update(a, timeline, now)
                if timeline is not None:
                    # newly opened: freeze the forensic bundle (history
                    # slices, matching events, replication/workload
                    # state) before the system can recover past it
                    fstore = None
                    try:
                        fstore = self._fstore()
                    except Exception:
                        pass
                    if fstore is not None:
                        fstore.capture(inc)
            resolved = []
            if tick:
                resolved = self.store.sweep(
                    firing, now, int(config.DOCTOR_CLEAR_TICKS.get()))
            return {"alerts": alerts,
                    "incidents": self.store.active(),
                    "resolved": [i["id"] for i in resolved]}

    def alerts(self) -> dict:
        """The ``GET /alerts`` payload: current firings + active
        incident ids (evaluates, so reading IS detecting)."""
        res = self.evaluate()
        return {"alerts": res.get("alerts", []),
                "active_incidents": [i["id"] for i in
                                     res.get("incidents", [])],
                "enabled": bool(config.DOCTOR_ENABLED.get())}

    def incidents(self, active_only: bool = False) -> dict:
        """The ``GET /incidents`` payload (evaluates first so the answer
        reflects the present, then includes the resolved tail)."""
        self.evaluate()
        return {"incidents": self.store.all(active_only=active_only),
                "stats": self.store.stats()}

    def reset(self) -> None:
        """Forget rate-detector history and all incidents (tests)."""
        with self._lock:
            self.history.clear()
            self.store.clear()


def verdict(inc: dict) -> str:
    """One human line per incident: what fired, since when, suspected
    cause, linked trace — the CLI ``doctor`` output contract."""
    age_s = None
    if inc.get("opened_ms"):
        age_s = max(0.0, time.time() - inc["opened_ms"] / 1000.0)
    since = f"{age_s:.0f}s ago" if age_s is not None else "unknown"
    suspect = inc.get("suspect") or {}
    cause = ", ".join(f"{k}={v}" for k, v in sorted(suspect.items())) \
        or inc.get("cause", "?")
    tl = inc.get("timeline") or {}
    gids = tl.get("trace_gids") or []
    link = f" trace={gids[0]}" if gids else ""
    status = inc.get("status", "open")
    return (f"[{inc.get('severity', '?').upper()}] {inc.get('rule')}"
            f" ({status}) since {since} x{inc.get('count', 1)}"
            f" — suspected: {cause}{link}")


# -- process-global doctor (the /alerts /incidents surfaces' backing) ---------

DOCTOR = DoctorEngine()
