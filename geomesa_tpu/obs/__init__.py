"""Request-centric observability: flight recorder, tail-sampled traces
with exemplars, per-kernel device cost attribution, SLO burn rates —
plus the performance observatory (ISSUE 6): device-level kernel
profiling with recompile detection and build-phase progress
(obs/profiling.py) and the noise-aware bench regression gate
(obs/perfwatch.py).

Layered ON TOP of trace.py/metrics.py (which stay import-light and
hook-based): ``install()`` wires

  - a trace close hook: every closed root trace is offered to the tail
    sampler (obs/sampling.py) and — unless the scheduler already emitted
    a richer event for it — derived into a flight-recorder wide event
    (obs/flight.py);
  - the registry's exemplar filter: only tail-retained trace ids become
    /metrics bucket exemplars;
  - the trace device hook: per-kernel attribution of dispatch/wait time
    (obs/attrib.py);
  - the default SLOs (obs/slo.py) when none are registered.

``install()`` is idempotent and called from TpuDataStore/QueryScheduler
construction, so any store-bearing process is observable by default;
GEOMESA_TPU_OBS=0 turns the per-request work off at runtime without
uninstalling.

Import discipline: obs submodules import only config/metrics/trace —
never the planner/scheduler/datastore layers — so hot paths (index/scan,
serve/scheduler) can import them without cycles. The close hook computes
the per-stage breakdown ONCE and shares it between the sampling decision
and the wide event (the hot-path budget is guarded by
tests/test_perf_budget.py's obs overhead bar).
"""

from __future__ import annotations

from geomesa_tpu import config as _config
from geomesa_tpu.obs import flight as _flight
from geomesa_tpu.obs import sampling as _sampling

_INSTALLED = False

# cached GEOMESA_TPU_OBS verdict for the close hook (an env read per trace
# close is measurable on µs-scale queries); re-read every _ENABLED_REFRESH
# closes so flipping the knob at runtime still takes effect promptly
_enabled_cache = [True, 0]
_ENABLED_REFRESH = 64


def _obs_enabled() -> bool:
    c = _enabled_cache
    c[1] -= 1
    if c[1] <= 0:
        c[0] = bool(_config.OBS_ENABLED.get())
        c[1] = _ENABLED_REFRESH
    return c[0]


def install() -> None:
    """Wire the observability hooks (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    _INSTALLED = True
    from geomesa_tpu import trace as _trace
    from geomesa_tpu.metrics import REGISTRY as _metrics
    from geomesa_tpu.obs import attrib as _attrib
    from geomesa_tpu.obs import slo as _slo
    _trace.add_close_hook(_on_trace_close)
    _metrics.set_exemplar_filter(_retained_filter)

    # the sampler's deferred retention decisions, the device hook's
    # pending fetch attributions, and the workload plane's pending event
    # queue all settle right before any snapshot-ish registry read, so
    # surfaces stay accurate without the per-query hot path paying for any
    def _pre_drain():
        from geomesa_tpu.obs import history as _history
        from geomesa_tpu.obs import workload as _workload
        _sampling.SAMPLER.drain()
        _attrib.flush()
        _workload.WORKLOAD.drain()
        # history sampler LAST, so a tick retains the just-drained state;
        # self-throttled to the finest tier interval and reentrancy-guarded
        # (taking a sample reads the registry, which re-enters this hook)
        _history.HISTORY.maybe_sample()

    _metrics.set_pre_drain_hook(_pre_drain)
    _metrics.set_gauge("obs.flight_depth", lambda: len(_flight.RECORDER))
    _attrib.install()
    if not _slo.ENGINE.objectives():
        for obj in _slo.default_objectives():
            _slo.ENGINE.add(obj)


def _retained_filter(trace_id: int) -> bool:
    return _sampling.SAMPLER.is_retained(trace_id)


def _on_trace_close(t) -> None:
    """Root-trace close: enqueue for the tail sampler's DEFERRED retention
    decision and for lazy wide-event derivation — the hot path pays two
    appends; decisions and event dicts materialize when somebody reads
    /events, /traces?retained=1, or a metrics snapshot. Scheduled counts
    skip the event (their requests emit richer ones with cache/batch/
    admission fields — see serve/scheduler.py)."""
    if not _obs_enabled():
        return
    _sampling.SAMPLER.enqueue(t)
    attrs = t.root.attrs
    if attrs is not None and attrs.get("scheduled"):
        return
    _flight.RECORDER.record_trace(t)


def installed() -> bool:
    return _INSTALLED
