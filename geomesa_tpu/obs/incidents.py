"""Incident objects + the size-rotated incident journal.

An incident is the doctor's unit of attribution: one detector firing,
deduplicated while it stays active, carrying a correlated TIMELINE
snapshot (matching flight events, retained trace gids, router demotions,
drill counters, the suspect kernel/plan/tenant) captured at open time —
the evidence an operator needs without re-querying five surfaces after
the fact. When the detector clears for enough consecutive evaluations,
the incident closes with a resolution record.

Every open/close appends a JSONL record to the incident journal,
size-rotated through the SAME durability helper the flight recorder's
wide-event sink uses (``durability/rotation.py``) — a failing journal
never fails an evaluation (dropwizard rule).

Import discipline (obs/__init__ rule): config/metrics only.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics

_CLOSED_KEEP = 64  # resolved incidents kept queryable in memory


def _public(inc: dict) -> dict:
    """An incident dict minus the store's private bookkeeping keys."""
    return {k: v for k, v in inc.items() if not k.startswith("_")}


class IncidentStore:
    """Active-incident dedup + resolution + the rotated JSONL journal."""

    def __init__(self, journal_path: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 registry=None, node: Optional[str] = None):
        self._lock = threading.RLock()
        self._active: Dict[Tuple[str, str], dict] = {}
        self._closed: deque = deque(maxlen=_CLOSED_KEEP)
        self._seq = 0
        self._journal_path = journal_path
        self._max_bytes = max_bytes
        self._fh = None
        self._fh_path: Optional[str] = None
        self._fh_bytes = 0
        self._reg = registry if registry is not None else _metrics
        self._node = node
        self._reg.set_gauge("incident.active", lambda: len(self._active))

    # -- journal (same shape as FlightRecorder's rotated sink) ----------------

    def _path(self) -> Optional[str]:
        if self._journal_path is not None:
            return self._journal_path or None
        return config.DOCTOR_JOURNAL.get() or None

    def _journal_locked(self, record: dict) -> None:
        path = self._path()
        if path is None:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            return
        try:
            if self._fh is None or self._fh_path != path:
                if self._fh is not None:
                    self._fh.close()
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(path, "ab")
                self._fh_path = path
                self._fh_bytes = self._fh.tell()
            line = (json.dumps(record, default=str) + "\n").encode()
            self._fh.write(line)
            self._fh.flush()
            self._fh_bytes += len(line)
            cap = int(self._max_bytes if self._max_bytes is not None
                      else config.DOCTOR_JOURNAL_MAX_BYTES.get())
            if cap > 0 and self._fh_bytes >= cap:
                from geomesa_tpu.durability.rotation import rotate
                self._fh.close()
                self._fh = None
                def _dropped(p):
                    self._reg.inc("incident.journal_dropped")
                    self._reg.inc("journal.gc")
                rotate(path,
                       keep=max(1, int(config.JOURNAL_KEEP.get())),
                       on_drop=_dropped)
        except OSError:
            # a failing journal must never fail an evaluation
            self._reg.inc("incident.journal_errors")
            self._fh = None

    # -- lifecycle ------------------------------------------------------------

    def open_or_update(self, alert: dict, timeline: Optional[dict],
                       now: float) -> dict:
        """Open a new incident for this (rule, cause), or bump the active
        one (dedup) — either way the clear streak resets."""
        key = (str(alert["rule"]), str(alert.get("cause", "")))
        with self._lock:
            inc = self._active.get(key)
            if inc is not None:
                inc["count"] += 1
                inc["last_seen_ts"] = now
                inc["severity"] = alert.get("severity", inc["severity"])
                if alert.get("detail"):
                    inc["detail"] = alert["detail"]
                inc["_clear"] = 0
                self._reg.inc("incident.deduped")
                return inc
            self._seq += 1
            inc = {
                "id": f"inc-{self._seq}",
                "rule": key[0],
                "cause": key[1],
                "severity": alert.get("severity", "ticket"),
                "node": self._node,
                "status": "open",
                "opened_ts": now,
                "last_seen_ts": now,
                "opened_ms": int(time.time() * 1000),
                "count": 1,
                "detail": alert.get("detail") or {},
                "suspect": alert.get("suspect") or {},
                "timeline": timeline or {},
                "_clear": 0,
            }
            self._active[key] = inc
            self._reg.inc("incident.opened")
            self._journal_locked({"kind": "incident.open", **_public(inc)})
            return inc

    def sweep(self, firing: set, now: float, clear_ticks: int) -> List[dict]:
        """Advance the clear streak of every active incident NOT in
        ``firing``; close the ones that stayed clear long enough.
        Returns the incidents resolved this sweep."""
        resolved = []
        with self._lock:
            for key in list(self._active):
                inc = self._active[key]
                if key in firing:
                    continue
                inc["_clear"] += 1
                if inc["_clear"] < max(1, int(clear_ticks)):
                    continue
                del self._active[key]
                inc["status"] = "resolved"
                inc["resolved_ts"] = now
                inc["resolved_ms"] = int(time.time() * 1000)
                inc["resolution"] = {
                    "cleared_after_s": round(now - inc["opened_ts"], 3),
                    "clear_ticks": inc.pop("_clear"),
                    "firings": inc["count"],
                }
                self._closed.append(inc)
                resolved.append(inc)
                self._reg.inc("incident.resolved")
                self._journal_locked(
                    {"kind": "incident.close", **_public(inc)})
        return resolved

    # -- read surfaces --------------------------------------------------------

    def active(self) -> List[dict]:
        with self._lock:
            return [_public(i) for i in
                    sorted(self._active.values(),
                           key=lambda i: i["opened_ts"])]

    def all(self, active_only: bool = False) -> List[dict]:
        """Active incidents plus the recently-resolved tail, oldest
        first (the /incidents payload)."""
        with self._lock:
            out = [] if active_only else [_public(i) for i in self._closed]
            out.extend(_public(i) for i in
                       sorted(self._active.values(),
                              key=lambda i: i["opened_ts"]))
            return out

    def stats(self) -> dict:
        with self._lock:
            return {"active": len(self._active),
                    "resolved_kept": len(self._closed),
                    "opened_total": self._seq,
                    "journal": self._path()}

    def clear(self) -> None:
        """Drop all state (tests / soak halves)."""
        with self._lock:
            self._active.clear()
            self._closed.clear()
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def replay_journal(path: str) -> List[dict]:
    """Read the incident journal back, oldest rotated generation first
    (``path.N`` .. ``path.1``, then the live file) — the replay surface
    for post-mortems and the rotation/retention tests."""
    out: List[dict] = []
    keep = max(1, int(config.JOURNAL_KEEP.get()))
    generations = [f"{path}.{k}" for k in range(keep, 0, -1)]
    for p in generations + [path]:
        try:
            with open(p, "rb") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line.decode()))
                    except (ValueError, UnicodeDecodeError):
                        continue  # torn tail from rotation mid-write
        except OSError:
            continue
    return out
