"""Forensic bundles: the evidence capsule the doctor captures at open.

An incident's timeline snapshot (obs/incidents.py) answers *what fired*;
a forensic bundle answers *what the minutes around it looked like*: the
telemetry-history slices covering the firing window, the flight events
matching the incident sliced to the same ``since_ms``, the retained
trace gids, the replication/cell registry state, the workload hot_set
and the shardwatch balance verdict — everything an operator replays
after the page, frozen at capture time so a recovered system can't
retroactively exonerate itself.

Bundles live in a bounded in-memory ring (fetchable at
``GET /incidents/{id}/bundle`` and via ``geomesa-tpu forensics``), and —
when ``GEOMESA_TPU_FORENSICS_DIR`` is set — are installed durably via
the shared tmp+rename discipline (``durability/rotation.atomic_install``,
so a crash mid-capture leaves no torn bundle) with keep-N GC
(``rotation.keep_newest``; ``forensics.gc`` counts the drops).

A failing capture never fails a doctor evaluation (dropwizard rule);
``forensics.errors`` counts the swallows.

Import discipline (obs/__init__ rule): config/metrics/trace/obs.* +
durability/rotation only; heavier collaborators bind lazily at capture.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from geomesa_tpu import config
from geomesa_tpu import trace as _trace
from geomesa_tpu.metrics import REGISTRY as _metrics

_MEM_KEEP = 32  # in-memory bundle ring (independent of the disk keep knob)


class ForensicStore:
    """Capture + fetch surface for forensic bundles. Injectable for
    tests (registry, clock, history, dir); the global ``FORENSICS``
    late-binds to the process globals and reads the knobs per capture
    so runtime reconfiguration applies."""

    def __init__(self, dir_path: Optional[str] = None,
                 keep: Optional[int] = None, registry=None,
                 history=None, clock: Callable[[], float] = time.time):
        self._dir = dir_path
        self._keep = keep
        self._reg = registry if registry is not None else _metrics
        self._history = history
        self._clock = clock
        self._lock = threading.Lock()
        self._bundles: deque = deque(maxlen=_MEM_KEEP)

    # -- lazy collaborators ----------------------------------------------

    def _hist(self):
        if self._history is not None:
            return self._history
        from geomesa_tpu.obs import history as _history
        return _history.HISTORY

    def _dir_path(self) -> Optional[str]:
        if self._dir is not None:
            return self._dir or None
        return str(config.FORENSICS_DIR.get() or "") or None

    def _keep_n(self) -> int:
        if self._keep is not None:
            return int(self._keep)
        return max(1, int(config.FORENSICS_KEEP.get()))

    # -- capture ---------------------------------------------------------

    def capture(self, incident: dict, now: Optional[float] = None) -> Optional[dict]:
        """Build + retain the bundle for a newly-opened incident. Never
        raises — the doctor's evaluation must survive a failing disk,
        a half-wired collaborator, or an injected crash."""
        if not config.FORENSICS_ENABLED.get():
            return None
        try:
            bundle = self._build(incident, now)
        except Exception:
            self._reg.inc("forensics.errors")
            return None
        with self._lock:
            self._bundles.append(bundle)
        self._reg.inc("forensics.captured")
        try:
            self._install(bundle)
        except BaseException:
            # InjectedCrash is a BaseException: surface it to the test
            # harness AFTER accounting, so atomicity is still provable.
            self._reg.inc("forensics.errors")
            raise
        return bundle

    def _build(self, incident: dict, now: Optional[float]) -> dict:
        if now is None:
            now = self._clock()
        now_ms = int(now * 1000)
        opened_ms = int(incident.get("opened_ms") or now_ms)
        slice_ms = max(0.0, float(config.HISTORY_SLICE_S.get())) * 1000.0
        # anchor at the EARLIER of the incident's wall open and the
        # store's clock, so an injected test clock still yields a slice
        # that covers the firing window
        since_ms = int(min(opened_ms, now_ms) - slice_ms)
        hist = self._hist()
        history_slice = {"since_ms": since_ms, "series": {}}
        try:
            tier = None
            for name in hist.series_names():
                history_slice["series"][name] = hist.range(
                    name, since_ms=since_ms, tier=tier)
        except Exception:
            history_slice["error"] = "history unavailable"

        timeline = incident.get("timeline") or {}
        match = {}
        events: List[dict] = []
        try:
            from geomesa_tpu.obs.flight import RECORDER
            cap = max(0, int(config.DOCTOR_TIMELINE_EVENTS.get()))
            events = RECORDER.recent(limit=cap, since_ms=since_ms,
                                     **match) if cap else []
        except Exception:
            pass

        state = {}
        try:
            snap = self._reg.snapshot_prefixed(
                "replication.", "cell.", "cluster.", "shard.")
            state = {k: v for k, v in snap.items() if v}
        except Exception:
            pass

        hot_set = None
        try:
            from geomesa_tpu.obs import workload as _wl
            hot_set = _wl.WORKLOAD.hot_set()
        except Exception:
            pass
        balance = None
        try:
            from geomesa_tpu.obs import shardwatch as _sw
            balance = _sw.WATCH.balance()
        except Exception:
            pass

        return {
            "incident_id": incident.get("id"),
            "rule": incident.get("rule"),
            "cause": incident.get("cause"),
            "severity": incident.get("severity"),
            "node": incident.get("node") or _trace.node_id(),
            "opened_ms": opened_ms,
            "captured_ms": int(now * 1000),
            "history": history_slice,
            "events": events,
            "trace_gids": list(timeline.get("trace_gids") or []),
            "router_demotions": timeline.get("router_demotions") or {},
            "replication_state": state,
            "workload_hot_set": hot_set,
            "shard_balance": balance,
        }

    def _install(self, bundle: dict) -> None:
        """Durable half: tmp + atomic rename + keep-N GC. No-op without
        a configured directory."""
        d = self._dir_path()
        if not d:
            return
        from geomesa_tpu.durability import rotation
        os.makedirs(d, exist_ok=True)
        name = f"bundle-{bundle['captured_ms']}-{bundle['incident_id']}.json"
        final = os.path.join(d, name)
        tmp = final + ".tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump(bundle, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            rotation.atomic_install(tmp, final)
        except OSError:
            self._reg.inc("forensics.errors")
            try:
                os.remove(tmp)
            except OSError:
                pass
            return
        finally:
            if os.path.exists(tmp) and os.path.exists(final):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        kept = sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.startswith("bundle-") and f.endswith(".json"))
        rotation.keep_newest(
            kept, self._keep_n(),
            on_drop=lambda p: self._reg.inc("forensics.gc"))

    # -- fetch -----------------------------------------------------------

    def get(self, incident_id: str) -> Optional[dict]:
        """Newest bundle for an incident id — memory first, then the
        durable directory (a restart keeps bundles fetchable)."""
        with self._lock:
            for bundle in reversed(self._bundles):
                if bundle.get("incident_id") == incident_id:
                    return bundle
        d = self._dir_path()
        if not d or not os.path.isdir(d):
            return None
        suffix = f"-{incident_id}.json"
        candidates = sorted(f for f in os.listdir(d)
                            if f.startswith("bundle-")
                            and f.endswith(suffix))
        for name in reversed(candidates):
            try:
                with open(os.path.join(d, name)) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                continue
        return None

    def list(self) -> List[dict]:
        """Bundle index, oldest first: id/rule/captured_ms per bundle."""
        with self._lock:
            return [{"incident_id": b.get("incident_id"),
                     "rule": b.get("rule"),
                     "cause": b.get("cause"),
                     "captured_ms": b.get("captured_ms"),
                     "events": len(b.get("events") or ()),
                     "series": len((b.get("history") or {})
                                   .get("series") or ())}
                    for b in self._bundles]

    def clear(self) -> None:
        with self._lock:
            self._bundles.clear()


FORENSICS = ForensicStore()
