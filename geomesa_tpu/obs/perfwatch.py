"""Noise-aware bench regression gating against a committed baseline store.

The problem this solves (ISSUE 6): cfg4 KNN regressed 472 -> 614 ms
between bench rounds and only a human scanning raw BENCH json blobs
noticed. From this PR on, every bench run emits a flat machine-stable
``BENCH_summary.json`` and ``bench.py --check`` / ``geomesa-tpu
perfwatch`` compare it against ``perf/baselines.json`` (committed), with
three properties an absolute-threshold gate lacks:

  noise-aware   each metric's baseline is a rolling sample window with
                median + MAD (median absolute deviation — robust to the
                occasional loaded-runner outlier the mean is not); a
                run flags only past ``median + k * MAD`` in the metric's
                BAD direction, floored by a relative band
                (PERFWATCH_MIN_REL) so few-sample baselines with MAD ~0
                don't flag measurement jitter. An unmodified back-to-back
                run must never flag.
  direction-aware   ``_qps`` / ``_per_s`` / throughput metrics regress
                DOWN, ``_ms`` / ``_s`` / bytes regress UP, and count
                metrics (``_matched`` / ``_mass``) are exact — any drift
                there is a correctness bug, not noise.
  attributing   each summary carries the per-kernel attribution snapshot
                (obs/attrib); the comparator diffs per-kernel device-wait
                means, compile counts and recompiles between run and
                baseline and NAMES the kernel whose cost moved — the
                report says "cfg4_knn10_ms regressed 2.1x; culprit
                kernel.topk_blocks.point_boxes.b8 device_wait +105%",
                not just "something got slower".

Machine normalization: baselines record a host-speed proxy (the pure-CPU
indexed count, ``cfg0_cpu_1m_bbox_p50_ms``). When a run's proxy differs
from the baseline's (CI runner vs the bench box), duration/throughput
medians scale by the clamped proxy ratio before comparison, so the
committed baselines gate loosely-but-sanely on foreign machines while
staying tight on the machine that recorded them.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional

from geomesa_tpu import config

SCHEMA = 1
# host-speed proxy metric: pure-CPU work, present in every mini run
SPEED_PROXY = "cfg0_cpu_1m_bbox_p50_ms"
# samples kept per metric in the rolling baseline window
KEEP_SAMPLES = 12

# -- metric directions --------------------------------------------------------

_HIGHER = ("_qps", "_per_s", "_per_chip", "_mbps", "_hit_rate",
           "_gb_per_s", "upload_mbps", "_speedup")
_EXACT = ("_matched", "_mass", "_pairs", "_blocks", "_submitted")
_LOWER = ("_ms", "_s", "_us", "_bytes", "_kb", "_pct", "_seconds",
          "_slop", "_fraction")
# metrics whose suffix misleads (shed rate is workload-set, not a perf
# axis; raw sizes describe the corpus, not the code)
_OVERRIDES = {
    "cfg7_overload_shed_rate": "skip",
    "n_points": "skip", "host_cores": "skip", "value": "skip",
    # fleet-soak scoreboard (cfg11): the doctor's precision/recall and
    # the conservation checks are correctness axes — ANY drift from the
    # baselined 1.0 / 0 is a gate failure, not statistical noise
    "cfg11_doctor_precision": "exact",
    "cfg11_doctor_recall": "exact",
    "cfg11_acked_write_loss": "exact",
    "cfg11_clean_incidents": "exact",
    "cfg11_worst_phase_burn_rate": "lower",
    # cluster dryrun (cfg12): exactness vs the single-process oracle is
    # a correctness axis — a psum count / merged select / density grid
    # that drifts from byte-equality is a distribution bug, never noise,
    # and a shard that stops being a strict subset means partitioning
    # silently degenerated to replication
    "cfg12_count_mismatch": "exact",
    "cfg12_select_mismatch": "exact",
    "cfg12_density_mismatch": "exact",
    "cfg12_shard_strict_subset": "exact",
    # shard balance observatory (cfg13, two-sided): a Zipf storm the
    # ledger fails to flag / mis-attributes, a projected split key
    # outside the victim's key range, or a false alarm on the uniform
    # control half is a correctness bug, never noise. The raw balance
    # scores ride the statistical gate with pinned directions: skew
    # detection eroding DOWN or the control drifting UP both flag.
    "cfg13_skew_flagged": "exact",
    "cfg13_skew_incidents": "exact",
    "cfg13_skew_attributed": "exact",
    "cfg13_skew_splits_in_range": "exact",
    "cfg13_control_incidents": "exact",
    "cfg13_control_balanced": "exact",
    "cfg13_fleet_federated": "exact",
    "cfg13_dryrun_ok": "exact",
    "cfg13_skew_max_over_mean": "higher",
    "cfg13_control_max_over_mean": "lower",
    # single-dispatch cold queries (cfg14): one round per fused cold
    # query, zero recompiles across distinct same-shape values, and
    # fused==staged counts are the contract the fused path exists on —
    # any drift is a correctness bug, never noise. Latencies and the
    # speedup ride the statistical gate via their suffixes; the floor
    # multiple pins how far the fused path sits above the raw dispatch
    # RTT (erosion there is overhead creeping back into the hot path).
    "cfg14_fused_dispatches_per_cold_query": "exact",
    "cfg14_fused_recompiles": "exact",
    "cfg14_fused_parity_mismatches": "exact",
    "cfg14_fused_floor_multiple": "lower",
    # how many rounds the staged path pays is workload description, not
    # a perf axis of the code under gate
    "cfg14_staged_dispatches_per_cold_query": "skip",
    "cfg14_staged_floor_multiple": "skip",
    # geometry function catalog (cfg15): every exactness axis is a
    # correctness contract, never noise — fused st_* counts byte-equal
    # to the host oracle, one device round and zero fallbacks per
    # eligible cold function query, the 2-process join / function-count
    # batteries byte-equal to the single-process oracle, and the join
    # numbers only mean anything at the recorded process count. The
    # latencies, the >=10x host-vs-fused speedup, and the join candidate
    # throughput ride the statistical gate via their suffixes.
    "cfg15_func_parity_mismatches": "exact",
    "cfg15_fused_dispatches_per_cold_query": "exact",
    "cfg15_fused_fallbacks": "exact",
    "cfg15_join_mismatch": "exact",
    "cfg15_func_count_mismatch": "exact",
    "cfg15_join_dryrun_ok": "exact",
    "cfg15_join_num_processes": "exact",
    # cluster cell soak (cfg16, two-sided like cfg11): every robustness
    # verdict is a correctness axis — zero acked-write loss through
    # failover/handoff/dark-shard chaos, per-cell fingerprint equality,
    # BOTH fenced split-brain losers refusing, failover inside the
    # budget, the doctor's shard_dark precision/recall, the honest
    # partial-result envelope, and a silent clean half. ANY drift from
    # the baselined values fails --check; the failover/handoff/steady
    # latencies ride the statistical gate via their suffixes.
    "cfg16_failover_within_budget": "exact",
    "cfg16_acked_write_loss": "exact",
    "cfg16_split_brain_refused": "exact",
    "cfg16_doctor_precision": "exact",
    "cfg16_doctor_recall": "exact",
    "cfg16_clean_incidents": "exact",
    "cfg16_shard_dark_fired": "exact",
    "cfg16_partial_envelope_seen": "exact",
    # telemetry history plane (cfg17): the sampler tick, the amortized
    # scrape-cadence overhead and the bundle freeze are costs that must
    # only ever erode DOWN — retention creeping into the hot path is
    # exactly what the <5% obs-overhead guard exists to catch, and the
    # bench pins the trend early. Ring memory is structure-shaped (it
    # tracks whatever series the registry happens to hold), so it is
    # informational, not gated.
    "cfg17_history_tick_us": "lower",
    "cfg17_history_overhead_pct": "lower",
    "cfg17_history_cost_us_per_query": "lower",
    "cfg17_bundle_capture_ms": "lower",
    "cfg17_ring_memory_bytes": "skip",
    "cfg17_wall_s": "skip",
}


def metric_direction(name: str) -> str:
    """'lower' (regression = value UP), 'higher' (regression = DOWN),
    'exact' (any drift at equal scale is a correctness flag), or 'skip'
    (non-gated informational metric)."""
    o = _OVERRIDES.get(name)
    if o is not None:
        return o
    if name.endswith(_HIGHER) or "qps" in name or "vs_" in name:
        return "higher"
    if name.endswith(_EXACT):
        return "exact"
    if name.endswith(_LOWER):
        return "lower"
    return "skip"


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _mad(xs: List[float], med: Optional[float] = None) -> float:
    if not xs:
        return 0.0
    m = _median(xs) if med is None else med
    return _median([abs(x - m) for x in xs])


# -- baseline store -----------------------------------------------------------


def empty_baselines() -> dict:
    return {"schema": SCHEMA, "updated_ts": None, "meta": {},
            "metrics": {}, "kernels": {}}


def load_baselines(path: str) -> dict:
    with open(path) as fh:
        b = json.load(fh)
    if b.get("schema") != SCHEMA:
        raise ValueError(f"baseline schema {b.get('schema')} != {SCHEMA}")
    return b


def save_baselines(baselines: dict, path: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(baselines, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def kernel_summary(attrib_snapshot: dict) -> Dict[str, dict]:
    """Reduce an obs/attrib snapshot to the flat per-kernel numbers the
    comparator diffs: kernel series name -> {wait_mean_ms, dispatches,
    compiles, compile_total_ms, transfer_bytes, flops, hbm_bytes}."""
    out: Dict[str, dict] = {}

    def k(name: str) -> dict:
        # kernel.<id>.b<tier>.<metric> -> kernel.<id>.b<tier>
        base = name.rsplit(".", 1)[0]
        return out.setdefault(base, {})

    for name, h in (attrib_snapshot.get("timers") or {}).items():
        if name.endswith(".device_wait"):
            d = k(name)
            # one device round trip = host enqueue + block-until-ready;
            # the .dispatch series carries the enqueue side on direct
            # paths, so the per-kernel mean folds both
            d["wait_mean_ms"] = round(
                d.get("wait_mean_ms", 0.0) + h.get("mean_ms", 0.0), 4)
            d["dispatches"] = h.get("count", 0)
        elif name.endswith(".dispatch"):
            d = k(name)
            d["wait_mean_ms"] = round(
                d.get("wait_mean_ms", 0.0) + h.get("mean_ms", 0.0), 4)
        elif name.endswith(".compile"):
            k(name)["compile_total_ms"] = round(
                h.get("total_s", 0.0) * 1000, 3)
    for name, v in (attrib_snapshot.get("counters") or {}).items():
        if name.endswith(".compiles"):
            k(name)["compiles"] = v
        elif name.endswith(".transfer_bytes"):
            k(name)["transfer_bytes"] = v
    for name, v in (attrib_snapshot.get("gauges") or {}).items():
        if name.endswith((".flops", ".hbm_bytes")):
            k(name)[name.rsplit(".", 1)[1]] = v
    return {name: d for name, d in out.items() if d}


def update_baselines(baselines: dict, summary: dict,
                     keep: int = KEEP_SAMPLES) -> dict:
    """Fold one run summary into the rolling baseline store: append each
    metric's value to its sample window (bounded to ``keep``), recompute
    median + MAD, refresh the kernel reference snapshot and meta. Returns
    the same dict, mutated."""
    metrics = summary.get("metrics") or {}
    store = baselines.setdefault("metrics", {})
    for name, value in metrics.items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        if metric_direction(name) == "skip":
            continue
        ent = store.setdefault(name, {"samples": []})
        ent["samples"] = (ent["samples"] + [float(value)])[-keep:]
        med = _median(ent["samples"])
        ent["median"] = round(med, 6)
        ent["mad"] = round(_mad(ent["samples"], med), 6)
        ent["direction"] = metric_direction(name)
    baselines["kernels"] = summary.get("kernels") or {}
    baselines["meta"] = summary.get("meta") or {}
    baselines["updated_ts"] = int(time.time())
    baselines["runs"] = int(baselines.get("runs") or 0) + 1
    return baselines


# -- comparison ---------------------------------------------------------------


def _meta_procs(meta) -> Optional[int]:
    """Process count recorded in a summary/baseline ``meta`` block.
    Absent (a baseline written before the field existed) means the
    historical single-process population → 1. Present but unparseable
    (a corrupted or future-schema store) → None, which ``compare``
    treats as a process mismatch — new-baseline semantics, never a
    crash: an aged baseline file must not brick the gate."""
    v = (meta or {}).get("num_processes")
    if v is None or v == "":
        return 1
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


def _speed_ratio(run_metrics: dict, baselines: dict) -> float:
    """run-host / baseline-host speed ratio from the CPU proxy metric.
    A DEADBAND treats ratios within [0.67, 1.5] as 1.0 — the proxy itself
    is a wall measurement, and letting its run-to-run noise rescale every
    threshold would hide same-machine regressions. Beyond the deadband
    (a genuinely different machine, e.g. a CI runner vs the bench box)
    the ratio applies, clamped to [0.5, 4]. 1.0 when either side lacks
    the proxy."""
    ent = (baselines.get("metrics") or {}).get(SPEED_PROXY)
    now = run_metrics.get(SPEED_PROXY)
    if not ent or not ent.get("median") or not now:
        return 1.0
    raw = float(now) / float(ent["median"])
    if 0.67 <= raw <= 1.5:
        return 1.0
    return max(0.5, min(4.0, raw))


def compare(summary: dict, baselines: dict,
            k: Optional[float] = None,
            min_rel: Optional[float] = None) -> dict:
    """One run summary vs the baseline store -> structured report.

    A metric flags as a regression only when its delta (in the bad
    direction) exceeds BOTH ``k * MAD`` and ``min_rel * median`` past the
    (machine-normalized) baseline median. Improvements past the same band
    in the good direction are reported but never fail the gate. Exact
    metrics flag on any difference when the run scale matches the
    baseline scale (same n_points)."""
    k = float(config.PERFWATCH_K.get() if k is None else k)
    min_rel = float(config.PERFWATCH_MIN_REL.get()
                    if min_rel is None else min_rel)
    run_metrics = summary.get("metrics") or {}
    base_metrics = baselines.get("metrics") or {}
    ratio = _speed_ratio(run_metrics, baselines)
    same_scale = (summary.get("meta") or {}).get("n_points") \
        == (baselines.get("meta") or {}).get("n_points")
    run_procs = _meta_procs(summary.get("meta"))
    base_procs = _meta_procs(baselines.get("meta"))
    if run_procs is None or base_procs is None or run_procs != base_procs:
        # a single-process baseline says nothing about a multi-process
        # run (collectives, host exchange, per-shard cardinality all
        # differ) — a mismatch is a new baseline population, never a
        # regression or an improvement
        return {
            "schema": SCHEMA, "ok": True,
            "k": k, "min_rel": min_rel, "speed_ratio": 1.0,
            "same_scale": False,
            "process_mismatch": {"run": run_procs, "baseline": base_procs},
            "checked": 0,
            "regressions": [], "improvements": [], "missing_metrics": [],
            "new_metrics": sorted(
                n for n in run_metrics
                if metric_direction(n) != "skip"
                and isinstance(run_metrics[n], (int, float))),
            "kernels": attribute_kernels({}, {}),
        }

    regressions, improvements, missing, new = [], [], [], []
    checked = 0
    for name, ent in sorted(base_metrics.items()):
        direction = ent.get("direction") or metric_direction(name)
        if direction == "skip":
            continue
        if name not in run_metrics \
                or not isinstance(run_metrics[name], (int, float)):
            missing.append(name)
            continue
        value = float(run_metrics[name])
        median = float(ent.get("median") or 0.0)
        mad = float(ent.get("mad") or 0.0)
        checked += 1
        if direction == "exact":
            if same_scale and value != median:
                regressions.append({
                    "metric": name, "kind": "value_changed",
                    "value": value, "baseline": median,
                    "note": "exact metric drifted at equal scale "
                            "(correctness, not noise)"})
            continue
        # machine normalization applies to measured quantities only
        scaled = median * ratio if direction == "lower" else median / ratio
        noise = ratio if direction == "lower" else 1.0 / ratio
        samples = ent.get("samples") or ()
        # the baseline's own observed spread is an empirical noise
        # envelope: never flag a delta the baseline runs themselves
        # exhibited (few-sample MAD underestimates loaded-host swing)
        span = (max(samples) - min(samples)) if len(samples) >= 2 else 0.0
        threshold = max(k * mad * noise, span * noise,
                        min_rel * abs(scaled))
        if name.endswith(("_ms", "_s")):
            # measurement-resolution floor: sub-0.05 deltas on rounded
            # duration metrics are timer quantization, not signal
            threshold = max(threshold, 0.05)
        delta = value - scaled if direction == "lower" else scaled - value
        rec = {
            "metric": name, "value": value, "baseline": median,
            "baseline_scaled": round(scaled, 6), "mad": mad,
            "threshold": round(threshold, 6),
            "ratio": round(value / scaled, 3) if scaled else None,
            "samples": len(ent.get("samples") or ()),
        }
        if delta > threshold:
            rec["kind"] = "regression"
            rec["severity"] = round(delta / threshold, 2)
            regressions.append(rec)
        elif -delta > threshold:
            rec["kind"] = "improvement"
            improvements.append(rec)
    for name in sorted(run_metrics):
        if name not in base_metrics \
                and metric_direction(name) != "skip" \
                and isinstance(run_metrics[name], (int, float)):
            new.append(name)

    regressions.sort(key=lambda r: -(r.get("severity") or math.inf))
    report = {
        "schema": SCHEMA,
        "ok": not regressions,
        "k": k, "min_rel": min_rel, "speed_ratio": round(ratio, 3),
        "same_scale": bool(same_scale),
        "checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "missing_metrics": missing,
        "new_metrics": new,
        "kernels": attribute_kernels(summary.get("kernels") or {},
                                     baselines.get("kernels") or {},
                                     ratio),
    }
    return report


def attribute_kernels(run_kernels: Dict[str, dict],
                      base_kernels: Dict[str, dict],
                      ratio: float = 1.0,
                      min_rel: float = 0.25,
                      min_abs_ms: float = 0.05) -> dict:
    """Diff the per-kernel attribution snapshots and name the kernels
    whose device cost moved — the 'which kernel did it' half of the
    report. A kernel flags when its mean device wait grew > ``min_rel``
    past the machine-normalized baseline AND by at least ``min_abs_ms``,
    or when it compiled where the baseline did not (recompile churn)."""
    moved: List[dict] = []
    for name, now in sorted(run_kernels.items()):
        base = base_kernels.get(name)
        if base is None:
            continue
        w_now = now.get("wait_mean_ms")
        w_base = base.get("wait_mean_ms")
        if w_now is not None and w_base:
            scaled = w_base * ratio
            if w_now > scaled * (1 + min_rel) \
                    and (w_now - scaled) > min_abs_ms:
                moved.append({
                    "kernel": name, "kind": "device_wait",
                    "wait_mean_ms": w_now,
                    "baseline_ms": w_base,
                    "ratio": round(w_now / scaled, 2)})
        c_now = now.get("compiles") or 0
        c_base = base.get("compiles") or 0
        if c_now > c_base:
            moved.append({
                "kernel": name, "kind": "compiles",
                "compiles": c_now, "baseline": c_base,
                "note": "compiled more than baseline — recompile/shape "
                        "churn suspect"})
    moved.sort(key=lambda m: -(m.get("ratio") or 2.0))
    out = {"moved": moved}
    if moved:
        out["culprit"] = moved[0]["kernel"]
    return out


def render(report: dict) -> str:
    """Human-readable regression report (stderr / CI log / runbook)."""
    lines = []
    status = "OK" if report["ok"] else "REGRESSED"
    lines.append(
        f"perfwatch: {status} — {len(report['regressions'])} regression(s), "
        f"{len(report['improvements'])} improvement(s), "
        f"{report['checked']} metric(s) checked "
        f"(k={report['k']}, floor={report['min_rel']:.0%}, "
        f"speed_ratio={report['speed_ratio']})")
    pm = report.get("process_mismatch")
    if pm:
        lines.append(
            f"  process-count mismatch: run has {pm['run']} process(es), "
            f"baseline has {pm['baseline']} — treating every metric as "
            f"new-baseline (nothing compared, nothing gated)")
    for r in report["regressions"]:
        if r.get("kind") == "value_changed":
            lines.append(f"  REGRESSION {r['metric']}: {r['value']} != "
                         f"baseline {r['baseline']} (exact metric)")
        else:
            lines.append(
                f"  REGRESSION {r['metric']}: {r['value']:g} vs baseline "
                f"{r['baseline']:g} (x{r['ratio']}, threshold "
                f"{r['threshold']:g}, severity {r['severity']})")
    culprit = (report.get("kernels") or {}).get("culprit")
    if culprit:
        lines.append(f"  culprit kernel: {culprit}")
    for m in (report.get("kernels") or {}).get("moved", []):
        if m["kind"] == "device_wait":
            lines.append(
                f"    {m['kernel']}: device_wait {m['wait_mean_ms']:g}ms "
                f"vs {m['baseline_ms']:g}ms (x{m['ratio']})")
        else:
            lines.append(
                f"    {m['kernel']}: {m['compiles']} compiles vs "
                f"{m['baseline']} — {m['note']}")
    for r in report["improvements"]:
        lines.append(f"  improvement {r['metric']}: {r['value']:g} vs "
                     f"{r['baseline']:g} (x{r['ratio']})")
    if report["missing_metrics"]:
        lines.append(f"  missing vs baseline: "
                     f"{', '.join(report['missing_metrics'])}")
    if report["new_metrics"]:
        lines.append(f"  new (unbaselined): "
                     f"{', '.join(report['new_metrics'])}")
    return "\n".join(lines)


def check_summary(summary: dict, baseline_path: str,
                  k: Optional[float] = None,
                  report_path: Optional[str] = None) -> dict:
    """The one-call gate: load baselines, compare, optionally write the
    report JSON. Raises FileNotFoundError when no baseline exists (the
    bootstrap path: run with --update-baseline first)."""
    baselines = load_baselines(baseline_path)
    report = compare(summary, baselines, k=k)
    if report_path:
        d = os.path.dirname(report_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return report
