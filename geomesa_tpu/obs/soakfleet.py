"""Fleet soak scoreboard: chaos-scored SLO verification over a REAL fleet.

Where obs/soak.py drives one in-process store through fault injections,
this orchestrator launches the genuine PR-7/8 topology — a durable
primary, N follower replicas and the read router as *subprocesses* over
localhost WAL-shipping sockets — drives sustained Zipf multi-tenant
traffic through the router, and executes a declarative chaos timeline
mid-run (rolling restart, replica kill, replication-lag spike,
promote-failover, reindex-under-load) while a fleet-level DoctorEngine
watches the run through a Federator.

The run is scored into a scoreboard (JSON + rendered markdown):

  * fleet-federated p50/p99 and SLO burn per phase (steady / each
    fault / recovery), from merged ``query.count`` histogram deltas;
  * doctor incident precision + recall against the known fault
    schedule — every injected fault must open exactly one
    correctly-attributed incident, and no incident may open outside a
    fault window;
  * failover and catch-up times vs their budgets;
  * result-cache hit-rate and per-tenant QoS victim p99 under the
    storm;
  * federation honesty under node death (``partial``/``missing``
    truthful, paging suppressed, ``fed.scrape_errors.<node>`` matching
    the kill window);
  * conservation: no acked write lost (final count == seed + acks) and
    byte-identical durability-dir fingerprints across the surviving
    fleet at exit.

The scoreboard's numeric metrics surface as bench cfg11 and fold into
perf/baselines.json, so an SLO/recovery regression gates a PR exactly
like a kernel perf regression.  ``faulted=False`` replays the same
traffic with paced writes and no chaos: zero incidents allowed.

Knobs (``GEOMESA_TPU_SOAK_*``): SOAK_PHASE_S (per-phase drive window),
SOAK_WAIT_S (incident/catch-up wait ceiling), SOAK_FOLLOWERS,
SOAK_CATCHUP_BUDGET_S, and SOAK_STRETCH — a multiplier on injected
chaos magnitudes used by the gate self-test (stretch > 1 makes the
lag-spike genuinely worse, so ``perfwatch --check`` must fail).

obs/soakcells.py is this soak's cluster-v2 sibling: the same
launch/drive/score skeleton over a SHARDED fleet of replicated cells
behind the shard-aware router, scored as bench cfg16 (cell failover,
ownership handoff, cross-cell split-brain, dark-shard envelopes).
"""
from __future__ import annotations

import json
import math
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import BUCKET_BOUNDS
from geomesa_tpu.metrics import REGISTRY as _metrics

SCOREBOARD_DEFAULT = "SOAK_scoreboard.json"

# the most recent completed run in this process (GET /fleet/soak serves
# it; falls back to the scoreboard file a previous run wrote)
LAST: Optional[dict] = None


def _log(msg: str) -> None:
    """Progress narration (stderr) when GEOMESA_TPU_SOAK_VERBOSE is set —
    a multi-minute multi-process run is undebuggable without it."""
    if os.environ.get("GEOMESA_TPU_SOAK_VERBOSE"):
        print(f"[soakfleet +{time.monotonic() % 100000:.1f}] {msg}",
              file=sys.stderr, flush=True)


def last_run() -> Optional[dict]:
    if LAST is not None:
        return LAST
    path = os.environ.get("GEOMESA_TPU_SOAK_SCOREBOARD", SCOREBOARD_DEFAULT)
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- plumbing -----------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(port: int, path: str, method: str = "GET",
          body: Optional[bytes] = None, timeout: float = 10.0) -> dict:
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _wait_http(port: int, path: str = "/healthz",
               timeout_s: float = 120.0) -> dict:
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            return _http(port, path, timeout=2.0)
        except Exception as e:  # noqa: BLE001 - startup race, keep polling
            last = e
            time.sleep(0.2)
    raise TimeoutError(f"node on :{port} never served {path}: {last}")


# -- pure scoring helpers (unit-tested without a fleet) -----------------------


def hist_delta_percentile(buckets0: List[int], buckets1: List[int],
                          q: float) -> float:
    """Percentile (in ms) of the observations that landed BETWEEN two
    cumulative bucket snapshots of a merged ``metrics.Histogram`` —
    bucket-resolution, conservative (upper bound), like
    ``Histogram.percentile``."""
    delta = [max(0, int(b1) - int(b0))
             for b0, b1 in zip(buckets0, buckets1)]
    n = sum(delta)
    if n <= 0:
        return 0.0
    rank = max(1, math.ceil(q * n))
    seen = 0
    for i, d in enumerate(delta):
        seen += d
        if seen >= rank:
            return BUCKET_BOUNDS[i] * 1000.0
    return BUCKET_BOUNDS[-1] * 1000.0


def fleet_backlog(seqs: Dict[str, dict], primary: str,
                  followers: List[str]) -> int:
    """Worst follower replication backlog from last-KNOWN positions.
    A dead follower's applied_seq stays frozen while the primary's
    wal_seq advances, so its backlog keeps growing — exactly the signal
    the fleet doctor needs when the node itself can no longer report."""
    head = (seqs.get(primary) or {}).get("wal")
    if head is None:
        return 0
    worst = 0
    for name in followers:
        applied = (seqs.get(name) or {}).get("applied")
        if applied is not None:
            worst = max(worst, int(head) - int(applied))
    return worst


def percentile_ms(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]


def score_phases(phases: List[dict]) -> dict:
    """Precision/recall of the incident stream against the fault
    schedule.  Recall: fault phases that got exactly one incident with
    the right rule.  Precision: correctly-attributed incidents over all
    incidents opened anywhere in the run (an incident during steady or
    recovery is a false positive by construction)."""
    fault = [p for p in phases if p.get("expected_rule")]
    hits = sum(1 for p in fault if p.get("ok"))
    recall = (hits / len(fault)) if fault else 1.0
    total = sum(len(p.get("new_incidents") or []) for p in phases)
    correct = sum(
        sum(1 for i in (p.get("new_incidents") or [])
            if i.get("rule") == p.get("expected_rule"))
        for p in fault)
    precision = (correct / total) if total else 1.0
    return {"precision": round(precision, 4), "recall": round(recall, 4),
            "fault_phases": len(fault), "detected": hits,
            "incidents_total": total, "correct": correct,
            "false_positives": total - correct}


class _NoWorkload:
    """Silent workload plane: the orchestrator process serves nothing,
    so the skew detector must not read its (possibly dirty, e.g. mid-
    bench) process-global workload state."""

    def hot_set(self, k=None):
        return {"total": 0, "plans": [], "cells": []}

    def top_tenants(self, k=10):
        return []


class _FleetView:
    """Registry facade over a Federator: the fleet DoctorEngine and the
    fleet SloEngine read merged counters, computed replication-backlog
    gauges and merged latency histograms through the same ``snapshot()``
    / ``timer_good_total()`` surface a node-local registry offers.
    ``retarget()`` swaps in the post-failover Federator so the engines
    keep scoring across a primary change."""

    def __init__(self, fed, primary: str, followers: List[str]):
        self.fed = fed
        self.primary = primary
        self.followers = list(followers)
        self.seqs: Dict[str, dict] = {}

    def retarget(self, fed, primary: str, followers: List[str]) -> None:
        self.fed = fed
        self.primary = primary
        self.followers = list(followers)
        keep = {primary, *followers}
        self.seqs = {n: s for n, s in self.seqs.items() if n in keep}

    def observe(self) -> None:
        for name, s in self.fed.refresh().items():
            if not s.ok or not s.healthz:
                continue
            dur = s.healthz.get("durability") or {}
            repl = s.healthz.get("replication") or {}
            d = self.seqs.setdefault(name, {})
            if dur.get("wal_seq") is not None:
                d["wal"] = int(dur["wal_seq"])
            if repl.get("applied_seq") is not None:
                d["applied"] = int(repl["applied_seq"])

    def backlog(self) -> int:
        return fleet_backlog(self.seqs, self.primary, self.followers)

    # -- registry surface (DoctorEngine + SloEngine) --------------------------

    def snapshot(self) -> dict:
        self.observe()
        return {"counters": self.fed.merged_counters(),
                "gauges": {"replication.lag_seqs": float(self.backlog()),
                           "replication.lag_ms": 0.0}}

    def inc(self, name: str, v: int = 1):
        return _metrics.inc(name, v)

    def set_gauge(self, name: str, fn):
        return _metrics.set_gauge(name, fn)

    def timer_good_total(self, name: str, threshold_s: float):
        return self.fed.timer_good_total(name, threshold_s)


# -- traffic ------------------------------------------------------------------

_TENANTS = [f"tenant{k}" for k in range(8)]
# rarest tenant: the QoS "victim" whose p99 under the storm is scored
VICTIM_TENANT = _TENANTS[-1]


def _query_shapes(n: int = 60) -> List[str]:
    shapes = []
    for i in range(n):
        x0 = round(-10.0 + (i % 10) * 1.7, 2)
        y0 = round(-10.0 + (i // 10) * 2.9, 2)
        shapes.append(f"BBOX(geom, {x0}, {y0}, {x0 + 3.0}, {y0 + 3.0})")
    return shapes


class _Traffic(threading.Thread):
    """Sustained Zipf multi-tenant reads through the router, cfg8-shaped:
    ~60 bbox shapes under a 1/r^1.1 popularity law, 8 tenants weighted
    1/r.  Client-side latencies are recorded per (phase, tenant) so the
    scoreboard can report the victim tenant's p99 under the storm."""

    def __init__(self, router_port: int, seed: int = 7,
                 period_s: float = 0.004):
        super().__init__(name="soakfleet-traffic", daemon=True)
        self.router_port = router_port
        self.period_s = period_s
        self.stop_evt = threading.Event()
        self.phase = "warmup"
        self.samples: List[tuple] = []   # (phase, tenant, ms) — append-only
        self.sent = 0
        self.errors = 0
        import random
        self._rng = random.Random(seed)
        self._shapes = _query_shapes()
        self._wshapes = [1.0 / (r + 1) ** 1.1
                         for r in range(len(self._shapes))]
        self._wtenants = [1.0 / (r + 1) for r in range(len(_TENANTS))]

    def set_phase(self, name: str) -> None:
        self.phase = name

    def run(self) -> None:
        while not self.stop_evt.is_set():
            cql = self._rng.choices(self._shapes, self._wshapes)[0]
            tenant = self._rng.choices(_TENANTS, self._wtenants)[0]
            q = urllib.parse.urlencode({"cql": cql, "tenant": tenant})
            t0 = time.perf_counter()
            try:
                _http(self.router_port, f"/types/t/count?{q}", timeout=5.0)
            except Exception:  # noqa: BLE001 - mid-chaos errors are expected
                self.errors += 1
            else:
                self.samples.append(
                    (self.phase, tenant,
                     (time.perf_counter() - t0) * 1000.0))
            self.sent += 1
            self.stop_evt.wait(self.period_s)

    def stop(self) -> None:
        self.stop_evt.set()
        self.join(timeout=10.0)

    def phase_lat(self, phase: str,
                  tenant: Optional[str] = None) -> List[float]:
        return [ms for (p, t, ms) in list(self.samples)
                if p == phase and (tenant is None or t == tenant)]


# -- the orchestrator ---------------------------------------------------------


class FleetSoak:
    """One soak half over a real subprocess fleet.  ``faulted=True``
    executes the chaos timeline and requires one correctly-attributed
    incident per fault; ``faulted=False`` replays the same traffic with
    paced writes and requires zero incidents."""

    def __init__(self, base_dir: str, faulted: bool = True,
                 mini: bool = True, stretch: Optional[float] = None):
        self.base = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.faulted = faulted
        self.mini = mini
        self.stretch = float(stretch if stretch is not None
                             else config.SOAK_STRETCH.get())
        scale = 1.0 if mini else 3.0
        self.phase_s = float(config.SOAK_PHASE_S.get()) * scale
        self.wait_s = float(config.SOAK_WAIT_S.get())
        self.catchup_budget_s = float(config.SOAK_CATCHUP_BUDGET_S.get())
        self.throttle_ms = 120
        self.primary = "p0"
        n_f = max(2, int(config.SOAK_FOLLOWERS.get()))
        self.followers = [f"r{i + 1}" for i in range(n_f)]
        self.procs: Dict[str, subprocess.Popen] = {}
        self.ports: Dict[str, int] = {}
        self.dirs: Dict[str, str] = {}
        self.ship_ports: Dict[str, int] = {}
        self.router_port = 0
        self.rows = 0            # seed + acked ingests (expected final count)
        self.acked = 0
        self._wb = 100           # write-batch counter (seed used 0..2)
        self.fed = None
        self.fv: Optional[_FleetView] = None
        self.slo_eng = None
        self.doctor = None
        self.traffic: Optional[_Traffic] = None
        self.phases: List[dict] = []
        self._seen: set = set()
        self._phase_burn = 0.0
        self._partial_ok = False
        self._partial_violations = 0
        self._pages_while_partial = 0
        self.threshold_ms = 0.0
        self.failover: Optional[dict] = None
        self.catchup_s: Optional[float] = None
        self.honesty: Optional[dict] = None
        self.cache: Optional[dict] = None
        self.notes: List[str] = []

    # -- process management ---------------------------------------------------

    def _spawn(self, args: List[str],
               extra_env: Optional[dict] = None) -> subprocess.Popen:
        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable, "-m", "geomesa_tpu.tools.cli", *args],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)

    def _node_env(self, name: str) -> dict:
        return {"GEOMESA_TPU_NODE_ID": name,
                "GEOMESA_TPU_FAULT_API": "1",
                "GEOMESA_TPU_REINDEX_THROTTLE_MS": str(self.throttle_ms),
                "GEOMESA_TPU_REPL_TRACE_EVERY": "1",
                "GEOMESA_TPU_REPL_ACK_EVERY": "1"}

    def _alive(self, name: str) -> bool:
        p = self.procs.get(name)
        return p is not None and p.poll() is None

    def _signal(self, name: str, sig: int, wait_s: float = 20.0) -> None:
        p = self.procs.get(name)
        if p is None or p.poll() is not None:
            return
        p.send_signal(sig)
        try:
            p.wait(timeout=wait_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10.0)

    def _spawn_primary(self) -> None:
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.replication.drills import SPEC, make_batch
        pdir = os.path.join(self.base, "p0")
        self.dirs["p0"] = pdir
        store = TpuDataStore.open(pdir, params={"wal.fsync": "off"})
        try:
            store.create_schema("t", SPEC)
            for i in range(3):
                store.load("t", make_batch(store.schemas["t"], i))
                self.rows += 40
        finally:
            store.close()
        sp, wp = _free_port(), _free_port()
        self.ship_ports["p0"] = sp
        self.ports["p0"] = wp
        self.procs["p0"] = self._spawn(
            ["serve", "-s", pdir, "--durable",
             "--ship-port", str(sp), "--port", str(wp)],
            self._node_env("p0"))
        _wait_http(wp)

    def _spawn_follower(self, name: str, wait: bool = True) -> None:
        rdir = self.dirs.setdefault(name, os.path.join(self.base, name))
        port = self.ports.get(name) or _free_port()
        self.ports[name] = port
        sp = self.ship_ports[self.primary]
        self.procs[name] = self._spawn(
            ["replica", "--dir", rdir, "--follow", f"127.0.0.1:{sp}",
             "--port", str(port), "--id", name],
            self._node_env(name))
        if wait:
            _wait_http(port)

    def _spawn_router(self) -> None:
        self.router_port = _free_port()
        args = ["router", "--port", str(self.router_port)]
        for n in [self.primary, *self.followers]:
            args += ["--endpoint", f"{n}=127.0.0.1:{self.ports[n]}"]
        self.procs["router"] = self._spawn(args, {"GEOMESA_TPU_NODE_ID":
                                                  "router"})
        _wait_http(self.router_port)

    # -- fleet state ----------------------------------------------------------

    def _mk_federator(self):
        from geomesa_tpu.obs.federation import Federator
        nodes = {n: f"127.0.0.1:{self.ports[n]}"
                 for n in [self.primary, *self.followers]}
        return Federator(nodes, ttl_ms=150.0, timeout_s=2.0)

    def _mk_doctor(self) -> None:
        from geomesa_tpu.obs import slo as _slo
        from geomesa_tpu.obs.doctor import DoctorEngine
        self.fed = self._mk_federator()
        self.fv = _FleetView(self.fed, self.primary, self.followers)
        # calibrate the fleet latency SLO off warm routed counts, the
        # same 20x-warm idiom obs/soak.py uses for the node-local soak
        warm = []
        for _ in range(4):
            t0 = time.perf_counter()
            q = urllib.parse.urlencode({"cql": "BBOX(geom, -5, -5, 5, 5)"})
            _http(self.router_port, f"/types/t/count?{q}")
            warm.append((time.perf_counter() - t0) * 1000.0)
        self.threshold_ms = max(60.0, 20.0 * (sum(warm) / len(warm)))
        self.slo_eng = _slo.SloEngine(registry=self.fv)
        self.slo_eng.add(_slo.Objective(
            name="fleet_count", kind="latency", target=0.99,
            timer="query.count", threshold_ms=self.threshold_ms))
        journal = os.path.join(self.base, "fleet_doctor.jsonl")
        self.doctor = DoctorEngine(registry=self.fv,
                                   slo_engine=self.slo_eng,
                                   journal_path=journal,
                                   federator=False,
                                   workload=_NoWorkload())

    def _counters(self) -> dict:
        self.fed.refresh(force=True)
        return self.fed.merged_counters()

    def _hist_snapshot(self):
        self.fed.refresh(force=True)
        h = self.fed._merged_hists("timers").get("query.count")
        if h is None:
            return (0, [0] * len(BUCKET_BOUNDS))
        hist = h[0]
        return (hist.count, list(hist.buckets))

    # -- writes / catch-up ----------------------------------------------------

    def _write_batch(self, n: int = 40) -> int:
        i = self._wb
        self._wb += 1
        feats = []
        for j in range(n):
            x = -9.5 + ((i * 7 + j) % 190) * 0.1
            y = -9.5 + ((i * 11 + j * 3) % 190) * 0.1
            feats.append({
                "type": "Feature", "id": f"s{i}_{j}",
                "geometry": {"type": "Point",
                             "coordinates": [round(x, 3), round(y, 3)]},
                "properties": {"name": "abc"[j % 3], "v": (i + j) % 100,
                               "dtg": "2024-01-01T06:00:00"}})
        body = json.dumps({"type": "FeatureCollection",
                           "features": feats}).encode()
        out = _http(self.ports[self.primary], "/types/t/features",
                    method="POST", body=body, timeout=15.0)
        got = int(out.get("ingested", 0))
        self.acked += got
        self.rows += got
        return got

    def _wait_catchup(self, names: Optional[List[str]] = None,
                      timeout_s: Optional[float] = None) -> Optional[float]:
        """Wait until every named (live) follower reports connected with
        zero lag.  Returns elapsed seconds, or None on timeout."""
        names = [n for n in (names or self.followers) if self._alive(n)]
        t0 = time.monotonic()
        deadline = t0 + (timeout_s if timeout_s is not None else self.wait_s)
        while time.monotonic() < deadline:
            # authoritative head: a follower stalled mid-apply reports a
            # stale primary_seq, so its own lag_seqs can read 0 while it
            # is in fact far behind — always compare against the primary
            try:
                head = int((_http(self.ports[self.primary], "/healthz",
                                  timeout=2.0).get("durability")
                            or {}).get("wal_seq") or 0)
            except Exception:  # noqa: BLE001
                head = None
            ok = head is not None
            for n in names if ok else []:
                try:
                    r = _http(self.ports[n], "/healthz",
                              timeout=2.0).get("replication") or {}
                    applied = r.get("applied_seq")
                    if not r.get("connected") or applied is None \
                            or int(applied) < head:
                        ok = False
                except Exception:  # noqa: BLE001
                    ok = False
            if ok:
                return time.monotonic() - t0
            time.sleep(0.1)
        return None

    def _wait_synced(self, names: List[str], timeout_s: float = 20.0):
        """Wait for each node's WAL to report nothing unsynced, so a
        subsequent shutdown cannot drop an acked tail."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ok = True
            for n in names:
                try:
                    d = _http(self.ports[n], "/healthz",
                              timeout=2.0).get("durability") or {}
                    if d.get("enabled") and int(d.get("unsynced_bytes")
                                                or 0) > 0:
                        ok = False
                except Exception:  # noqa: BLE001
                    ok = False
            if ok:
                return True
            time.sleep(0.1)
        return False

    # -- doctor drive / phase machinery ---------------------------------------

    def _fresh(self) -> List[dict]:
        return [i for i in self.doctor.store.all()
                if i["id"] not in self._seen]

    def _open_rule(self, rule: str) -> bool:
        return any(i["rule"] == rule for i in self._fresh())

    def _all_resolved(self) -> bool:
        fresh = self._fresh()
        return bool(fresh) and all(i["status"] == "resolved" for i in fresh)

    def _drive(self, seconds: float,
               until: Optional[Callable[[], bool]] = None,
               period_s: float = 0.15) -> bool:
        deadline = time.monotonic() + seconds
        while True:
            self.doctor.evaluate()
            res = self.slo_eng.evaluate(tick=False)
            obj = res.get("fleet_count") or {}
            burns = [b for b in (obj.get("burn_rates") or {}).values()
                     if b is not None]
            if burns:
                self._phase_burn = max(self._phase_burn, max(burns))
            snap = self.fed.snapshot()
            if snap.get("partial"):
                if not self._partial_ok:
                    self._partial_violations += 1
                fslo = self.fed.slo()
                for o in fslo.values():
                    if isinstance(o, dict) and o.get("page"):
                        self._pages_while_partial += 1
            if until is not None and until():
                return True
            if time.monotonic() >= deadline:
                return until is None
            time.sleep(period_s)

    def _run_phase(self, name: str, expected_rule: Optional[str],
                   body: Callable[[], Optional[dict]]) -> dict:
        self._seen = {i["id"] for i in self.doctor.store.all()}
        self._phase_burn = 0.0
        h0 = self._hist_snapshot()
        if self.traffic is not None:
            self.traffic.set_phase(name)
        _log(f"phase {name} start")
        t0 = time.monotonic()
        extra = body() or {}
        dur = time.monotonic() - t0
        h1 = self._hist_snapshot()
        fresh = self._fresh()
        rep = {
            "name": name, "expected_rule": expected_rule,
            "duration_s": round(dur, 2),
            "fleet_p50_ms": round(hist_delta_percentile(h0[1], h1[1],
                                                        0.50), 3),
            "fleet_p99_ms": round(hist_delta_percentile(h0[1], h1[1],
                                                        0.99), 3),
            "requests": max(0, h1[0] - h0[0]),
            "burn": round(self._phase_burn, 3),
            "new_incidents": [{"id": i["id"], "rule": i["rule"],
                               "cause": i["cause"],
                               "severity": i["severity"],
                               "status": i["status"]} for i in fresh],
        }
        rep.update(extra)
        _log(f"phase {name} done in {dur:.1f}s incidents="
             f"{[i['rule'] for i in rep['new_incidents']]}")
        if expected_rule is None:
            rep["ok"] = not fresh
        else:
            rep["exactly_one"] = len(fresh) == 1
            rep["rule_correct"] = bool(fresh) and all(
                i["rule"] == expected_rule for i in fresh)
            rep["resolved"] = bool(fresh) and all(
                i["status"] == "resolved" for i in fresh)
            rep["ok"] = bool(rep["exactly_one"] and rep["rule_correct"]
                             and rep["resolved"])
        self.phases.append(rep)
        return rep

    # -- phase bodies ---------------------------------------------------------

    def _p_steady(self) -> dict:
        c0 = self._counters()
        span = self.phase_s * 1.5
        self._drive(span * 0.4)
        self._write_batch()
        self._wait_catchup(timeout_s=15.0)
        self._drive(span * 0.4)
        self._write_batch()
        self._wait_catchup(timeout_s=15.0)
        self._drive(span * 0.2)
        c1 = self._counters()
        hits = c1.get("result_cache.hits", 0) - c0.get("result_cache.hits", 0)
        miss = (c1.get("result_cache.misses", 0)
                - c0.get("result_cache.misses", 0))
        victim = self.traffic.phase_lat("steady", VICTIM_TENANT)
        self.cache = {
            "hit_rate": round(hits / (hits + miss), 4) if hits + miss else 0.0,
            "hits": hits, "misses": miss,
            "victim_tenant": VICTIM_TENANT,
            "victim_samples": len(victim),
            "victim_p99_ms": round(percentile_ms(victim, 0.99), 3),
        }
        return {"cache": self.cache}

    def _p_rolling_restart(self) -> dict:
        v = self.followers[0]
        self._partial_ok = True              # node is legitimately down
        self._signal(v, signal.SIGINT)       # graceful: a rolling restart
        for _ in range(10):
            self._write_batch(n=20)
            self._drive(0.2)
        found = self._drive(self.wait_s,
                            until=lambda: self._open_rule("replication_lag"))
        self._spawn_follower(v)
        caught = self._wait_catchup([v], timeout_s=self.wait_s)
        self._partial_ok = False
        self._drive(self.wait_s, until=self._all_resolved)
        return {"victim": v, "detected": found,
                "caught_up_s": round(caught, 2) if caught else None}

    def _p_lag_spike(self) -> dict:
        v = self.followers[0]
        delay_s = 0.3 * self.stretch
        n = max(1, int(round(8 * self.stretch)))
        _http(self.ports[v],
              f"/debug/fault?point=repl.apply&delay_s={delay_s}&n={n}",
              method="POST")
        for _ in range(10):
            self._write_batch(n=20)
        found = self._drive(self.wait_s,
                            until=lambda: self._open_rule("replication_lag"))
        t0 = time.monotonic()
        caught = self._wait_catchup(
            [v], timeout_s=max(self.wait_s, delay_s * n + 20.0))
        self.catchup_s = round(time.monotonic() - t0, 2) if caught is None \
            else round(caught, 2)
        self._drive(self.wait_s, until=self._all_resolved)
        return {"victim": v, "detected": found, "delay_s": delay_s,
                "delayed_applies": n, "catchup_s": self.catchup_s,
                "catchup_budget_s": self.catchup_budget_s,
                "within_budget": (caught is not None
                                  and self.catchup_s
                                  <= self.catchup_budget_s)}

    def _p_replica_kill(self) -> dict:
        v = self.followers[-1]
        self._partial_ok = True
        self._signal(v, signal.SIGKILL)      # crash, not a restart
        # federation-honesty block, isolated so the scrape-error count
        # is exact: M forced refreshes against a dead node must cost
        # exactly M fed.scrape_errors.<node> and flag partial+missing
        key = f"fed.scrape_errors.{v}"
        c0 = _metrics.snapshot()["counters"].get(key, 0)
        forced = 4
        for _ in range(forced):
            self.fed.refresh(force=True)
            time.sleep(0.05)
        c1 = _metrics.snapshot()["counters"].get(key, 0)
        snap = self.fed.snapshot()
        honesty = {
            "node": v, "forced_refreshes": forced,
            "scrape_errors_delta": c1 - c0,
            "scrape_errors_exact": (c1 - c0) == forced,
            "partial_during_kill": bool(snap.get("partial")),
            "missing_exact": snap.get("missing") == [v],
        }
        for _ in range(12):
            self._write_batch(n=20)
        found = self._drive(self.wait_s,
                            until=lambda: self._open_rule("replication_lag"))
        self._spawn_follower(v)
        caught = self._wait_catchup([v], timeout_s=self.wait_s)
        # once the node is back, a forced refresh must cost nothing
        c2 = _metrics.snapshot()["counters"].get(key, 0)
        self.fed.refresh(force=True)
        c3 = _metrics.snapshot()["counters"].get(key, 0)
        honesty["clean_after_respawn"] = (c3 - c2) == 0
        honesty["partial_cleared"] = not self.fed.snapshot().get("partial")
        self._partial_ok = False
        self.honesty = honesty
        self._drive(self.wait_s, until=self._all_resolved)
        return {"victim": v, "detected": found, "honesty": honesty,
                "caught_up_s": round(caught, 2) if caught else None}

    def _p_failover(self) -> dict:
        old = self.primary
        self._wait_catchup(timeout_s=self.wait_s)
        expected = self.rows
        self._partial_ok = True
        self._signal(old, signal.SIGKILL)
        new_ship = _free_port()
        res = _http(self.router_port, f"/promote?port={new_ship}",
                    method="POST", timeout=60.0)
        promoted = res["promoted"]
        self.failover = {
            "old_primary": old, "promoted": promoted,
            "duration_ms": float(res["duration_ms"]),
            "budget_ms": float(res["budget_ms"]),
            "within_budget": bool(res["within_budget"]),
        }
        addr = (res.get("result") or {}).get("address") or ""
        self.ship_ports[promoted] = int(addr.rsplit(":", 1)[1]) \
            if ":" in addr else new_ship
        self.primary = promoted
        self.followers = [n for n in self.followers if n != promoted]
        self.notes.append(f"{old} killed; {promoted} promoted "
                          f"(dir {old} excluded from exit fingerprints)")
        # conservation at the moment of failover: every acked write must
        # already be on the promoted node
        cnt = int(_http(self.ports[promoted],
                        "/types/t/count", timeout=30.0)["count"])
        self.failover["count_at_promote"] = cnt
        self.failover["expected"] = expected
        self.failover["no_acked_loss"] = cnt == expected
        # re-point the observability plane at the surviving fleet
        self.fed = self._mk_federator()
        self.fv.retarget(self.fed, self.primary, self.followers)
        self._partial_ok = False
        # the stale follower still points at the dead primary's shipper:
        # writes to the NEW primary grow its backlog until re-pointed
        for _ in range(12):
            self._write_batch(n=20)
        found = self._drive(self.wait_s,
                            until=lambda: self._open_rule("replication_lag"))
        stale = self.followers[0]
        self._partial_ok = True              # restart window: node down
        self._signal(stale, signal.SIGINT)
        self._spawn_follower(stale)          # follows the new ship port
        caught = self._wait_catchup([stale],
                                    timeout_s=self.catchup_budget_s * 2)
        self._partial_ok = False
        self.failover["stale_follower"] = stale
        self.failover["repoint_catchup_s"] = round(caught, 2) if caught \
            else None
        self._drive(self.wait_s, until=self._all_resolved)
        return {"failover": self.failover, "detected": found}

    def _p_reindex_churn(self) -> dict:
        p = self.primary
        port = self.ports[p]
        c0 = self._counters()
        _http(port, "/types/t/reindex", method="POST")
        aborts = 0
        deadline = time.monotonic() + self.wait_s
        while aborts < 2 and time.monotonic() < deadline:
            self._write_batch(n=20)
            _http(port, "/types/t/flush", method="POST", timeout=15.0)
            st = _http(port, "/types/t/reindex")
            if not st.get("running") and st.get("state") != "installed":
                _http(port, "/types/t/reindex", method="POST")
            time.sleep(0.06)
            aborts = (self._counters().get("reindex.aborts", 0)
                      - c0.get("reindex.aborts", 0))
        found = self._drive(self.wait_s,
                            until=lambda: self._open_rule("reindex_churn"))
        # let one build land clean (no concurrent flushes)
        deadline = time.monotonic() + self.wait_s
        while time.monotonic() < deadline:
            st = _http(port, "/types/t/reindex")
            if st.get("state") == "installed" and not st.get("running"):
                break
            if not st.get("running"):
                _http(port, "/types/t/reindex", method="POST")
            time.sleep(0.2)
        self._wait_catchup(timeout_s=self.wait_s)
        self._drive(self.wait_s, until=self._all_resolved)
        return {"aborts": int(aborts), "detected": found,
                "installed": st.get("state") == "installed"}

    def _p_recovery(self) -> dict:
        self._drive(self.phase_s)
        self._write_batch()
        caught = self._wait_catchup(timeout_s=self.wait_s)
        self._drive(self.phase_s * 0.5)
        return {"caught_up_s": round(caught, 2) if caught else None}

    # -- clean-half bodies (same traffic, no chaos) ---------------------------

    def _p_clean_writes(self) -> dict:
        for _ in range(6):
            self._write_batch(n=20)
            self._wait_catchup(timeout_s=15.0)
            self._drive(0.4)
        return {}

    def _p_clean_reindex(self) -> dict:
        port = self.ports[self.primary]
        _http(port, "/types/t/reindex", method="POST")
        deadline = time.monotonic() + self.wait_s
        st = {}
        while time.monotonic() < deadline:
            st = _http(port, "/types/t/reindex")
            if not st.get("running") and st.get("state") in ("installed",
                                                             "aborted"):
                break
            self._drive(0.2)
        self._wait_catchup(timeout_s=self.wait_s)
        return {"state": st.get("state")}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        _log(f"spawning fleet under {self.base}")
        self._spawn_primary()
        for n in self.followers:
            self._spawn_follower(n)
        self._wait_catchup(timeout_s=self.wait_s)
        self._spawn_router()
        _log("fleet up; calibrating SLO threshold")
        self._mk_doctor()
        _log(f"threshold_ms={self.threshold_ms:.1f}")
        self.traffic = _Traffic(self.router_port)
        self.traffic.start()
        # let the merge surfaces warm so phase-0 deltas are meaningful
        self._drive(1.0)

    def _shutdown(self) -> None:
        if self.traffic is not None:
            self.traffic.stop()
        live = [n for n in [self.primary, *self.followers]
                if self._alive(n)]
        self._wait_catchup(timeout_s=self.wait_s)
        _log("quiesced; waiting WAL sync")
        self._wait_synced(live)
        # SIGINT → KeyboardInterrupt → graceful close paths (the replica
        # CLI closes its Follower; the primary's batch syncer has
        # already fsynced everything after the quiesce above)
        for n in list(self.procs):
            self._signal(n, signal.SIGINT)

    def _conservation(self) -> dict:
        from geomesa_tpu.replication.drills import fingerprint_dir
        out = {"expected_rows": self.rows, "acked_ingests": self.acked}
        try:
            out["final_count"] = int(_http(self.ports[self.primary],
                                           "/types/t/count",
                                           timeout=30.0)["count"])
        except Exception as e:  # noqa: BLE001
            out["final_count"] = -1
            out["count_error"] = str(e)
        out["loss"] = out["expected_rows"] - out["final_count"]
        self._shutdown()
        prints = {}
        for n in [self.primary, *self.followers]:
            try:
                prints[n] = fingerprint_dir(self.dirs[n])
            except Exception as e:  # noqa: BLE001
                prints[n] = {"error": str(e)}
        vals = list(prints.values())
        out["fingerprints"] = prints
        out["fingerprints_matched"] = (len(vals) > 1
                                       and all(v == vals[0] for v in vals)
                                       and "error" not in vals[0])
        return out

    def run(self) -> dict:
        t_start = time.time()
        knobs = [
            (config.DOCTOR_WINDOW_S, 8.0),
            (config.DOCTOR_LAG_MS, 1e12),        # seqs-only: deterministic
            (config.DOCTOR_LAG_SEQS, 4.0),
            (config.DOCTOR_RECOMPILES_PER_MIN, 1e12),
            (config.DOCTOR_SHED_PER_MIN, 1e12),
            (config.DOCTOR_BREAKER_FLAPS, 1e12),
            (config.DOCTOR_FSYNC_ERRORS, 1e12),
            (config.DOCTOR_SKEW_MIN, 1e12),
            (config.DOCTOR_CLEAR_TICKS, 2),
            (config.DOCTOR_REINDEX_PER_MIN, 3.0),
            # forced flushes during the churn phase legitimately breach
            # the merge fraction; only the abort signal is under test
            (config.DOCTOR_MERGE_BREACHES_PER_MIN, 0.0),
        ]
        saved = [(p, p._override) for p, _ in knobs]
        try:
            for p, v in knobs:
                p.set(v)
            self.start()
            if self.faulted:
                self._run_phase("steady", None, self._p_steady)
                self._run_phase("rolling_restart", "replication_lag",
                                self._p_rolling_restart)
                self._run_phase("lag_spike", "replication_lag",
                                self._p_lag_spike)
                self._run_phase("replica_kill", "replication_lag",
                                self._p_replica_kill)
                self._run_phase("failover", "replication_lag",
                                self._p_failover)
                self._run_phase("reindex_churn", "reindex_churn",
                                self._p_reindex_churn)
                self._run_phase("recovery", None, self._p_recovery)
            else:
                self._run_phase("steady", None, self._p_steady)
                self._run_phase("writes", None, self._p_clean_writes)
                self._run_phase("reindex", None, self._p_clean_reindex)
                self._run_phase("recovery", None, self._p_recovery)
            conservation = self._conservation()
        finally:
            if self.traffic is not None and self.traffic.is_alive():
                self.traffic.stop()
            for n, p in self.procs.items():
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        pass
            for p, old in saved:
                if old is None:
                    p.unset()
                else:
                    p.set(old)
            art = os.environ.get("GEOMESA_TPU_SOAK_ARTIFACT")
            if art:
                mode = "faulted" if self.faulted else "clean"
                src = os.path.join(self.base, "fleet_doctor.jsonl")
                if os.path.exists(src):
                    shutil.copyfile(src, f"{art}.fleet.{mode}.jsonl")
        doctor_score = score_phases(self.phases)
        fault_burns = [p["burn"] for p in self.phases
                       if p.get("expected_rule")]
        report = {
            "mode": "chaos" if self.faulted else "clean",
            "mini": self.mini,
            "stretch": self.stretch,
            "duration_s": round(time.time() - t_start, 1),
            "threshold_ms": round(self.threshold_ms, 1),
            "phases": self.phases,
            "doctor": doctor_score,
            "slo": {"worst_fault_phase_burn": round(max(fault_burns,
                                                        default=0.0), 3),
                    "overall_worst_burn": round(max(
                        (p["burn"] for p in self.phases), default=0.0), 3),
                    "partial_outside_fault_windows":
                        self._partial_violations,
                    "pages_while_partial": self._pages_while_partial},
            "failover": self.failover,
            "catchup_s": self.catchup_s,
            "honesty": self.honesty,
            "cache": self.cache,
            "conservation": conservation,
            "traffic": {"requests": self.traffic.sent if self.traffic
                        else 0,
                        "errors": self.traffic.errors if self.traffic
                        else 0},
            "notes": self.notes,
        }
        checks = [doctor_score["precision"] == 1.0,
                  doctor_score["recall"] == 1.0,
                  conservation["loss"] == 0,
                  conservation["fingerprints_matched"],
                  self._partial_violations == 0,
                  self._pages_while_partial == 0]
        if self.faulted:
            h = self.honesty or {}
            checks += [bool(h.get("scrape_errors_exact")),
                       bool(h.get("partial_during_kill")),
                       bool(h.get("missing_exact")),
                       bool(h.get("partial_cleared")),
                       bool((self.failover or {}).get("no_acked_loss"))]
            if self.stretch == 1.0:
                checks += [bool((self.failover or {}).get("within_budget"))]
        else:
            checks += [doctor_score["incidents_total"] == 0]
        report["ok"] = all(checks)
        return report


# -- entry points -------------------------------------------------------------


def run_fleet_soak(base_dir: Optional[str] = None, faulted: bool = True,
                   mini: bool = True,
                   stretch: Optional[float] = None) -> dict:
    """Run one soak half, managing a scratch dir when none is given."""
    tmp = None
    if base_dir is None:
        tmp = tempfile.mkdtemp(prefix="geomesa-soakfleet-")
        base_dir = tmp
    try:
        return FleetSoak(base_dir, faulted=faulted, mini=mini,
                         stretch=stretch).run()
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def scoreboard_metrics(board: dict) -> dict:
    """Flatten the scoreboard into the numeric cfg11 metrics that fold
    into perf/baselines.json (names carry perfwatch direction
    suffixes; exact-match metrics are pinned in perfwatch._OVERRIDES)."""
    m: Dict[str, float] = {}
    ch = (board.get("halves") or {}).get("chaos")
    cl = (board.get("halves") or {}).get("clean")
    if ch:
        steady = next((p for p in ch["phases"] if p["name"] == "steady"),
                      None)
        if steady:
            m["cfg11_steady_fleet_p50_ms"] = steady["fleet_p50_ms"]
            m["cfg11_steady_fleet_p99_ms"] = steady["fleet_p99_ms"]
        if ch.get("failover"):
            m["cfg11_failover_ms"] = ch["failover"]["duration_ms"]
        if ch.get("catchup_s") is not None:
            m["cfg11_catchup_s"] = ch["catchup_s"]
        m["cfg11_worst_phase_burn_rate"] = \
            ch["slo"]["worst_fault_phase_burn"]
        m["cfg11_doctor_precision"] = ch["doctor"]["precision"]
        m["cfg11_doctor_recall"] = ch["doctor"]["recall"]
        m["cfg11_acked_write_loss"] = ch["conservation"]["loss"]
        m["cfg11_fingerprints_matched"] = int(
            ch["conservation"]["fingerprints_matched"]
            and (cl is None or cl["conservation"]["fingerprints_matched"]))
        if ch.get("cache"):
            m["cfg11_storm_cache_hit_rate"] = ch["cache"]["hit_rate"]
            m["cfg11_storm_victim_p99_ms"] = ch["cache"]["victim_p99_ms"]
    if cl:
        p99s = [p["fleet_p99_ms"] for p in cl["phases"]
                if p.get("requests")]
        if p99s:
            m["cfg11_clean_fleet_p99_ms"] = max(p99s)
        m["cfg11_clean_incidents"] = cl["doctor"]["incidents_total"]
    return m


def render_scoreboard(board: dict) -> str:
    """Markdown rendering of a scoreboard (written next to the JSON)."""
    lines = ["# Fleet soak scoreboard", ""]
    lines.append(f"- mini: {board.get('mini')}  ok: **{board.get('ok')}**")
    for mode, half in (board.get("halves") or {}).items():
        lines += ["", f"## {mode} half "
                      f"({'PASS' if half.get('ok') else 'FAIL'}, "
                      f"{half.get('duration_s')}s)", ""]
        lines.append("| phase | expected | incidents | p50 ms | p99 ms "
                     "| burn | ok |")
        lines.append("|---|---|---|---|---|---|---|")
        for p in half.get("phases", []):
            rules = ", ".join(i["rule"] for i in p["new_incidents"]) or "-"
            lines.append(
                f"| {p['name']} | {p.get('expected_rule') or '-'} "
                f"| {rules} | {p['fleet_p50_ms']} | {p['fleet_p99_ms']} "
                f"| {p['burn']} | {'yes' if p.get('ok') else 'NO'} |")
        d = half.get("doctor") or {}
        lines.append("")
        lines.append(f"- doctor precision **{d.get('precision')}** / "
                     f"recall **{d.get('recall')}** "
                     f"({d.get('correct')}/{d.get('incidents_total')} "
                     f"incidents correct, "
                     f"{d.get('detected')}/{d.get('fault_phases')} faults "
                     f"detected)")
        fo = half.get("failover")
        if fo:
            lines.append(
                f"- failover: {fo['old_primary']} → {fo['promoted']} in "
                f"{fo['duration_ms']}ms (budget {fo['budget_ms']}ms, "
                f"within: {fo['within_budget']}; acked rows at promote "
                f"{fo['count_at_promote']}/{fo['expected']})")
        if half.get("catchup_s") is not None:
            lines.append(f"- lag-spike catch-up: {half['catchup_s']}s")
        hon = half.get("honesty")
        if hon:
            lines.append(
                f"- federation honesty ({hon['node']} killed): "
                f"scrape_errors {hon['scrape_errors_delta']}/"
                f"{hon['forced_refreshes']} exact="
                f"{hon['scrape_errors_exact']}, partial="
                f"{hon['partial_during_kill']}, missing_exact="
                f"{hon['missing_exact']}, cleared="
                f"{hon['partial_cleared']}")
        cache = half.get("cache")
        if cache:
            lines.append(
                f"- storm cache hit-rate {cache['hit_rate']} "
                f"({cache['hits']}h/{cache['misses']}m); victim "
                f"{cache['victim_tenant']} p99 {cache['victim_p99_ms']}ms "
                f"over {cache['victim_samples']} samples")
        cons = half.get("conservation") or {}
        lines.append(
            f"- conservation: {cons.get('final_count')}/"
            f"{cons.get('expected_rows')} rows (loss {cons.get('loss')}), "
            f"fingerprints_matched={cons.get('fingerprints_matched')}")
    metrics = board.get("metrics") or {}
    if metrics:
        lines += ["", "## cfg11 gate metrics", ""]
        lines.append("| metric | value |")
        lines.append("|---|---|")
        for k in sorted(metrics):
            lines.append(f"| {k} | {metrics[k]} |")
    return "\n".join(lines) + "\n"


def run(mini: bool = True, scoreboard_path: Optional[str] = None,
        base_dir: Optional[str] = None,
        halves: tuple = ("chaos", "clean"),
        stretch: Optional[float] = None) -> dict:
    """Run the full soak (chaos + clean halves), write the scoreboard
    JSON + markdown, and remember it for GET /fleet/soak."""
    global LAST
    scoreboard_path = scoreboard_path or os.environ.get(
        "GEOMESA_TPU_SOAK_SCOREBOARD", SCOREBOARD_DEFAULT)
    board: dict = {"schema": 1, "mini": mini, "halves": {},
                   "generated": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())}
    for half in halves:
        board["halves"][half] = run_fleet_soak(
            base_dir=os.path.join(base_dir, half) if base_dir else None,
            faulted=(half == "chaos"), mini=mini, stretch=stretch)
    board["metrics"] = scoreboard_metrics(board)
    board["ok"] = all(h.get("ok") for h in board["halves"].values())
    with open(scoreboard_path, "w", encoding="utf-8") as f:
        json.dump(board, f, indent=2, sort_keys=True)
    md_path = os.path.splitext(scoreboard_path)[0] + ".md"
    with open(md_path, "w", encoding="utf-8") as f:
        f.write(render_scoreboard(board))
    LAST = board
    return board
