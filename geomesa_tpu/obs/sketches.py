"""Mergeable streaming sketches for workload analytics.

Two primitives back obs/workload.py's heavy-hitter surfaces:

  SpaceSaving   the Metwally/Agrawal/El Abbadi stream-summary: a fixed
                budget of counters tracks the heavy hitters of an
                unbounded key stream. Every tracked key carries an
                OVERESTIMATE of its true count plus an explicit error
                bound: true <= estimate and estimate - error <= true.
                Sketches MERGE like histograms (counter sums + error
                propagation, commutative), so per-node sketches fold
                into one fleet-wide top-k through the Federator exactly
                the way bucket histograms do.

  cell_key()    coarse Morton/Z-prefix spatial cells: a query's bbox
                center quantized onto a 2^bits x 2^bits lon/lat grid and
                bit-interleaved in the same x-least-significant layout
                as curves/zorder.py's Z2 keys (a cell IS a z2 prefix at
                reduced resolution). SpaceSaving over cell keys is the
                hot-cell grid — a spatial heatmap of query LOAD, not of
                the data.

Import discipline (obs/__init__ rule): stdlib only — no planner /
scheduler / datastore imports, not even curves/ (the interleave is ~10
lines; tests assert it agrees with curves.zorder.z2_encode).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class SpaceSaving:
    """Fixed-capacity heavy-hitter summary over (key -> count) streams.

    ``offer(key, n)`` admits a key by evicting the minimum counter and
    inheriting its value as the new key's error bound — the classic
    stream-summary update. Guarantees for every tracked key:

        true_count <= estimate            (never an undercount)
        estimate - error <= true_count    (the bound is explicit)

    and any key with true_count > n_total/capacity is guaranteed tracked.
    """

    __slots__ = ("capacity", "n_total", "_counts", "_errors")

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self.n_total = 0                       # total weight offered
        self._counts: Dict[str, int] = {}
        self._errors: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, count: int = 1) -> None:
        if count <= 0:
            return
        self.n_total += count
        c = self._counts.get(key)
        if c is not None:
            self._counts[key] = c + count
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = count
            self._errors[key] = 0
            return
        # evict the minimum counter; the newcomer inherits its value as
        # the overestimate/error (ties break on key for determinism)
        mkey = min(self._counts, key=lambda k: (self._counts[k], k))
        m = self._counts.pop(mkey)
        self._errors.pop(mkey, None)
        self._counts[key] = m + count
        self._errors[key] = m

    def estimate(self, key: str) -> int:
        return self._counts.get(key, 0)

    def error(self, key: str) -> int:
        return self._errors.get(key, 0)

    def min_count(self) -> int:
        """The floor below which an UNTRACKED key's true count must lie
        (0 while the summary still has free slots)."""
        if len(self._counts) < self.capacity:
            return 0
        return min(self._counts.values()) if self._counts else 0

    def top(self, k: int) -> List[Tuple[str, int, int]]:
        """Top-k ``(key, estimate, error)`` by estimate, deterministic
        tie-break on key."""
        items = sorted(self._counts.items(),
                       key=lambda kv: (-kv[1], kv[0]))[: max(0, int(k))]
        return [(key, c, self._errors.get(key, 0)) for key, c in items]

    # -- merge / serialization ------------------------------------------------

    def to_state(self) -> dict:
        """Wire form for /metrics?format=state federation (sorted so two
        equal sketches serialize identically)."""
        return {"capacity": self.capacity, "n_total": self.n_total,
                "items": {k: [self._counts[k], self._errors.get(k, 0)]
                          for k in sorted(self._counts)}}

    @classmethod
    def from_state(cls, state: dict) -> "SpaceSaving":
        s = cls(int(state.get("capacity", 1)))
        s.n_total = int(state.get("n_total", 0))
        for k, (c, e) in (state.get("items") or {}).items():
            s._counts[k] = int(c)
            s._errors[k] = int(e)
        return s

    @classmethod
    def merge(cls, a: "SpaceSaving", b: "SpaceSaving") -> "SpaceSaving":
        """Commutative merge: for each key in either summary the merged
        estimate sums the per-sketch estimates, substituting a sketch's
        ``min_count`` (its maximum possible missed count) for keys it
        does not track — so the merged value is still an overestimate
        and the merged error still bounds it. Keeps the top ``capacity``
        keys by (estimate, key), which is symmetric in (a, b)."""
        cap = max(a.capacity, b.capacity)
        out = cls(cap)
        out.n_total = a.n_total + b.n_total
        amin, bmin = a.min_count(), b.min_count()
        merged: Dict[str, Tuple[int, int]] = {}
        for key in set(a._counts) | set(b._counts):
            ca, cb = a._counts.get(key), b._counts.get(key)
            est = (ca if ca is not None else amin) \
                + (cb if cb is not None else bmin)
            err = (a._errors.get(key, 0) if ca is not None else amin) \
                + (b._errors.get(key, 0) if cb is not None else bmin)
            merged[key] = (est, err)
        keep = sorted(merged.items(),
                      key=lambda kv: (-kv[1][0], kv[0]))[:cap]
        for key, (est, err) in keep:
            out._counts[key] = est
            out._errors[key] = err
        return out

    @classmethod
    def merge_all(cls, sketches: List["SpaceSaving"]) -> "SpaceSaving":
        if not sketches:
            return cls(1)
        out = sketches[0]
        for s in sketches[1:]:
            out = cls.merge(out, s)
        return out


# -- coarse Morton/Z-prefix cells ---------------------------------------------


def _spread_bits(v: int) -> int:
    """Interleave helper: bit i of ``v`` moves to bit 2i (plain-int twin
    of curves/zorder.spread2, enough bits for any cell resolution)."""
    v &= 0xFFFFFFFF
    v = (v | (v << 16)) & 0x0000FFFF0000FFFF
    v = (v | (v << 8)) & 0x00FF00FF00FF00FF
    v = (v | (v << 4)) & 0x0F0F0F0F0F0F0F0F
    v = (v | (v << 2)) & 0x3333333333333333
    v = (v | (v << 1)) & 0x5555555555555555
    return v


def _squash_bits(v: int) -> int:
    v &= 0x5555555555555555
    v = (v | (v >> 1)) & 0x3333333333333333
    v = (v | (v >> 2)) & 0x0F0F0F0F0F0F0F0F
    v = (v | (v >> 4)) & 0x00FF00FF00FF00FF
    v = (v | (v >> 8)) & 0x0000FFFF0000FFFF
    v = (v | (v >> 16)) & 0x00000000FFFFFFFF
    return v


def z_interleave(x: int, y: int) -> int:
    """x least-significant of each bit pair — the Z2 layout of
    curves/zorder.z2_encode, as plain ints."""
    return _spread_bits(x) | (_spread_bits(y) << 1)


def cell_key(xmin: float, ymin: float, xmax: float, ymax: float,
             bits: int) -> Optional[str]:
    """The coarse Morton cell holding a query bbox's CENTER on a
    ``2^bits x 2^bits`` lon/lat grid, as a stable string key
    ``b<bits>:<z hex>``. None for out-of-range/degenerate boxes."""
    bits = max(1, min(16, int(bits)))
    try:
        cx = (float(xmin) + float(xmax)) / 2.0
        cy = (float(ymin) + float(ymax)) / 2.0
    except (TypeError, ValueError):
        return None
    if not (-180.0 <= cx <= 180.0 and -90.0 <= cy <= 90.0):
        return None
    n = 1 << bits
    gx = min(n - 1, max(0, int((cx + 180.0) / 360.0 * n)))
    gy = min(n - 1, max(0, int((cy + 90.0) / 180.0 * n)))
    width = max(1, (2 * bits + 3) // 4)  # fixed hex width per resolution
    return f"b{bits}:{z_interleave(gx, gy):0{width}x}"


def cell_bbox(cell: str) -> Optional[Tuple[float, float, float, float]]:
    """Invert :func:`cell_key` → the cell's (xmin, ymin, xmax, ymax) in
    lon/lat degrees (the heatmap display surface)."""
    try:
        prefix, zhex = cell.split(":", 1)
        bits = int(prefix.lstrip("b"))
        z = int(zhex, 16)
    except (AttributeError, ValueError):
        return None
    n = 1 << bits
    gx = _squash_bits(z)
    gy = _squash_bits(z >> 1)
    dx, dy = 360.0 / n, 180.0 / n
    return (-180.0 + gx * dx, -90.0 + gy * dy,
            -180.0 + (gx + 1) * dx, -90.0 + (gy + 1) * dy)
