"""Device-level kernel profiling: XLA cost analysis, compile telemetry,
recompilation detection, and index-build phase progress.

PR 5's attribution (obs/attrib.py) charges *observed* device time to
kernels; this module adds what XLA itself knows about each kernel and —
crucially — when XLA is asked to compile the *same logical kernel again*
for a new shape. BENCH history shows why that matters:
``cfg1_index_build_s`` swings 170–495 s and cfg4 KNN regressed 472→614 ms
with no telemetry explaining either; plan-shape churn (a padded batch
tier flipping between adjacent powers of two) silently turns steady-state
serving into a compile loop, and nothing counted it.

Three instruments, all of which cost nothing on the steady-state dispatch
path (everything lands at compile/build time):

  recompile detection
      ``note_signature`` is called by ``ScanKernels._get`` on every cache
      miss, keyed by a crc32 hash of the kernel's structural signature
      (mode, primary, residual structure, box/window/capacity tiers — the
      exact key XLA compiles one program per). The FIRST signature for a
      kernel id is its cold compile; any LATER distinct signature — or a
      re-jit of an LRU-evicted one — increments ``kernels.recompiles``
      and drops a ``kernel.recompile`` wide event into the flight
      recorder carrying the triggering shape, so `debug events
      --kind kernel.recompile` answers "what shape churned?".

  cost analysis + compile telemetry
      ``kernel_probe`` wraps each freshly-jitted kernel: the first
      invocation (where XLA traces + compiles) is timed into the
      existing ``kernel.<id>.b<tier>.compile`` series (obs/attrib), then
      a second trace-only lowering feeds ``Lowered.cost_analysis()``
      into ``kernel.<id>.b<tier>.flops`` / ``.hbm_bytes`` gauges — the
      analytic cost model `debug kernels` shows next to the measured
      dispatch/wait times.

  build phase progress
      ``PROGRESS.phase(...)`` wraps the long-running index-build stages
      (encode/upload/sort, plus the mesh-parallel/incremental stages
      ``shard_sort`` / ``splitter_exchange`` / ``merge`` and the online
      reindex's ``swap_install``) with row throughput; live phases and a
      bounded history surface at ``GET /progress``, finished phases emit
      ``progress`` flight events and ``build.<phase>`` registry timers,
      and ``explain`` carries the owning index's stage breakdown.
      Background reindex runs set ``op="reindex"`` and additionally emit
      ``reindex`` flight events (build_started/aborted/installed/failed).

A deterministic fault hook (``arm_kernel_handicap``) stretches matching
kernels' device time by a factor — the regression gate's self-test
(bench.py --check must flag an injected 2x slowdown and name the kernel).
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics

_pc = time.perf_counter


def enabled() -> bool:
    return bool(config.PROFILING_ENABLED.get()
                and config.OBS_ENABLED.get())


# -- recompile detection ------------------------------------------------------


def signature_hash(key) -> str:
    """Stable short hash of a kernel's structural signature (the jit cache
    key). crc32 of the repr — not ``hash()``, so two processes agree and a
    flight event's hash can be grepped across runs."""
    return format(zlib.crc32(repr(key).encode()), "08x")


def note_signature(seen: Dict[str, set], kernel_id: str, key,
                   shape: Optional[dict] = None) -> None:
    """Record that ``kernel_id`` is being jitted for signature ``key``
    (called by ScanKernels._get on every compiled-cache miss; ``seen`` is
    the owning instance's kernel_id -> signature-hash set, so two indexes
    each compiling their own kernels never read as churn).

    First signature per kernel id = the cold compile. Anything later is a
    RECOMPILE: a new shape (plan-shape churn — the index-build-variance
    suspect) or a re-jit of an evicted signature. Both increment
    ``kernels.recompiles`` and leave the triggering shape in the flight
    recorder."""
    sig = signature_hash(key)
    sigs = seen.get(kernel_id)
    if sigs is None:
        seen[kernel_id] = {sig}
        return
    reason = "evicted" if sig in sigs else "new_shape"
    sigs.add(sig)
    _metrics.inc("kernels.recompiles")
    try:
        from geomesa_tpu.obs.flight import RECORDER
        RECORDER.record({
            "kind": "kernel.recompile",
            "kernel": kernel_id,
            "signature": sig,
            "reason": reason,
            "shape": shape or {},
            "known_signatures": len(sigs),
        })
    except Exception:
        pass  # observability must never fail the compile


# -- deterministic kernel handicap (the regression gate's fault hook) ---------

_handicap: Optional[tuple] = None  # (substring, factor)


def arm_kernel_handicap(match: str, factor: float) -> None:
    """Stretch every dispatch of kernels whose id contains ``match`` by
    ``factor`` (sleep (factor-1) x the measured call time after it). The
    deterministic injection bench.py --check's self-test uses to prove an
    in-kernel slowdown is flagged AND attributed to the right kernel.
    Applies to kernels compiled after arming."""
    global _handicap
    _handicap = (match, float(factor)) if factor and factor > 1.0 else None


def reset_kernel_handicap() -> None:
    global _handicap
    _handicap = None


def kernel_handicap() -> Optional[tuple]:
    return _handicap


# -- cost analysis + compile probe -------------------------------------------


def _record_cost_analysis(fn, args, kw, kernel_id: str, tier: int) -> None:
    """Best-effort XLA cost model for one compiled kernel: a trace-only
    lowering (no second XLA compile) feeds flops / bytes-accessed gauges
    under the kernel's attribution prefix. Backends that report nothing
    leave the gauges unset."""
    try:
        ca = fn.lower(*args, **kw).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return
        prefix = f"kernel.{kernel_id}.b{int(tier)}."
        flops = ca.get("flops")
        if flops is not None and flops >= 0:
            _metrics.set_gauge(prefix + "flops", float(flops))
        nbytes = ca.get("bytes accessed")
        if nbytes is not None and nbytes >= 0:
            _metrics.set_gauge(prefix + "hbm_bytes", float(nbytes))
    except Exception:
        pass  # cost analysis is advisory; never fail the query


def kernel_probe(fn, kernel_id: str, tier: int):
    """Wrap a freshly-jitted kernel (the profiling-enabled superset of
    obs/attrib.compile_probe): the FIRST invocation times the XLA
    trace+compile into the kernel's compile series and captures its cost
    analysis; later invocations pay one list check — plus the armed
    handicap stretch when the deterministic fault hook matches."""
    from geomesa_tpu.obs import attrib as _attrib
    state: list = []
    h = _handicap
    stretch = h[1] - 1.0 if h is not None and h[0] in kernel_id else 0.0

    def call(*args, **kw):
        if state:
            if stretch:
                t0 = _pc()
                out = fn(*args, **kw)
                import jax
                jax.block_until_ready(out)
                time.sleep(stretch * (_pc() - t0))
                return out
            return fn(*args, **kw)
        t0 = _pc()
        out = fn(*args, **kw)
        state.append(1)
        _attrib.record_compile(kernel_id, tier, _pc() - t0)
        _record_cost_analysis(fn, args, kw, kernel_id, tier)
        return out

    return call


# -- build phase progress -----------------------------------------------------


class _Phase:
    __slots__ = ("op", "phase", "type_name", "rows", "t0", "ts_ms")

    def __init__(self, op, phase, type_name, rows):
        self.op = op
        self.phase = phase
        self.type_name = type_name
        self.rows = rows
        self.t0 = _pc()
        self.ts_ms = int(time.time() * 1000)

    def to_dict(self, done_s: Optional[float] = None) -> dict:
        dt = done_s if done_s is not None else (_pc() - self.t0)
        out = {"op": self.op, "phase": self.phase, "type": self.type_name,
               "ts_ms": self.ts_ms, "rows": self.rows,
               "duration_ms": round(dt * 1000, 1),
               "done": done_s is not None}
        if self.rows and dt > 0:
            out["rows_per_s"] = round(self.rows / dt, 0)
        return out


class BuildProgress:
    """Live + recent phase registry for long-running operations (index
    builds foremost: a 100M-point build is minutes of silence without it).
    ``phase()`` is a context manager; active phases list at GET /progress
    with elapsed time and running row throughput, finished phases keep a
    bounded history, emit a ``progress`` flight event and feed a
    ``build.<phase>`` registry timer (so phase p50/p99 ride /metrics)."""

    def __init__(self, keep: int = 64):
        self._lock = threading.Lock()
        self._active: List[_Phase] = []
        self._recent: deque = deque(maxlen=keep)

    def phase(self, phase: str, rows: Optional[int] = None,
              op: str = "index_build", type_name: Optional[str] = None):
        return _PhaseCtx(self, _Phase(op, phase, type_name, rows))

    def _start(self, p: _Phase) -> None:
        with self._lock:
            self._active.append(p)

    def _finish(self, p: _Phase) -> None:
        dt = _pc() - p.t0
        with self._lock:
            try:
                self._active.remove(p)
            except ValueError:
                pass
            self._recent.append(p.to_dict(done_s=dt))
        _metrics.observe(f"build.{p.phase}", dt)
        try:
            from geomesa_tpu.obs.flight import RECORDER
            ev = dict(self._recent[-1])
            ev["kind"] = "progress"
            RECORDER.record(ev)
        except Exception:
            pass

    def recent(self, type_name: Optional[str] = None,
               limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            items = list(self._recent)
        items.reverse()
        if type_name is not None:
            items = [e for e in items if e.get("type") == type_name]
        return items[: limit] if limit is not None else items

    def snapshot(self) -> dict:
        with self._lock:
            active = [p.to_dict() for p in self._active]
            recent = list(self._recent)
        recent.reverse()
        return {"active": active, "recent": recent}

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._recent.clear()


class _PhaseCtx:
    __slots__ = ("_progress", "_phase", "_span")

    def __init__(self, progress: BuildProgress, phase: _Phase):
        self._progress = progress
        self._phase = phase

    def __enter__(self):
        from geomesa_tpu import trace as _trace
        self._progress._start(self._phase)
        # under an active trace the phase shows as a span too (a traced
        # ingest that triggers a rebuild attributes the build stages)
        self._span = _trace.span(f"build.{self._phase.phase}",
                                 kind="build_phase")
        self._span.__enter__()
        return self._phase

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        self._progress._finish(self._phase)
        return False


PROGRESS = BuildProgress()
