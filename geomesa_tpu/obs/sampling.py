"""Tail-based trace sampling: keep the traces worth reading.

Head sampling (decide at trace START) throws away exactly the traces you
end up needing — the slow outliers and the failures are invisible until
the trace closes. This sampler decides at CLOSE (the Canopy/X-Ray "tail"
discipline):

  keep always      error traces, and anything that cancelled / shed /
                   degraded (the kinds the resilience layer stamps)
  keep slow        duration over the slow threshold — a fixed
                   GEOMESA_TPU_OBS_SLOW_MS, or (at 0, the default) an
                   ADAPTIVE threshold: the rolling p99 of recent root
                   durations (decayed log-bucket histogram, so a traffic
                   shift re-learns what "slow" means within ~1k queries)
  sample the rest  probabilistically at GEOMESA_TPU_OBS_SAMPLE

Retained traces land in a dedicated ring (``SAMPLER.recent()``, web
``GET /traces?retained=1``) and their ids pass the metrics registry's
exemplar filter — so a `/metrics` histogram bucket annotates the id of a
concrete retained trace a reader can actually pull up.

Deterministic by construction: the rng is injectable (tests pin rates to
0/1 anyway) and nothing sleeps or reads wall-clock.
"""

from __future__ import annotations

import random
import threading
from collections import OrderedDict
from typing import List, Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.metrics import Histogram
from geomesa_tpu.trace import QueryTrace, TraceRing

# span kinds whose presence always retains the trace
_KEEP_KINDS = frozenset(("cancel", "shed", "degrade"))

# decay the rolling-duration histogram every N offers (halving counts) so
# the adaptive p99 tracks the RECENT distribution, not all of history
_DECAY_EVERY = 1024


class TailSampler:
    """Tail-based retention over closed root traces."""

    # pending-queue bound: past this, enqueue() drains inline so the
    # deferred decision can never hoard unbounded traces
    _PENDING_MAX = 1024

    def __init__(self, keep: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self._keep = int(keep or config.OBS_TRACE_RING.get())
        self.ring = TraceRing(keep=self._keep)
        self._rng = rng or random.Random(0x6E05A)
        self._lock = threading.Lock()
        self._durations = Histogram()
        self._since_decay = 0
        self._p99_ms = 0.0  # cached; recomputed on decay + every 32 offers
        self._since_p99 = 0
        # ids of traces currently retained, insertion-ordered and trimmed
        # alongside the ring (its deque evicts silently)
        self._retained_ids: "OrderedDict[int, str]" = OrderedDict()
        # closed traces awaiting their retention decision: the close hook
        # pays ONE list append (GIL-atomic); every reader drains first
        self._pending: List[QueryTrace] = []
        self.kept = 0
        self.seen = 0

    # -- deferred offers ------------------------------------------------------

    def enqueue(self, t: QueryTrace) -> None:
        """Hot-path entry: queue a closed trace for a lazy retention
        decision (decided at the next read — recent()/is_retained()/
        stats()/metrics drain). Bounded: past _PENDING_MAX the decision
        runs inline."""
        self._pending.append(t)
        if len(self._pending) > self._PENDING_MAX:
            self.drain()

    def drain(self) -> None:
        """Decide retention for every queued trace."""
        if not self._pending:
            return
        with self._lock:
            pending, self._pending = self._pending, []
        for t in pending:
            self.offer(t)

    # -- the decision ---------------------------------------------------------

    def _slow_threshold_ms(self) -> float:
        fixed = float(config.OBS_SLOW_MS.get())
        if fixed > 0:
            return fixed
        # adaptive: the rolling p99 (0 until enough traffic to be meaningful
        # — below 100 observations everything would be "slow", so gate)
        return self._p99_ms if self._durations.count >= 100 else float("inf")

    def offer(self, t: QueryTrace, stages: Optional[dict] = None) -> bool:
        """Decide retention for one closed root trace; returns True when
        retained (the trace landed in the sampled ring). ``stages`` is an
        optional precomputed per-kind breakdown (its keys ARE the span
        kinds) so the hot-path caller walks the span tree once, not
        twice."""
        dur_ms = t.duration_ms
        kinds = stages.keys() if stages is not None else t.kinds()
        with self._lock:
            self.seen += 1
            self._durations.observe(dur_ms / 1000.0)
            self._since_decay += 1
            self._since_p99 += 1
            if self._since_decay >= _DECAY_EVERY:
                self._since_decay = 0
                h = self._durations
                h.buckets = [c >> 1 for c in h.buckets]
                h.count = sum(h.buckets)
                h.total_s /= 2.0
                h.max_s = 0.0  # re-learned by subsequent observations
                self._since_p99 = 32  # force recompute below
            if self._since_p99 >= 32:
                self._since_p99 = 0
                self._p99_ms = self._durations.percentile(0.99) * 1000.0
            reason = None
            if t.error is not None:
                reason = "error"
            elif not _KEEP_KINDS.isdisjoint(kinds):
                reason = "outcome"
            elif getattr(t, "sampled_hint", False):
                # the propagated cross-process decision (trace.py): when
                # the upstream hop keeps its half, every downstream half
                # is kept too — a stitched fleet trace is never partial
                reason = "propagated"
            elif dur_ms >= self._slow_threshold_ms():
                reason = "slow"
            elif self._rng.random() < float(config.OBS_SAMPLE.get()):
                reason = "sampled"
            if reason is None:
                return False
            self.kept += 1
            self._retained_ids[t.trace_id] = reason
            while len(self._retained_ids) > self._keep:
                self._retained_ids.popitem(last=False)
        self.ring.append(t)
        _metrics.inc("obs.traces_retained")
        _metrics.inc(f"obs.traces_retained.{reason}")
        return True

    # -- queries --------------------------------------------------------------

    def is_retained(self, trace_id: int) -> bool:
        """Retained-ring membership. NOTE: does NOT drain (the metrics
        registry consults this under ITS lock — the registry's pre-drain
        hook runs ``drain()`` beforehand instead)."""
        with self._lock:
            return trace_id in self._retained_ids

    def recent(self, limit: Optional[int] = None) -> List[dict]:
        self.drain()
        return self.ring.recent(limit)

    def slow_threshold_ms(self) -> float:
        with self._lock:
            th = self._slow_threshold_ms()
        return -1.0 if th == float("inf") else round(th, 3)

    def stats(self) -> dict:
        self.drain()
        with self._lock:
            th = self._slow_threshold_ms()
            return {
                "seen": self.seen,
                "kept": self.kept,
                "retained": len(self._retained_ids),
                "capacity": self._keep,
                "slow_threshold_ms": -1.0 if th == float("inf")
                else round(th, 3),
                "sample_rate": float(config.OBS_SAMPLE.get()),
            }

    def clear(self) -> None:
        with self._lock:
            self._retained_ids.clear()
            self._pending = []
            self._durations = Histogram()
            self._p99_ms = 0.0
            self._since_decay = self._since_p99 = 0
            self.kept = self.seen = 0
        self.ring.clear()


# process-global sampler
SAMPLER = TailSampler()
