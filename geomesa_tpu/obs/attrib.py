"""Per-kernel device cost attribution.

The trace layer splits ``device_scan`` (dispatch) from ``device_wait``
(block_until_ready) per QUERY; this module attributes the same costs per
KERNEL — (kernel id, batch tier) — so a fleet-wide p99 regression can be
charged to the one fused kernel that got slower, not just "the device".

Attributed series (all land in metrics.REGISTRY under ``kernel.<id>.b<tier>.*``
so they ride the existing /metrics + Prometheus surfaces and
``snapshot_prefixed("kernel.")`` filtering):

  .dispatches        device dispatch count (counter)
  .device_wait       block-until-ready seconds (histogram timer → p50/p99)
  .dispatch          host-side enqueue seconds (histogram timer)
  .transfer_bytes    host→device bytes shipped for the dispatch (counter)
  .compiles          XLA compilations triggered (counter)
  .compile           compilation seconds (histogram timer)

Kernel ids are ``<mode>.<primary_kind>`` (e.g. ``count_multi_blocks.
point_boxes``); the tier is the padded batch size the dispatch shipped
(the shape XLA actually compiled for).

Wiring:

  - ``ScanKernels._get`` wraps every newly-jitted kernel in
    ``compile_probe`` → first invocation records compile count/time;
  - the scheduler measures the completer's device wait per fused batch
    directly (``record_dispatch``) and the upload bytes per group
    (``record_transfer``);
  - direct-path entry points label the ambient thread
    (``with kernel("count.point_boxes", 1): ...``) and the trace layer's
    device hook charges each ``device_fetch`` to that label.

Everything no-ops when GEOMESA_TPU_OBS is off.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics

_pc = time.perf_counter


def enabled() -> bool:
    return bool(config.OBS_ENABLED.get())


@functools.lru_cache(maxsize=4096)
def _series(kernel_id: str, tier: int, metric: str) -> str:
    # cached: the hot device hook would otherwise build 2-3 f-strings per
    # dispatch; the set of (kernel, tier, metric) names is small and stable
    return f"kernel.{kernel_id}.b{int(tier)}.{metric}"


def record_dispatch(kernel_id: str, tier: int, wait_s: float,
                    dispatch_s: float = 0.0, n: int = 1) -> None:
    """Charge one device round trip to (kernel id, batch tier)."""
    if not enabled():
        return
    _metrics.inc(_series(kernel_id, tier, "dispatches"), n)
    _metrics.observe(_series(kernel_id, tier, "device_wait"), wait_s)
    if dispatch_s > 0:
        _metrics.observe(_series(kernel_id, tier, "dispatch"), dispatch_s)


def record_transfer(kernel_id: str, tier: int, nbytes: int) -> None:
    """Charge host→device bytes (query constants, block ids, table planes)."""
    if nbytes and enabled():
        _metrics.inc(_series(kernel_id, tier, "transfer_bytes"), int(nbytes))


def record_compile(kernel_id: str, tier: int, seconds: float) -> None:
    if not enabled():
        return
    _metrics.inc(_series(kernel_id, tier, "compiles"))
    _metrics.observe(_series(kernel_id, tier, "compile"), seconds)


def compile_probe(fn, kernel_id: str, tier: int):
    """Wrap a freshly-jitted kernel: its FIRST invocation (where XLA
    traces + compiles) is timed and recorded as the kernel's compile cost;
    later invocations pass straight through (one list check)."""
    state: list = []

    def call(*args, **kw):
        if state:
            return fn(*args, **kw)
        t0 = _pc()
        out = fn(*args, **kw)
        state.append(1)
        record_compile(kernel_id, tier, _pc() - t0)
        return out

    return call


# -- ambient labeling for the direct (unscheduled) path -----------------------


class _Local(threading.local):
    label = None  # (kernel_id, tier) | None


_local = _Local()


class kernel:
    """Context manager labeling this thread's device fetches with a
    (kernel id, batch tier) — the trace layer's device hook charges each
    ``device_fetch`` inside to the label. Nesting keeps the innermost."""

    __slots__ = ("_label", "_prev")

    def __init__(self, kernel_id: str, tier: int = 1):
        self._label = (kernel_id, tier) if enabled() else None

    def __enter__(self):
        self._prev = _local.label
        if self._label is not None:
            _local.label = self._label
        return self

    def __exit__(self, *exc):
        _local.label = self._prev
        return False


# labeled fetches awaiting their registry feed: the device hook sits on the
# per-query hot path, so it pays ONE list append (GIL-atomic) and the
# histogram math happens at the next flush (registry pre-drain / reader)
_pending_fetches: list = []
_PENDING_FETCH_MAX = 4096
_flush_lock = threading.Lock()


def _on_device_fetch(dispatch_s: float, wait_s: float) -> None:
    """trace.set_device_hook slot: charge an ambient-labeled fetch. The
    enabled() gate was already paid when the label was installed; the
    registry feed is deferred (see flush)."""
    lab = _local.label
    if lab is None:
        return
    _pending_fetches.append((lab, dispatch_s, wait_s))
    if len(_pending_fetches) > _PENDING_FETCH_MAX:
        flush()


def flush() -> None:
    """Fold pending labeled fetches into the registry (wait + dispatch
    timers per (kernel id, tier); the wait histogram's count IS the
    dispatch count). Runs from the registry's pre-drain hook and any
    attribution reader."""
    if not _pending_fetches:
        return
    with _flush_lock:
        pending = _pending_fetches[:]
        # concurrent appends land past the copied prefix and survive
        del _pending_fetches[: len(pending)]
    batch = []
    for (kid, tier), dispatch_s, wait_s in pending:
        batch.append((_series(kid, tier, "device_wait"), wait_s))
        batch.append((_series(kid, tier, "dispatch"), dispatch_s))
    _metrics.observe_batch(batch)


def install() -> None:
    """Wire the device hook into the trace layer (idempotent)."""
    from geomesa_tpu import trace as _trace
    _trace.set_device_hook(_on_device_fetch)


def snapshot() -> dict:
    """The per-kernel attribution series (counters/timers under
    ``kernel.``) — the CLI/web summary feed."""
    flush()
    return _metrics.snapshot_prefixed("kernel.")


# -- explain(analyze=True) annotation ----------------------------------------


def annotate_tree(node: dict) -> float:
    """Annotate a trace-tree dict in place: each span gains ``device_ms``
    (device time in its subtree) and ``cached: False`` on plan/
    range_decompose spans (a span that RAN was, by construction, not
    served from a cache — cache hits show as ABSENT spans). Returns the
    node's subtree device ms."""
    kind = node.get("kind")
    own = node.get("self_ms", node.get("duration_ms", 0.0)) \
        if kind in ("device_scan", "device_wait") else 0.0
    dev = own + sum(annotate_tree(c) for c in node.get("children", ()))
    node["device_ms"] = round(dev, 3)
    if kind in ("plan", "range_decompose"):
        node["cached"] = False
    return dev
