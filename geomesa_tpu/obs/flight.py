"""Flight recorder: one canonical wide event per request.

The Dapper/Canopy lesson (PAPERS.md): aggregate histograms tell you THAT a
p99 regressed; only a per-request record with every dimension on one row
tells you WHICH queries paid it. Every query/count/batch emits one
structured wide event — trace id, query type, plan hash, plan/cover cache
hit flags, batch size + batch id, admission class, deadline budget vs
slack, device ms vs host ms, rows scanned/matched, shed/degrade/cancel/
breaker flags, error kind — into a bounded ring plus an optional JSONL
sink with size rotation (the shared durability/rotation.py policy).

Two producers feed it:

  - the micro-batching scheduler emits the rich event per scheduled count
    (it knows cache hits, batch membership, admission class, degradation)
    plus one ``batch`` event per fused device dispatch;
  - the trace-close hook derives an event from every other ROOT trace
    (direct counts, feature queries, explains), so the unscheduled paths
    are never dark.

Query with ``RECORDER.recent(slow_ms=..., errors=..., kind=..., ...)`` —
the same ``matches()`` predicate backs ``GET /events`` and the CLI's
``debug events`` / ``debug traces`` filters.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.obs import workload as _workload


def plan_hash(type_name: str, f_key: str, auths_key=None) -> str:
    """Stable short hash identifying a (type, normalized filter, auths)
    plan shape across events and processes (crc32 — not salted like
    ``hash()``, so two runs agree)."""
    raw = f"{type_name}|{f_key}|{auths_key}".encode()
    return format(zlib.crc32(raw), "08x")


def tenant_label(tenant=None, auths=None) -> str:
    """Canonical tenant label for workload analytics and metering: the
    explicit tenant (``?tenant=`` / ``X-Tenant`` / submit kwarg) wins;
    otherwise the FIRST sorted auth stands in (one label per principal
    group, bounded cardinality); otherwise ``default``."""
    if tenant:
        return str(tenant)[:64]
    if auths:
        return "auth:" + sorted(str(a) for a in auths)[0][:56]
    return "default"


def matches(rec: dict, slow_ms: Optional[float] = None,
            errors: bool = False, kind: Optional[str] = None,
            type_name: Optional[str] = None,
            since_ms: Optional[float] = None) -> bool:
    """The shared filter predicate over wide events AND trace dicts.

    slow_ms    keep records at least this slow (duration_ms)
    errors     keep only failed/shed/cancelled records
    kind       match the record kind / trace name, or a span kind present
               in its ``stages_ms`` breakdown
    type_name  match the feature type
    since_ms   keep records stamped at/after this wall time — the slice
               filter shared by ``GET /events``, ``debug events`` and the
               forensic-bundle capture path, so flight events line up
               with a history ``range(name, since_ms)`` window
    """
    if slow_ms is not None and float(rec.get("duration_ms") or 0.0) < slow_ms:
        return False
    if since_ms is not None and float(rec.get("ts_ms") or 0.0) < since_ms:
        return False
    if errors and not (rec.get("error") or rec.get("cancelled")
                       or rec.get("shed")):
        return False
    if kind is not None:
        stages = rec.get("stages_ms") or {}
        if kind not in (rec.get("kind"), rec.get("name")) \
                and kind not in stages:
            return False
    if type_name is not None and rec.get("type") != type_name:
        return False
    return True


class FlightRecorder:
    """Bounded ring of wide events + optional rotated JSONL sink."""

    def __init__(self, keep: Optional[int] = None,
                 jsonl_path: Optional[str] = None,
                 max_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=int(keep or config.OBS_RING.get()))
        self._jsonl_path = jsonl_path
        self._max_bytes = max_bytes
        self._fh = None
        self._fh_path = None
        self._fh_bytes = 0
        self._n_recorded = 0
        # cached sink decision for the hot record_trace path (re-read from
        # config every _SINK_REFRESH records and on every read surface, so
        # flipping GEOMESA_TPU_OBS_JSONL at runtime takes effect promptly
        # without an env read per query)
        self._sink_cached = self._sink_path() is not None
        self._sink_age = 0

    _SINK_REFRESH = 512

    # -- sink -----------------------------------------------------------------

    def _sink_path(self) -> Optional[str]:
        if self._jsonl_path is not None:
            return self._jsonl_path or None
        return config.OBS_JSONL.get() or None

    def _write_jsonl_locked(self, line: bytes) -> None:
        path = self._sink_path()
        if path is None:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            return
        try:
            if self._fh is None or self._fh_path != path:
                if self._fh is not None:
                    self._fh.close()
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._fh = open(path, "ab")
                self._fh_path = path
                self._fh_bytes = self._fh.tell()
            self._fh.write(line)
            self._fh_bytes += len(line)
            cap = int(self._max_bytes
                      if self._max_bytes is not None
                      else config.OBS_JSONL_MAX_BYTES.get())
            if cap > 0 and self._fh_bytes >= cap:
                from geomesa_tpu.durability.rotation import rotate
                self._fh.close()
                self._fh = None
                def _dropped(p):
                    _metrics.inc("obs.jsonl_dropped")
                    _metrics.inc("journal.gc")
                rotate(path,
                       keep=max(1, int(config.JOURNAL_KEEP.get())),
                       on_drop=_dropped)
        except OSError:
            # a failing sink must never fail the request (dropwizard rule)
            _metrics.inc("obs.jsonl_errors")
            self._fh = None

    # -- recording ------------------------------------------------------------

    def record(self, event: dict) -> None:
        if "ts_ms" not in event:
            event["ts_ms"] = int(time.time() * 1000)
        with self._lock:
            self._ring.append(event)
            self._n_recorded += 1
            if self._sink_path() is not None:
                self._write_jsonl_locked(
                    (json.dumps(event, default=str) + "\n").encode())
        # tee into the workload-analytics plane (one bounded append;
        # aggregation is deferred to its drain)
        _workload.WORKLOAD.offer(event)

    def record_trace(self, t) -> None:
        """Hot-path variant for the trace close hook: the ring holds the
        (already-built) QueryTrace itself and the wide event materializes
        lazily at READ time (``recent()``), with its retention flag
        resolved against the tail sampler then — trace close pays one lock
        + one deque append. With a JSONL sink configured the event must
        serialize now, so it eagerly materializes on that path only."""
        self._sink_age += 1
        if self._sink_age >= self._SINK_REFRESH:
            self._sink_age = 0
            self._sink_cached = self._sink_path() is not None
        if self._sink_cached:
            from geomesa_tpu.obs.sampling import SAMPLER
            SAMPLER.drain()
            self.record(event_from_trace(
                t, retained=SAMPLER.is_retained(t.trace_id)))
            return
        # lockless: deque appends are GIL-atomic (readers tolerate the
        # mutated-during-iteration race — see _ring_snapshot); the count
        # is advisory
        self._ring.append(t)
        self._n_recorded += 1
        # the workload plane gets the raw trace too; its wide event
        # materializes at ITS drain, same deferral as the ring's
        _workload.WORKLOAD.offer(t)

    def _ring_snapshot(self) -> list:
        """Copy the ring despite lockless concurrent appends: deque
        iteration raises RuntimeError when mutated mid-copy — retry."""
        while True:
            try:
                return list(self._ring)
            except RuntimeError:
                continue

    # -- querying -------------------------------------------------------------

    def recent(self, limit: Optional[int] = None,
               slow_ms: Optional[float] = None, errors: bool = False,
               kind: Optional[str] = None,
               type_name: Optional[str] = None,
               since_ms: Optional[float] = None) -> List[dict]:
        """Most-recent-first events passing the shared filter predicate."""
        from geomesa_tpu.obs.sampling import SAMPLER
        SAMPLER.drain()  # settle retention before resolving lazy entries
        self._sink_cached = self._sink_path() is not None
        items = self._ring_snapshot()
        items.reverse()
        out = []
        for e in items:
            if not isinstance(e, dict):  # lazily-recorded trace entry
                e = event_from_trace(
                    e, retained=SAMPLER.is_retained(e.trace_id))
            if matches(e, slow_ms=slow_ms, errors=errors, kind=kind,
                       type_name=type_name, since_ms=since_ms):
                out.append(e)
        if limit is not None:
            out = out[: max(0, int(limit))]
        return out

    def clear(self) -> None:
        self._sink_cached = self._sink_path() is not None
        self._sink_age = 0
        with self._lock:
            self._ring.clear()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {"depth": len(self._ring), "capacity": self._ring.maxlen,
                    "recorded": self._n_recorded,
                    "jsonl": self._sink_path(),
                    "jsonl_bytes": self._fh_bytes if self._fh else 0}


# process-global recorder (the serving shape: one recorder per process)
RECORDER = FlightRecorder()


# error type -> the wide-event error kind (matches the web envelope kinds)
_ERR_KINDS = {"DeadlineExceeded": "deadline", "ShedError": "shed",
              "CircuitOpenError": "breaker_open",
              "SchedulerCrashed": "crash", "SchedulerShutdown": "shutdown",
              "QueryGuardError": "guard", "QueryTimeout": "deadline"}


def error_kind(e: BaseException) -> str:
    return _ERR_KINDS.get(type(e).__name__, type(e).__name__)


def event_from_request(req, fut) -> dict:
    """The rich wide event for one scheduled request (serve/scheduler.py
    attaches this as a future done-callback — it fires on EVERY resolution
    path: result, degradation, cancellation, shed, crash sweep)."""
    import time as _time
    err = None
    rows = None
    if fut.cancelled():
        err = "cancelled"
    else:
        e = fut.exception()
        if e is not None:
            err = error_kind(e)
        else:
            try:
                rows = int(fut.result())
            except Exception:
                pass

    def ms(seconds):
        return None if seconds is None else round(seconds * 1000.0, 3)

    from geomesa_tpu import trace as _trace
    from geomesa_tpu.cluster.runtime import event_dims as _cluster_dims
    return {
        **_cluster_dims(),
        "kind": "count.scheduled",
        "type": req.type_name,
        "trace_id": req.trace_id,
        "trace_gid": req.trace_gid,
        "node_id": _trace.node_id(),
        "role": _trace.node_role(),
        "parent_span": req.parent_span,
        "plan_hash": plan_hash(req.type_name, req.f_key, req.auths_key),
        "duration_ms": round(
            (_time.perf_counter() - req.t_submit) * 1000.0, 3),
        "queue_wait_ms": ms(req.queue_wait_s),
        "plan_cache_hit": req.plan_cache_hit,
        "cover_cache_hit": req.cover_cache_hit,
        # provenance: "result" = served from the hot-result cache with NO
        # device round trip (device_ms stays zero; workload device-time
        # accounting must not re-bill the original dispatch)
        "cache": "result" if getattr(req, "result_cache_hit", None) else None,
        "batched": req.batched,
        "batch_size": req.batch_size,
        "batch_id": req.batch_id,
        "priority": req.priority,
        "tenant": req.tenant,
        "cell": req.cell,
        "funcs": list(getattr(req, "funcs", ()) or ()) or None,
        "deadline_budget_ms": req.budget_ms,
        "deadline_slack_ms": None if req.deadline is None
        else round(req.deadline.remaining_ms(), 3),
        "scan_ms": ms(req.scan_s),
        # batched scan time IS the fused device round trip; singles carry
        # their device split in the trace / kernel attribution instead
        "device_ms": ms(req.scan_s) if req.batched else None,
        "host_ms": ms((req.plan_s or 0.0) + (req.queue_wait_s or 0.0)),
        "rows_scanned": req.rows_scanned,
        "rows_matched": rows,
        "retries": req.retries,
        "cancelled": req.cancelled,
        "degraded": req.degraded,
        "shed": req.shed,
        "breaker_open": req.breaker_open,
        "error": err,
    }


def request_callback(req):
    """Done-callback emitting the request's wide event (guarded: a failing
    recorder must never poison future resolution)."""
    def _cb(fut):
        try:
            if config.OBS_ENABLED.get():
                RECORDER.record(event_from_request(req, fut))
        except Exception:
            pass
    return _cb


def event_from_trace(t, retained: bool = False,
                     stages: Optional[dict] = None) -> dict:
    """Derive a wide event from a closed root QueryTrace (the unscheduled
    paths: direct counts, feature queries, explain). ``stages`` is an
    optional precomputed per-kind self-time breakdown (the close hook
    shares one span walk between sampling and this)."""
    from geomesa_tpu import trace as _trace
    from geomesa_tpu.cluster.runtime import event_dims as _cluster_dims
    if stages is None:
        stages = t.self_times_ms()
    device_ms = stages.get("device_scan", 0.0) + stages.get("device_wait", 0.0)
    attrs = t.root.attrs or {}
    f = attrs.get("filter")
    parent = getattr(t, "parent", None)
    ev = {
        **_cluster_dims(),
        "ts_ms": t.ts_ms,
        "kind": t.name,
        "type": attrs.get("type"),
        "trace_id": t.trace_id,
        "trace_gid": t.global_id,
        "node_id": _trace.node_id(),
        "role": _trace.node_role(),
        "parent_span": parent.span_id if parent is not None else None,
        "parent_node": parent.node if parent is not None else None,
        "retained": bool(retained),
        "duration_ms": round(t.duration_ms, 3),
        "device_ms": round(device_ms, 3),
        "host_ms": round(max(0.0, t.duration_ms - device_ms), 3),
        "stages_ms": {k: round(v, 3) for k, v in stages.items()},
        "cancelled": "cancel" in stages,
        "degraded": "degrade" in stages,
        "shed": "shed" in stages,
        "error": t.error,
    }
    if f is not None:
        ev["plan_hash"] = plan_hash(str(attrs.get("type")), str(f))
    if attrs.get("tenant") is not None:
        ev["tenant"] = attrs.get("tenant")
    return ev
