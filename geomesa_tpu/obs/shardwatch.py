"""Shard balance observatory: the per-shard load ledger (ISSUE 16).

PR 15 partitioned the feature table by contiguous Morton key range but
left the cluster plane blind to WHERE the load lands. This module closes
that loop observationally — the prerequisite signal for ROADMAP item
2's split/merge/migrate plane — by joining two surfaces that already
speak the same Z2 key space:

  workload plane   ``hot_set()`` top Morton cells with SpaceSaving
                   confidence bounds (``count`` never undercounts,
                   ``at_least = count - error`` never overcounts);

  cluster plane    per-process ``key_ranges`` ownership plus an
                   EMPIRICAL cell -> shard occupancy map (which shard
                   holds how many rows of each coarse cell, measured at
                   table-build time by cluster/table.py shard_cell_map).

The join attributes each hot cell's load to the shards that own its
rows, FRACTIONALLY by row share — cells that straddle an ownership
boundary split their load honestly instead of being forced to one side.
Per shard the ledger reports qps / rows-scanned / device-ms / hot-cell
load shares; the imbalance score is the max-over-mean per-shard load
ratio plus the top-cell concentration. Doctor bars use the GUARANTEED
(at_least-based) loads, so sketch error can never fake an imbalance.

``project_splits`` turns the hottest shard's owned cells into candidate
boundary keys that partition its observed load into near-equal parts —
exactly the split points the elasticity PR will consume. Boundaries
always fall inside the victim's key range; the property test pins the
partition tolerance to the largest single-cell share (a cell is the
atomic unit — no boundary can do better than the cell granularity).

Rows-scanned / device-ms per cell come from a workload drain hook
(``workload.add_fold_hook``): the hot path still pays one deque append,
and the per-cell accumulator folds at read time under the workload
drain, same deferred discipline as every obs surface.

Federation: ``export_state()`` rides the /metrics?format=state scrape
next to the workload state; ``merge_states`` sums per-cell stats and
unions the (rank-identical) shard maps, backing GET /fleet/balance.

Import discipline (obs/__init__ rule): config/metrics + obs.sketches/
obs.workload only — never cluster/planner/datastore layers. The shard
map is PUSHED in by the cluster plane (set_shard_map), not pulled.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu.obs import sketches as _sk
from geomesa_tpu.obs import workload as _workload


def project_splits(cells: List[dict], key_range: Tuple[int, int],
                   parts: int = 2) -> List[dict]:
    """Candidate split boundaries for ONE shard from its owned hot-cell
    slices.

    ``cells`` entries carry ``load`` (this shard's share of the cell)
    and the shard-local key span ``key_lo``/``key_hi`` of the cell's
    rows. Boundaries are key values B such that rows with key < B land
    left; each targets cumulative load ``j/parts`` and lands within the
    largest single-cell share of it (cells are atomic — a split cannot
    cut finer than the cell granularity). Every boundary falls inside
    ``(key_lo, key_hi]`` of the victim's range."""
    lo, hi = int(key_range[0]), int(key_range[1])
    parts = max(2, int(parts))
    usable = [c for c in cells if float(c.get("load") or 0.0) > 0.0]
    total = sum(float(c["load"]) for c in usable)
    if not usable or total <= 0.0 or hi <= lo:
        return []
    order = sorted(usable, key=lambda c: ((int(c["key_lo"])
                                           + int(c["key_hi"])) / 2.0,
                                          str(c.get("cell"))))
    out: List[dict] = []
    cum = 0.0
    targets = [(j, total * j / parts) for j in range(1, parts)]
    ti = 0
    for i, c in enumerate(order):
        cum += float(c["load"])
        while ti < len(targets) and cum >= targets[ti][1] - 1e-12:
            j, _ = targets[ti]
            key = max(lo + 1, min(hi, int(c["key_hi"]) + 1))
            out.append({"key": key,
                        "left_fraction": round(cum / total, 6),
                        "target": round(j / parts, 6),
                        "cells_left": i + 1,
                        "cell": c.get("cell")})
            ti += 1
        if ti >= len(targets):
            break
    return out


class ShardWatch:
    """Per-shard load ledger (one per process, like the Federator).

    The cluster plane pushes the cell -> shard occupancy map in at
    table-build time (``set_shard_map``); a workload drain hook feeds
    per-cell rows-scanned / device-ms; ``balance()`` performs the join
    on demand."""

    def __init__(self, workload=None):
        self._lock = threading.Lock()
        self._workload = workload       # None -> process-global WORKLOAD
        # type -> {"cells": {cell: {shard: {"rows","key_lo","key_hi"}}},
        #          "key_ranges": {shard: [lo, hi]},
        #          "shard_rows": {shard: rows}}
        self._maps: Dict[str, dict] = {}
        # cell -> [events, rows_scanned, device_ms] (drain-hook fed)
        self._cells: Dict[str, list] = {}
        self._cell_drops = 0
        self._t0: Optional[float] = None

    def _wl(self):
        return self._workload if self._workload is not None \
            else _workload.WORKLOAD

    # -- cluster-plane input ----------------------------------------------------

    def set_shard_map(self, type_name: str, cells: Dict[str, dict],
                      key_ranges, shard_rows=None) -> None:
        """Install the empirical ownership map for one table type.

        ``cells[cell][shard]`` -> {"rows", "key_lo", "key_hi"} (that
        shard's row count and key span inside the cell); ``key_ranges``
        is per-shard [lo, hi] (dict keyed by shard, or a rank-ordered
        list). Shard ids normalize to strings for JSON stability."""
        if isinstance(key_ranges, (list, tuple)):
            key_ranges = {str(i): list(r)
                          for i, r in enumerate(key_ranges)}
        norm_cells = {}
        for cell, owners in (cells or {}).items():
            norm_cells[str(cell)] = {
                str(s): {"rows": int(o["rows"]),
                         "key_lo": int(o["key_lo"]),
                         "key_hi": int(o["key_hi"])}
                for s, o in owners.items()}
        smap = {"cells": norm_cells,
                "key_ranges": {str(s): [int(r[0]), int(r[1])]
                               for s, r in (key_ranges or {}).items()},
                "shard_rows": {str(s): int(n)
                               for s, n in (shard_rows or {}).items()}}
        with self._lock:
            self._maps[str(type_name)] = smap

    # -- workload drain hook ----------------------------------------------------

    def fold_event(self, ev: dict) -> None:
        """Per-event accumulator (runs under the workload drain, NOT on
        the query hot path). Cheap and bounded: one dict update per
        event carrying a cell."""
        if not config.SHARDWATCH_ENABLED.get():
            return
        cell = ev.get("cell")
        if not cell:
            return
        cell = str(cell)
        with self._lock:
            rec = self._cells.get(cell)
            if rec is None:
                if len(self._cells) >= int(
                        config.SHARDWATCH_CELL_STATS.get()):
                    self._cell_drops += 1
                    return
                rec = self._cells[cell] = [0, 0, 0.0]
            if self._t0 is None:
                self._t0 = time.monotonic()
            rec[0] += 1
            rec[1] += int(ev.get("rows_scanned") or 0)
            rec[2] += float(ev.get("device_ms") or 0.0)

    # -- the join ---------------------------------------------------------------

    def _type_report(self, hot: dict, smap: dict, stats: Dict[str, list],
                     elapsed_s: float, parts: int) -> dict:
        key_ranges = smap["key_ranges"]
        shards = {s: {"load": 0.0, "at_least": 0.0, "events": 0.0,
                      "qps": 0.0, "rows_scanned": 0.0, "device_ms": 0.0,
                      "key_range": list(r), "cells": []}
                  for s, r in key_ranges.items()}
        unmapped_cells = 0
        unmapped_load = 0
        for e in hot.get("cells") or ():
            owners = smap["cells"].get(e["key"])
            if not owners:
                unmapped_cells += 1
                unmapped_load += int(e["at_least"])
                continue
            rows_total = sum(o["rows"] for o in owners.values()) or 1
            st = stats.get(e["key"]) or (0, 0, 0.0)
            for s, o in owners.items():
                sh = shards.get(s)
                if sh is None:
                    continue
                frac = o["rows"] / rows_total
                sh["load"] += e["count"] * frac
                sh["at_least"] += e["at_least"] * frac
                sh["events"] += st[0] * frac
                sh["rows_scanned"] += st[1] * frac
                sh["device_ms"] += st[2] * frac
                sh["cells"].append({"cell": e["key"],
                                    "load": e["count"] * frac,
                                    "at_least": e["at_least"] * frac,
                                    "share_of_cell": round(frac, 4),
                                    "key_lo": o["key_lo"],
                                    "key_hi": o["key_hi"]})
        total_load = sum(sh["load"] for sh in shards.values())
        total_g = sum(sh["at_least"] for sh in shards.values())
        n_shards = max(1, len(shards))
        mean_g = total_g / n_shards
        mean_e = total_load / n_shards
        max_over_mean = max(
            (sh["at_least"] for sh in shards.values()), default=0.0) \
            / mean_g if mean_g > 0 else 1.0
        max_over_mean_est = max(
            (sh["load"] for sh in shards.values()), default=0.0) \
            / mean_e if mean_e > 0 else 1.0
        hot_cells = hot.get("cells") or []
        top_frac = float(hot_cells[0]["fraction"]) if hot_cells else 0.0
        hot_shard = max(shards,
                        key=lambda s: (shards[s]["at_least"],
                                       shards[s]["load"], s)) \
            if shards else None
        for s, sh in shards.items():
            sh["load_share"] = round(sh["load"] / total_load, 4) \
                if total_load > 0 else 0.0
            sh["qps"] = round(sh["events"] / elapsed_s, 3) \
                if elapsed_s > 0 else 0.0
            sh["load"] = round(sh["load"], 2)
            sh["at_least"] = round(sh["at_least"], 2)
            sh["events"] = round(sh["events"], 2)
            sh["rows_scanned"] = round(sh["rows_scanned"], 1)
            sh["device_ms"] = round(sh["device_ms"], 3)
            sh["cells"] = sorted(sh["cells"],
                                 key=lambda c: (-c["load"], c["cell"]))
            for c in sh["cells"]:
                c["load"] = round(c["load"], 2)
                c["at_least"] = round(c["at_least"], 2)
        splits = []
        if hot_shard is not None and hot_shard in key_ranges:
            splits = project_splits(shards[hot_shard]["cells"],
                                    key_ranges[hot_shard], parts)
        score = {
            "max_over_mean": round(max_over_mean, 4),
            "max_over_mean_est": round(max_over_mean_est, 4),
            "top_cell_fraction": round(top_frac, 4),
            "imbalance": round(max_over_mean + top_frac, 4),
            "hot_shard": hot_shard,
            "guaranteed_total": round(total_g, 2),
            "bar": float(config.DOCTOR_IMBALANCE_RATIO.get()),
            "min_load": int(config.DOCTOR_IMBALANCE_MIN.get()),
        }
        score["over_bar"] = bool(
            total_g >= score["min_load"]
            and max_over_mean >= score["bar"])
        return {"shards": shards, "score": score,
                "splits": {"shard": hot_shard,
                           "parts": max(2, int(parts)),
                           "boundaries": splits},
                "unmapped": {"cells": unmapped_cells,
                             "load": unmapped_load}}

    def balance(self, k: Optional[int] = None,
                parts: Optional[int] = None) -> dict:
        """The ledger join: per-type per-shard loads, imbalance score,
        and projected split points for the hottest shard. ``active`` is
        False until a shard map exists (solo processes stay quiet)."""
        if not config.SHARDWATCH_ENABLED.get():
            return {"active": False, "reason": "shardwatch disabled"}
        k = int(k if k is not None
                else config.SHARDWATCH_TOP_CELLS.get())
        parts = int(parts if parts is not None
                    else config.SHARDWATCH_SPLIT_PARTS.get())
        hot = self._wl().hot_set(k)
        with self._lock:
            maps = {t: m for t, m in self._maps.items()}
            stats = {c: list(v) for c, v in self._cells.items()}
            drops = self._cell_drops
            elapsed = (time.monotonic() - self._t0) \
                if self._t0 is not None else 0.0
        if not maps:
            return {"active": False, "reason": "no shard map registered",
                    "hot_cells": len(hot.get("cells") or ())}
        types = {t: self._type_report(hot, m, stats, elapsed, parts)
                 for t, m in sorted(maps.items())}
        worst = max(types, key=lambda t: types[t]["score"]["imbalance"])
        return {"active": True,
                "types": types,
                "worst": {"type": worst, **types[worst]["score"]},
                "hot_cells": len(hot.get("cells") or ()),
                "total": hot.get("total", 0),
                "cell_stats": {"tracked": len(stats), "dropped": drops,
                               "elapsed_s": round(elapsed, 3)}}

    # -- federation -------------------------------------------------------------

    def export_state(self) -> dict:
        """Mergeable wire form riding the /metrics?format=state scrape
        next to the workload state."""
        with self._lock:
            return {
                "maps": {t: m for t, m in sorted(self._maps.items())},
                "cells": {c: [v[0], v[1], round(v[2], 3)]
                          for c, v in sorted(self._cells.items())},
                "cell_drops": self._cell_drops,
                "elapsed_s": round((time.monotonic() - self._t0), 3)
                if self._t0 is not None else 0.0,
            }

    def load_state(self, state: dict) -> "ShardWatch":
        with self._lock:
            self._maps = dict(state.get("maps") or {})
            self._cells = {str(c): [int(v[0]), int(v[1]), float(v[2])]
                           for c, v in (state.get("cells") or {}).items()}
            self._cell_drops = int(state.get("cell_drops", 0))
            el = float(state.get("elapsed_s", 0.0))
            self._t0 = (time.monotonic() - el) if el > 0 else None
        return self

    def clear(self) -> None:
        with self._lock:
            self._maps.clear()
            self._cells.clear()
            self._cell_drops = 0
            self._t0 = None


def merge_states(states: List[dict]) -> dict:
    """Merge per-node shardwatch states: per-cell stats sum, shard maps
    union (every rank derives the identical map from the same exchange,
    so union == any one of them), elapsed takes the max."""
    maps: Dict[str, dict] = {}
    cells: Dict[str, list] = {}
    drops = 0
    elapsed = 0.0
    for st in states:
        if not st:
            continue
        drops += int(st.get("cell_drops", 0))
        elapsed = max(elapsed, float(st.get("elapsed_s", 0.0)))
        for t, m in (st.get("maps") or {}).items():
            maps.setdefault(t, m)
        for c, v in (st.get("cells") or {}).items():
            have = cells.setdefault(str(c), [0, 0, 0.0])
            have[0] += int(v[0])
            have[1] += int(v[1])
            have[2] += float(v[2])
    return {"maps": maps,
            "cells": {c: [v[0], v[1], round(v[2], 3)]
                      for c, v in sorted(cells.items())},
            "cell_drops": drops, "elapsed_s": round(elapsed, 3)}


def fleet_balance_report(workload_state: dict,
                         shardwatch_states: List[dict],
                         k: Optional[int] = None,
                         parts: Optional[int] = None) -> dict:
    """Build the fleet-wide balance report from merged scrape states —
    the Federator's GET /fleet/balance computation."""
    wl = _workload.WorkloadAnalytics.from_state(workload_state or {})
    sw = ShardWatch(workload=wl)
    sw.load_state(merge_states(shardwatch_states))
    return sw.balance(k=k, parts=parts)


# process-global ledger (the serving shape: one per process), fed by the
# workload plane's drain hook — producers never call into shardwatch
WATCH = ShardWatch()
_workload.add_fold_hook(WATCH.fold_event)


def _cell_span(cell: str) -> Optional[Tuple[float, float, float, float]]:
    """Re-export of the cell bbox inverse for balance consumers."""
    return _sk.cell_bbox(cell)
