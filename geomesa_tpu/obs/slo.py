"""SLO engine: declarative objectives evaluated as multi-window burn rates.

The SRE-workbook alerting discipline: an SLO (say 99.9% of counts under
250ms) defines an error budget (0.1%); the *burn rate* over a window is
how many times faster than budget-neutral the service is spending it
(burn 1.0 = exactly exhausting the budget over the SLO period). Alerting
on multi-window burn rates gets both fast detection and low flap:

  page    burn >= 14.4 over BOTH the 5m and 1h windows
          (at 14.4x, a 30-day budget is gone in ~2 days)
  ticket  burn >= 6 over BOTH the 30m and 6h windows

Objectives read the metrics registry we already populate — latency SLOs
count good observations straight out of the timer's log-scale buckets
(``timer_good_total``), availability SLOs diff counters (total vs bad).
The engine snapshots (ts, good, total) samples on an injectable clock;
window burn rates are computed by diffing against the newest sample at
least window-old, so tests drive hours of budget history in microseconds
with a fake clock and zero sleeps.

Surfaces: ``GET /slo``, the ``slo`` section of ``/healthz``, and CLI
``geomesa-tpu debug slo``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _default_registry

# evaluation windows (seconds) and the two alert pairings
WINDOWS: Dict[str, float] = {"5m": 300.0, "30m": 1800.0,
                             "1h": 3600.0, "6h": 21600.0}
PAGE_WINDOWS: Tuple[str, str] = ("5m", "1h")
TICKET_WINDOWS: Tuple[str, str] = ("30m", "6h")
PAGE_BURN = 14.4
TICKET_BURN = 6.0


@dataclass
class Objective:
    """One declarative objective.

    kind 'latency':      good = observations of ``timer`` landing under
                         ``threshold_ms`` (bucket-resolution, conservative)
    kind 'availability': good = ``total_counter`` minus the sum of
                         ``bad_counters``
    """

    name: str
    kind: str                      # "latency" | "availability"
    target: float                  # e.g. 0.999
    timer: Optional[str] = None
    threshold_ms: float = 0.0
    total_counter: Optional[str] = None
    bad_counters: tuple = field(default_factory=tuple)

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - float(self.target))

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.kind == "latency":
            out["timer"] = self.timer
            out["threshold_ms"] = self.threshold_ms
        else:
            out["total_counter"] = self.total_counter
            out["bad_counters"] = list(self.bad_counters)
        return out


class SloEngine:
    """Burn-rate evaluation over registry snapshots."""

    def __init__(self, registry=None, clock=time.monotonic,
                 history: int = 8192):
        self._registry = registry or _default_registry
        self._clock = clock
        self._lock = threading.Lock()
        self._objectives: Dict[str, Objective] = {}
        # per-objective (ts, good, total) cumulative samples, oldest first
        self._samples: Dict[str, deque] = {}
        self._history = int(history)

    # -- registration ---------------------------------------------------------

    def add(self, obj: Objective) -> Objective:
        with self._lock:
            self._objectives[obj.name] = obj
            self._samples.setdefault(obj.name,
                                     deque(maxlen=self._history))
        return obj

    def remove(self, name: str) -> None:
        with self._lock:
            self._objectives.pop(name, None)
            self._samples.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._objectives.clear()
            self._samples.clear()

    def objectives(self) -> List[Objective]:
        with self._lock:
            return list(self._objectives.values())

    # -- sampling -------------------------------------------------------------

    def _totals(self, obj: Objective) -> Tuple[int, int]:
        """Cumulative (good, total) for an objective right now."""
        if obj.kind == "latency":
            return self._registry.timer_good_total(
                obj.timer, obj.threshold_ms / 1000.0)
        counters = self._registry.snapshot()["counters"]
        total = int(counters.get(obj.total_counter, 0))
        bad = sum(int(counters.get(b, 0)) for b in obj.bad_counters)
        bad = min(bad, total)
        return total - bad, total

    def tick(self) -> None:
        """Append one (ts, good, total) sample per objective — called on
        every evaluation (and by anything periodic an operator wires up)."""
        now = self._clock()
        with self._lock:
            objs = list(self._objectives.values())
        for obj in objs:
            good, total = self._totals(obj)
            with self._lock:
                self._samples[obj.name].append((now, good, total))

    # -- evaluation -----------------------------------------------------------

    @staticmethod
    def _baseline(samples, cutoff: float):
        """Newest sample no newer than ``cutoff`` (the window's start),
        else the oldest available (a partially-filled window measures the
        history it has — better than pretending zero traffic)."""
        base = None
        for s in samples:
            if s[0] <= cutoff:
                base = s
            else:
                break
        return base if base is not None else (samples[0] if samples else None)

    def evaluate(self, tick: bool = True) -> dict:
        """Burn rates + alert state per objective. ``tick=False`` evaluates
        the existing history without adding a sample (pure readers)."""
        if tick:
            self.tick()
        now = self._clock()
        with self._lock:
            objs = list(self._objectives.values())
            hist = {n: list(s) for n, s in self._samples.items()}
        out = {}
        for obj in objs:
            samples = hist.get(obj.name, [])
            latest = samples[-1] if samples else (now, 0, 0)
            burns: Dict[str, Optional[float]] = {}
            for wname, wsec in WINDOWS.items():
                base = self._baseline(samples, now - wsec)
                if base is None or latest[2] <= base[2]:
                    burns[wname] = None  # no traffic in the window
                    continue
                d_total = latest[2] - base[2]
                d_bad = (latest[2] - latest[1]) - (base[2] - base[1])
                err_rate = max(0.0, d_bad) / d_total
                burns[wname] = round(err_rate / obj.budget, 3)

            def _pair(pair, bar):
                return all(burns.get(w) is not None and burns[w] >= bar
                           for w in pair)

            page = _pair(PAGE_WINDOWS, PAGE_BURN)
            ticket = _pair(TICKET_WINDOWS, TICKET_BURN)
            status = "page" if page else ("ticket" if ticket else "ok")
            good, total = latest[1], latest[2]
            out[obj.name] = {
                **obj.describe(),
                "good": good,
                "total": total,
                "error_budget": obj.budget,
                "compliance": round(good / total, 6) if total else None,
                "burn_rates": burns,
                "page": page,
                "ticket": ticket,
                "status": status,
            }
        return out

    def summary(self, tick: bool = True) -> dict:
        """Worst-status rollup for /healthz."""
        ev = self.evaluate(tick=tick)
        statuses = [v["status"] for v in ev.values()]
        worst = "page" if "page" in statuses else \
            ("ticket" if "ticket" in statuses else "ok")
        return {"status": worst,
                "objectives": {k: v["status"] for k, v in ev.items()}}


# process-global engine
ENGINE = SloEngine()


def default_objectives() -> List[Objective]:
    """The serving-path defaults install() registers: count latency under
    GEOMESA_TPU_SLO_LATENCY_MS at GEOMESA_TPU_SLO_TARGET, and scheduled-
    count availability (sheds, deadline cancellations and worker deaths
    spend the budget) at GEOMESA_TPU_SLO_AVAIL_TARGET."""
    return [
        Objective(name="count_latency", kind="latency",
                  target=float(config.SLO_TARGET.get()),
                  timer="query.count",
                  threshold_ms=float(config.SLO_LATENCY_MS.get())),
        Objective(name="count_availability", kind="availability",
                  target=float(config.SLO_AVAIL_TARGET.get()),
                  total_counter="scheduler.queries",
                  bad_counters=("admission.shed",
                                "scheduler.deadline_cancelled",
                                "scheduler.worker_deaths")),
    ]


def replication_objective() -> Objective:
    """Bounded-staleness SLO a read replica registers (replication/
    follower.py): every heartbeat/ack scores a staleness check, and a
    check with replication lag over GEOMESA_TPU_REPL_STALENESS_MS spends
    the budget — so a persistently lagging replica pages through exactly
    the same burn-rate machinery as a latency breach."""
    return Objective(name="replication_staleness", kind="availability",
                     target=float(config.REPL_SLO_TARGET.get()),
                     total_counter="replication.staleness_checks",
                     bad_counters=("replication.staleness_exceeded",))
