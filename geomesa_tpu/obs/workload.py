"""Workload intelligence plane: streaming rollups, heavy hitters, hot set.

The flight recorder (obs/flight.py) explains any SINGLE query; this module
answers the fleet-operator questions about the WORKLOAD: which query
shapes dominate, which spatial regions are hot, which tenant is burning
the device budget — the role GeoMesa's stats/audit subsystem plays for
the reference, feeding query-pattern analytics back into planning.

One process-global ``WorkloadAnalytics`` consumes the existing flight
event stream:

  rollups    a fixed ring of time-aligned windows per tier (10s/1m/10m)
             aggregating per (type, plan_hash, admission class, tenant):
             qps, latency p50/p99 on the SHARED metrics.py log-bucket
             geometry (so fleet merges stay lossless), rows scanned/
             matched, device-ms, plan/cover cache-hit rates, shed/
             degrade/error rates.

  sketches   SpaceSaving top-k over plan hashes and tenants plus the
             hot-cell grid over coarse Morton cells (obs/sketches.py) —
             a spatial heatmap of query load.

  hot_set()  the STABLE feed the future result cache consumes: top plan
             hashes + hot cells with explicit confidence bounds
             (estimate is never an undercount; estimate - error is
             never an overcount).

  tenant.*   per-tenant metering counters (queries / device-ms / rows
             scanned) in the process metrics registry, federated like
             every other counter.

Hot-path discipline: producers pay ONE bounded-deque append per event
(obs/flight.py tees each wide event / lazily-recorded trace here);
aggregation happens at read time via ``drain()``, chained into the
registry's pre-drain hook alongside tail sampling — the same deferred
pattern that keeps the obs overhead guard under 5%.

Fleet merge: ``export_state()`` rides the ``/metrics?format=state``
scrape payload; windows merge exactly like histograms (bucket-count
sums over identical wall-clock-aligned window starts), sketches merge
per obs/sketches.py — ``merge_states`` + ``from_state`` back the
Federator's ``GET /fleet/workload``.

Import discipline (obs/__init__ rule): config/metrics + obs.sketches
only — never planner/scheduler/datastore layers (obs.flight imports are
deferred to drain time).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import (Histogram, REGISTRY as _metrics,
                                 bucket_index)
from geomesa_tpu.obs import sketches as _sk

# window tiers (seconds): the short window answers "now", the long ones
# smooth bursts — all wall-clock aligned so per-node windows line up
SPANS = (10.0, 60.0, 600.0)

# cached GEOMESA_TPU_WORKLOAD verdict for the per-event offer() (same
# refresh pattern as obs.__init__._obs_enabled — no env read per query)
_enabled_cache = [True, 0]
_ENABLED_REFRESH = 64


def enabled() -> bool:
    c = _enabled_cache
    c[1] -= 1
    if c[1] <= 0:
        c[0] = bool(config.WORKLOAD_ENABLED.get())
        c[1] = _ENABLED_REFRESH
    return c[0]


# drain-time fold hooks: other obs planes (shardwatch's per-cell cost
# accumulator) observe every folded event WITHOUT touching the producer
# hot path — hooks run under the analytics lock at drain time and must
# never raise into the fold
_FOLD_HOOKS: List = []


def add_fold_hook(fn) -> None:
    """Register ``fn(event_dict)`` to run for every event folded at
    drain time (idempotent per function)."""
    if fn not in _FOLD_HOOKS:
        _FOLD_HOOKS.append(fn)


def tenant_metric_label(tenant) -> str:
    """A metrics-safe tenant label (the ``tenant.*`` counter namespace
    must stay bounded and exposition-clean)."""
    t = str(tenant or "default")[:64]
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in t) \
        or "default"


class _Group:
    """One (type, plan_hash, priority, tenant) aggregate inside one
    window. Latency buckets use the shared metrics.py geometry so two
    nodes' groups merge by plain bucket-count sums."""

    __slots__ = ("n", "errors", "shed", "degraded", "cancelled",
                 "plan_hits", "plan_known", "cover_hits", "cover_known",
                 "rows_scanned", "rows_matched", "device_ms", "buckets")

    def __init__(self):
        self.n = 0
        self.errors = 0
        self.shed = 0
        self.degraded = 0
        self.cancelled = 0
        self.plan_hits = 0
        self.plan_known = 0
        self.cover_hits = 0
        self.cover_known = 0
        self.rows_scanned = 0
        self.rows_matched = 0
        self.device_ms = 0.0
        self.buckets: Dict[int, int] = {}

    def fold(self, ev: dict) -> None:
        self.n += 1
        if ev.get("error"):
            self.errors += 1
        if ev.get("shed"):
            self.shed += 1
        if ev.get("degraded"):
            self.degraded += 1
        if ev.get("cancelled"):
            self.cancelled += 1
        ph = ev.get("plan_cache_hit")
        if ph is not None:
            self.plan_known += 1
            self.plan_hits += bool(ph)
        ch = ev.get("cover_cache_hit")
        if ch is not None:
            self.cover_known += 1
            self.cover_hits += bool(ch)
        self.rows_scanned += int(ev.get("rows_scanned") or 0)
        self.rows_matched += int(ev.get("rows_matched") or 0)
        self.device_ms += float(ev.get("device_ms") or 0.0)
        dur = ev.get("duration_ms")
        if dur is not None:
            bi = bucket_index(float(dur) / 1000.0)
            self.buckets[bi] = self.buckets.get(bi, 0) + 1

    def merge(self, other: "_Group") -> None:
        self.n += other.n
        self.errors += other.errors
        self.shed += other.shed
        self.degraded += other.degraded
        self.cancelled += other.cancelled
        self.plan_hits += other.plan_hits
        self.plan_known += other.plan_known
        self.cover_hits += other.cover_hits
        self.cover_known += other.cover_known
        self.rows_scanned += other.rows_scanned
        self.rows_matched += other.rows_matched
        self.device_ms += other.device_ms
        for bi, c in other.buckets.items():
            self.buckets[bi] = self.buckets.get(bi, 0) + c

    def to_state(self) -> dict:
        return {"n": self.n, "errors": self.errors, "shed": self.shed,
                "degraded": self.degraded, "cancelled": self.cancelled,
                "plan_hits": self.plan_hits, "plan_known": self.plan_known,
                "cover_hits": self.cover_hits,
                "cover_known": self.cover_known,
                "rows_scanned": self.rows_scanned,
                "rows_matched": self.rows_matched,
                "device_ms": round(self.device_ms, 3),
                "buckets": {str(bi): c
                            for bi, c in sorted(self.buckets.items())}}

    @classmethod
    def from_state(cls, st: dict) -> "_Group":
        g = cls()
        for f in ("n", "errors", "shed", "degraded", "cancelled",
                  "plan_hits", "plan_known", "cover_hits", "cover_known",
                  "rows_scanned", "rows_matched"):
            setattr(g, f, int(st.get(f, 0)))
        g.device_ms = float(st.get("device_ms", 0.0))
        g.buckets = {int(bi): int(c)
                     for bi, c in (st.get("buckets") or {}).items()}
        return g

    def _percentile_ms(self, q: float) -> float:
        h = Histogram()
        h.count = self.n if self.n else sum(self.buckets.values())
        for bi, c in self.buckets.items():
            h.buckets[bi] = c
        return round(h.percentile(q) * 1000.0, 3)

    def summarize(self, span_s: float) -> dict:
        n = self.n
        return {
            "n": n,
            "qps": round(n / span_s, 3),
            "p50_ms": self._percentile_ms(0.50),
            "p99_ms": self._percentile_ms(0.99),
            "error_rate": round(self.errors / n, 4) if n else 0.0,
            "shed_rate": round(self.shed / n, 4) if n else 0.0,
            "degrade_rate": round(self.degraded / n, 4) if n else 0.0,
            "cancel_rate": round(self.cancelled / n, 4) if n else 0.0,
            "plan_cache_hit_rate": round(
                self.plan_hits / self.plan_known, 4)
            if self.plan_known else None,
            "cover_cache_hit_rate": round(
                self.cover_hits / self.cover_known, 4)
            if self.cover_known else None,
            "rows_scanned": self.rows_scanned,
            "rows_matched": self.rows_matched,
            "device_ms": round(self.device_ms, 3),
        }


class _Window:
    __slots__ = ("start", "span", "groups")

    def __init__(self, start: float, span: float):
        self.start = start
        self.span = span
        self.groups: Dict[str, _Group] = {}

    @property
    def n(self) -> int:
        return sum(g.n for g in self.groups.values())

    def fold(self, key: str, ev: dict) -> None:
        g = self.groups.get(key)
        if g is None:
            g = self.groups[key] = _Group()
        g.fold(ev)

    def to_state(self) -> dict:
        return {"start": self.start, "span": self.span,
                "groups": {k: g.to_state()
                           for k, g in sorted(self.groups.items())}}

    @classmethod
    def from_state(cls, st: dict) -> "_Window":
        w = cls(float(st.get("start", 0.0)), float(st.get("span", 0.0)))
        for k, gs in (st.get("groups") or {}).items():
            w.groups[k] = _Group.from_state(gs)
        return w


class _WindowRing:
    """Fixed ring of wall-clock-aligned windows for one tier. Not
    internally locked — the analytics lock covers it."""

    def __init__(self, span_s: float, keep: int):
        self.span = float(span_s)
        self.keep = max(1, int(keep))
        self.windows: deque = deque()   # ascending by start
        self.retired_events = 0         # events in rotated-out windows
        self.late_dropped = 0           # older than the retained horizon

    def fold(self, ts_s: float, key: str, ev: dict) -> None:
        """Invariant: the ring holds the NEWEST <= keep wall-aligned
        windows in ascending start order. Conservation: every folded
        event is retained, retired (rotated out), or late-dropped."""
        start = (ts_s // self.span) * self.span
        ws = self.windows
        if ws and start < ws[0].start and len(ws) >= self.keep:
            self.late_dropped += 1  # older than the retained horizon
            return
        # find-or-insert in place (rings are tiny: <= keep entries); the
        # newest window is the hot one, so scan from the right
        for i in range(len(ws) - 1, -1, -1):
            if ws[i].start == start:
                ws[i].fold(key, ev)
                return
            if ws[i].start < start:
                w = _Window(start, self.span)
                ws.insert(i + 1, w)
                break
        else:
            w = _Window(start, self.span)
            ws.insert(0, w)
        w.fold(key, ev)
        while len(ws) > self.keep:
            self.retired_events += ws.popleft().n

    def total_events(self) -> int:
        return sum(w.n for w in self.windows)


def _group_key(ev: dict) -> str:
    return "|".join((str(ev.get("type") or "-"),
                     str(ev.get("plan_hash") or "-"),
                     str(ev.get("priority") or "-"),
                     str(ev.get("tenant") or "default")))


class WorkloadAnalytics:
    """The streaming workload-analytics plane (one per process).

    Producers call ``offer()`` (one bounded deque append); everything
    else — window folding, sketch updates, tenant metering — happens in
    ``drain()``, which the obs pre-drain hook runs before any metrics/
    events/workload read."""

    def __init__(self, clock=time.time, spans=SPANS,
                 keep: Optional[int] = None,
                 sketch_capacity: Optional[int] = None,
                 meter: bool = True):
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._keep = keep
        self._meter = meter
        k = int(keep if keep is not None
                else config.WORKLOAD_WINDOWS.get())
        cap = int(sketch_capacity if sketch_capacity is not None
                  else config.WORKLOAD_SKETCH_K.get())
        self.rings = {s: _WindowRing(s, k) for s in spans}
        self.plans = _sk.SpaceSaving(cap)
        self.tenants = _sk.SpaceSaving(cap)
        self.cells = _sk.SpaceSaving(cap)
        self.funcs = _sk.SpaceSaving(cap)
        self.consumed = 0
        self.dropped = 0

    # -- producer side (hot path) ---------------------------------------------

    def offer(self, item) -> None:
        """Enqueue one wide event (dict) or closed root trace for
        deferred aggregation. deque appends are GIL-atomic; the bound
        check is advisory (an over-append is harmless)."""
        if not enabled():
            return
        if len(self._pending) >= int(config.WORKLOAD_PENDING.get()):
            self.dropped += 1
            return
        self._pending.append(item)

    # -- consumer side (deferred) ---------------------------------------------

    def drain(self) -> int:
        """Fold every pending event into windows/sketches/meters.
        Reentrancy-safe and cheap when idle (one truthiness check)."""
        if not self._pending:
            return 0
        out = 0
        with self._lock:
            while True:
                try:
                    item = self._pending.popleft()
                except IndexError:
                    break
                ev = item
                if not isinstance(ev, dict):
                    # lazily-enqueued root trace: materialize the wide
                    # event now, at read time (mirrors flight.recent())
                    from geomesa_tpu.obs import flight as _flight
                    try:
                        ev = _flight.event_from_trace(item)
                    except Exception:
                        continue
                if ev.get("kind") == "batch":
                    continue  # per-query events already carry device_ms
                self._fold_event(ev)
                out += 1
        return out

    def _fold_event(self, ev: dict) -> None:
        self.consumed += 1
        if self._meter:  # read-only from_state views skip the hooks too
            for hook in _FOLD_HOOKS:
                try:
                    hook(ev)
                except Exception:
                    pass
        ts_s = float(ev.get("ts_ms") or self._clock() * 1000.0) / 1000.0
        key = _group_key(ev)
        for ring in self.rings.values():
            ring.fold(ts_s, key, ev)
        ph = ev.get("plan_hash")
        if ph:
            self.plans.offer(str(ph))
        tenant = str(ev.get("tenant") or "default")
        self.tenants.offer(tenant)
        cell = ev.get("cell")
        if cell:
            self.cells.offer(str(cell))
        # each distinct st_* name counts ONCE per query (funcs_of dedups
        # repeated occurrences at IR level), so sketch totals are
        # queries-touching-the-function, never call-site counts
        for fn in (ev.get("funcs") or ()):
            self.funcs.offer(str(fn))
        if self._meter:
            label = tenant_metric_label(tenant)
            _metrics.inc(f"tenant.{label}.queries")
            if ev.get("cache") == "result":
                # result-cache hit: the device/scan cost was billed when
                # the original dispatch ran — replaying it here would
                # double-count device time and rows against the tenant
                return
            dms = float(ev.get("device_ms") or 0.0)
            if dms:
                _metrics.inc(f"tenant.{label}.device_ms", dms)
            rows = int(ev.get("rows_scanned") or 0)
            if rows:
                _metrics.inc(f"tenant.{label}.rows_scanned", rows)

    # -- read surfaces --------------------------------------------------------

    def hot_set(self, k: Optional[int] = None) -> dict:
        """The stable feed a result cache consumes: top plan hashes and
        hot cells with explicit confidence bounds. For every entry,
        ``count`` is never an undercount of the true frequency and
        ``count - error`` is never an overcount — a consumer that wants
        certainty keys on ``count - error``."""
        self.drain()
        k = int(k if k is not None else config.WORKLOAD_HOTSET_K.get())

        def entries(sk: _sk.SpaceSaving, with_bbox: bool = False):
            total = sk.n_total
            out = []
            for key, est, err in sk.top(k):
                e = {"key": key, "count": est, "error": err,
                     "at_least": est - err,
                     "fraction": round(est / total, 4) if total else 0.0}
                if with_bbox:
                    e["bbox"] = _sk.cell_bbox(key)
                out.append(e)
            return out

        return {"total": self.plans.n_total,
                "plans": entries(self.plans),
                "cells": entries(self.cells, with_bbox=True),
                "funcs": entries(self.funcs),
                "sketch_capacity": self.plans.capacity}

    def top_tenants(self, k: int = 10) -> List[dict]:
        self.drain()
        total = self.tenants.n_total
        return [{"tenant": t, "count": est, "error": err,
                 "fraction": round(est / total, 4) if total else 0.0}
                for t, est, err in self.tenants.top(k)]

    def rollups(self) -> dict:
        """Per-tier windowed rollups, newest window first, each group
        summarized (qps, p50/p99, rates) from its mergeable state."""
        self.drain()
        out = {}
        for span, ring in sorted(self.rings.items()):
            out[f"{int(span)}s"] = [
                {"start": w.start, "span_s": span, "n": w.n,
                 "groups": {key: g.summarize(span)
                            for key, g in sorted(w.groups.items())}}
                for w in reversed(ring.windows)]
        return out

    def summary(self) -> dict:
        self.drain()
        return {"enabled": enabled(),
                "consumed": self.consumed,
                "dropped": self.dropped,
                "pending": len(self._pending),
                "retired_events": {f"{int(s)}s": r.retired_events
                                   for s, r in sorted(self.rings.items())},
                "hot_set": self.hot_set(),
                "tenants": self.top_tenants(),
                "rollups": self.rollups()}

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            k = int(self._keep if self._keep is not None
                    else config.WORKLOAD_WINDOWS.get())
            self.rings = {s: _WindowRing(s, k) for s in self.rings}
            cap = self.plans.capacity
            self.plans = _sk.SpaceSaving(cap)
            self.tenants = _sk.SpaceSaving(cap)
            self.cells = _sk.SpaceSaving(cap)
            self.funcs = _sk.SpaceSaving(cap)
            self.consumed = 0
            self.dropped = 0

    # -- federation -----------------------------------------------------------

    def export_state(self) -> dict:
        """Mergeable wire form for the /metrics?format=state payload —
        windows carry raw bucket counts (merge by summation over equal
        aligned starts), sketches their (count, error) items."""
        self.drain()
        with self._lock:
            return {
                "spans": {str(int(s)): [w.to_state() for w in r.windows]
                          for s, r in sorted(self.rings.items())},
                "plans": self.plans.to_state(),
                "tenants": self.tenants.to_state(),
                "cells": self.cells.to_state(),
                "funcs": self.funcs.to_state(),
                "consumed": self.consumed,
                "dropped": self.dropped,
            }

    @classmethod
    def from_state(cls, state: dict) -> "WorkloadAnalytics":
        """Rebuild a read-only analytics view from (merged) state —
        the Federator's path to fleet hot_set()/rollups()."""
        spans = sorted(float(s) for s in (state.get("spans") or
                                          {str(int(s)): 0 for s in SPANS}))
        w = cls(spans=tuple(spans) or SPANS, keep=max(
            1, max((len(v) for v in (state.get("spans") or {}).values()),
                   default=1)), sketch_capacity=1, meter=False)
        for s_str, windows in (state.get("spans") or {}).items():
            ring = w.rings.get(float(s_str))
            if ring is None:
                continue
            for wst in sorted(windows, key=lambda x: x.get("start", 0.0)):
                ring.windows.append(_Window.from_state(wst))
        w.plans = _sk.SpaceSaving.from_state(state.get("plans") or {})
        w.tenants = _sk.SpaceSaving.from_state(state.get("tenants") or {})
        w.cells = _sk.SpaceSaving.from_state(state.get("cells") or {})
        w.funcs = _sk.SpaceSaving.from_state(state.get("funcs") or {})
        w.consumed = int(state.get("consumed", 0))
        w.dropped = int(state.get("dropped", 0))
        return w


def merge_states(states: List[dict]) -> dict:
    """Merge per-node workload states exactly the way the Federator
    merges histograms: windows with equal (span, start) merge by bucket/
    count summation; sketches merge per obs/sketches.py (commutative)."""
    spans: Dict[str, Dict[float, _Window]] = {}
    plan_sk, ten_sk, cell_sk, func_sk = [], [], [], []
    consumed = dropped = 0
    for st in states:
        if not st:
            continue
        consumed += int(st.get("consumed", 0))
        dropped += int(st.get("dropped", 0))
        plan_sk.append(_sk.SpaceSaving.from_state(st.get("plans") or {}))
        ten_sk.append(_sk.SpaceSaving.from_state(st.get("tenants") or {}))
        cell_sk.append(_sk.SpaceSaving.from_state(st.get("cells") or {}))
        func_sk.append(_sk.SpaceSaving.from_state(st.get("funcs") or {}))
        for s_str, windows in (st.get("spans") or {}).items():
            tier = spans.setdefault(s_str, {})
            for wst in windows:
                w = _Window.from_state(wst)
                have = tier.get(w.start)
                if have is None:
                    tier[w.start] = w
                else:
                    for k, g in w.groups.items():
                        if k in have.groups:
                            have.groups[k].merge(g)
                        else:
                            have.groups[k] = g
    return {
        "spans": {s: [w.to_state()
                      for _, w in sorted(tier.items())]
                  for s, tier in sorted(spans.items())},
        "plans": _sk.SpaceSaving.merge_all(plan_sk).to_state()
        if plan_sk else {},
        "tenants": _sk.SpaceSaving.merge_all(ten_sk).to_state()
        if ten_sk else {},
        "cells": _sk.SpaceSaving.merge_all(cell_sk).to_state()
        if cell_sk else {},
        "funcs": _sk.SpaceSaving.merge_all(func_sk).to_state()
        if func_sk else {},
        "consumed": consumed,
        "dropped": dropped,
    }


# process-global analytics plane (the serving shape: one per process)
WORKLOAD = WorkloadAnalytics()
