"""Ramped-handicap predictive drill: the trend page leads the burn page.

The doctor's predictive claim (``slo_trend``, obs/doctor.py) is that a
ramping burn rate opens an incident BEFORE the classic multi-window
``slo_burn`` pages — prediction buys lead time, not noise. This drill
proves both halves deterministically, the soak discipline
(obs/soak.py) applied to a ramp:

* **faulted half** — a real store serves real counts on a shared fake
  clock (SLO windows elapse instantly; query durations stay real). A
  kernel handicap (``profiling.arm_kernel_handicap``) is armed before a
  fresh type's count kernels compile, so that type's counts are slow;
  the drill then RAMPS the slow:fast traffic ratio step by step — a
  monotone controlled burn ramp. Asserts: ``slo_trend`` opens strictly
  before the first ``slo_burn`` page fires, and every opened incident
  carries a fetchable forensic bundle whose history slice covers the
  firing window.
* **clean half** — the same traffic shape with no handicap and trend
  rules ENABLED must open ZERO incidents (the false-positive guard a
  predictive rule must clear before anyone trusts its pages).

Determinism notes (the soak's, inherited):
  * the latency objective threshold is calibrated off the measured warm
    count, so the drill passes on a fast laptop and a loaded CI runner;
    the handicap factor is derived from the same measurement so a "bad"
    count lands ~3x over the threshold without minutes of sleeping
  * skew/recompile bars go out of reach: single-plan synthetic traffic
    IS skewed and fresh kernels DO compile — correct firings, not the
    cause under test
  * DOCTOR_CLEAR_TICKS goes out of reach so nothing auto-resolves
    mid-ramp and the final bundle audit sees every incident
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from geomesa_tpu import config
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.obs.doctor import DoctorEngine
from geomesa_tpu.obs.forensics import ForensicStore
from geomesa_tpu.obs.history import TelemetryHistory

_BOX = "BBOX(geom, -5, -5, 5, 5)"
_STEP_S = 30.0          # fake seconds per ramp step
_PER_STEP = 12          # counts per step (bad + good)
_MAX_STEPS = 24


class _Clock:
    """Shared fake clock: SLO windows, doctor windows, history slots and
    forensic anchors all advance together, instantly."""

    def __init__(self, start: float = 1_000_000.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _bad_counts(step: int) -> int:
    """The ramp: three clean baseline steps, then one more slow count
    per step (a monotone controlled burn ramp, capped at all-slow)."""
    return min(_PER_STEP, max(0, step - 2))


def run(artifact: Optional[str] = None,
        bundle_artifact: Optional[str] = None) -> dict:
    """Run both halves; returns the scoreboard (``ok`` = both passed)."""
    report: dict = {"ok": False, "halves": {}}
    for half in ("faulted", "clean"):
        report["halves"][half] = _run_half(faulted=half == "faulted")
    f, c = report["halves"]["faulted"], report["halves"]["clean"]
    report["ok"] = bool(f.get("ok") and c.get("ok"))
    if artifact:
        with open(artifact, "w") as fh:
            json.dump(report, fh, indent=2, default=str)
    if bundle_artifact and f.get("bundle"):
        with open(bundle_artifact, "w") as fh:
            json.dump(f["bundle"], fh, indent=2, default=str)
        f.pop("bundle", None)
    else:
        f.pop("bundle", None)
    return report


def _run_half(faulted: bool) -> dict:
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.obs import profiling as _prof
    from geomesa_tpu.obs import slo as _slo
    from geomesa_tpu.replication.drills import SPEC, make_batch

    _prof.reset_kernel_handicap()
    knobs = [(config.DOCTOR_WINDOW_S, 300.0),
             (config.DOCTOR_TREND, True),
             (config.DOCTOR_TREND_LEAD_S, 180.0),
             (config.DOCTOR_TREND_MIN_POINTS, 5),
             (config.DOCTOR_RECOMPILES_PER_MIN, 10.0 ** 9),
             (config.DOCTOR_SHED_PER_MIN, 10.0 ** 9),
             (config.DOCTOR_SKEW_MIN, 10 ** 9),
             (config.DOCTOR_CLEAR_TICKS, 10 ** 6),
             (config.FORENSICS_ENABLED, True),
             (config.HISTORY_ENABLED, True)]
    saved = [(p, p._override) for p, _ in knobs]
    for p, v in knobs:
        p.set(v)
    half: dict = {"faulted": faulted, "ok": False}
    ds = None
    try:
        clock = _Clock()
        ds = TpuDataStore()
        ds.create_schema("t", SPEC)
        ds.load("t", make_batch(ds.schemas["t"], 1))

        # calibrate: threshold off the measured warm path, handicap off
        # the threshold (a slow count lands ~3x over the bar)
        for _ in range(4):
            ds.count("t", _BOX)
        t0 = time.perf_counter()
        for _ in range(4):
            ds.count("t", _BOX)
        warm_ms = (time.perf_counter() - t0) * 250.0  # mean of 4, in ms
        threshold_ms = max(60.0, 20.0 * warm_ms)
        # the stretch multiplies the KERNEL dispatch time (a fraction of
        # a count), so the factor is the soak's proven 2000x — a
        # handicapped count lands hundreds of ms over a >=60ms bar
        factor = 2000.0
        half["threshold_ms"] = round(threshold_ms, 1)
        half["handicap_factor"] = factor

        if faulted:
            # kernels compiled AFTER arming carry the stretch — the
            # fresh type's count kernels compile inside the handicap
            _prof.arm_kernel_handicap("count.", factor)
        ds.create_schema("h", SPEC)
        ds.load("h", make_batch(ds.schemas["h"], 2))

        engine = _slo.SloEngine(registry=_metrics, clock=clock)
        engine.add(_slo.Objective(
            name="count_latency", kind="latency", target=0.99,
            timer="query.count", threshold_ms=threshold_ms))
        hist = TelemetryHistory(clock=clock, tiers=[(int(_STEP_S), 64)],
                                registry=_metrics)
        fstore = ForensicStore(registry=_metrics, history=hist,
                               clock=clock)
        doctor = DoctorEngine(registry=_metrics, clock=clock,
                              slo_engine=engine, journal_path="",
                              federator=False, forensics=fstore)
        doctor.evaluate()   # the windows' baseline sample
        hist.sample_now(clock())

        t_trend = t_page = None
        start = clock()
        for step in range(_MAX_STEPS):
            bad = _bad_counts(step) if faulted else 0
            for _ in range(bad):
                ds.count("h", _BOX)
            for _ in range(_PER_STEP - bad):
                ds.count("t", _BOX)
            res = doctor.evaluate()
            hist.sample_now(clock())
            elapsed = clock() - start
            for a in res.get("alerts", []):
                if a["rule"] == "slo_trend" and t_trend is None:
                    t_trend = elapsed
                if a["rule"] == "slo_burn" and a["severity"] == "page" \
                        and t_page is None:
                    t_page = elapsed
            if not faulted and step >= 12:
                break
            if t_page is not None:
                break
            clock.advance(_STEP_S)
        _prof.reset_kernel_handicap()

        half["t_trend_s"] = t_trend
        half["t_page_s"] = t_page
        half["opened_total"] = doctor.store.stats()["opened_total"]
        half["incidents"] = [
            {"id": i["id"], "rule": i["rule"], "cause": i["cause"]}
            for i in doctor.store.all()]

        if faulted:
            bundles_ok = True
            audit = []
            for inc in doctor.store.all():
                b = fstore.get(inc["id"])
                entry = {"id": inc["id"], "bundle": b is not None}
                if b is None:
                    bundles_ok = False
                else:
                    # the slice must cover the firing window: it starts
                    # at/before the (clock-anchored) open and holds at
                    # least one retained sample inside it
                    anchor = min(int(inc.get("opened_ms") or 0),
                                 b["captured_ms"])
                    covered = b["history"]["since_ms"] <= anchor
                    sampled = any(
                        b["history"]["since_ms"] <= s["ts_ms"]
                        <= b["captured_ms"]
                        for ss in b["history"]["series"].values()
                        for s in ss)
                    entry["covers_window"] = bool(covered and sampled)
                    bundles_ok = bundles_ok and covered and sampled
                audit.append(entry)
            half["bundle_audit"] = audit
            first = doctor.store.all()
            half["bundle"] = fstore.get(first[0]["id"]) if first else None
            half["ok"] = (t_trend is not None and t_page is not None
                          and t_trend < t_page and bundles_ok)
        else:
            half["ok"] = half["opened_total"] == 0
        return half
    finally:
        _prof.reset_kernel_handicap()
        for p, v in saved:
            if v is None:
                p.unset()
            else:
                p.set(v)
        if ds is not None:
            ds.close()


def main() -> int:
    artifact = os.environ.get("GEOMESA_TPU_DRILL_ARTIFACT")
    bundle = os.environ.get("GEOMESA_TPU_BUNDLE_ARTIFACT")
    report = run(artifact=artifact, bundle_artifact=bundle)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 3


if __name__ == "__main__":
    raise SystemExit(main())
