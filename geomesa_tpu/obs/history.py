"""Telemetry history plane: retained, queryable metric timelines.

Every other observability surface reports the *instantaneous* state —
cumulative counters, current gauges, ad-hoc windowed deltas recomputed
per doctor detector. This module retains timelines: a per-node sampler
snapshots selected registry series into wall-clock-aligned fixed-interval
ring tiers (Monarch-style coarse/fine retention, e.g. 2s x 10m and
30s x 2h), driven off the registry pre-drain hook so *producers pay
nothing* — a sample is taken at most once per finest-tier interval, and
only when somebody reads the registry anyway.

Stored values are chosen for lossless fleet merging (the workload /
metrics-federation idiom):

* counters  -> per-second RATE over the inter-sample gap (rates are
  additive, so the fleet timeline at a slot is the sum of node rates);
  the first sighting records a baseline only, mirroring the doctor's
  first-sighting immunity — history never fabricates a spike from a
  preexisting total.
* gauges    -> the level (merged by summing: fleet lag is the sum of
  per-node lag the same way ``/fleet/metrics`` sums gauges).
* timers    -> sparse log-bucket DELTAS per slot over the shared
  BUCKET_BOUNDS geometry; p50/p99 are derived at read time, and a
  fleet merge sums bucket counts, so merged percentiles are exactly
  what one process observing everything would report.

``merge_states`` builds the fleet timeline with *honest gap markers*:
a node that reports a series but is missing a slot after its own first
sample (a pinned scrape, a restart, a dropped tick) is named in that
slot's ``gap_nodes`` instead of being silently averaged away.

``SeriesStore`` is the doctor-facing half: raw (ts, value) series with
the exact windowed-delta semantics the doctor's detectors historically
kept in ad-hoc ``_delta`` state, plus the slope/projection helpers the
predictive ``slo_trend``/``capacity_trend`` rules consume.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu import metrics as _metrics

# Series the sampler tracks out of the box: the dials the doctor and the
# runbooks actually read. Extras ride GEOMESA_TPU_HISTORY_SERIES.
DEFAULT_COUNTERS = (
    "scheduler.queries",
    "admission.shed",
    "kernels.recompiles",
    "scheduler.deadline_cancelled",
    "wal.fsync_errors",
    "breaker.open",
)
DEFAULT_GAUGES = (
    "replication.lag_ms",
    "incident.active",
)
DEFAULT_TIMERS = (
    "query.count",
)

SPARK_CHARS = " ▁▂▃▄▅▆▇█"


def parse_tiers(spec: str) -> List[Tuple[int, int]]:
    """``"2:300,30:240"`` -> [(2, 300), (30, 240)] (interval_s, slots),
    sorted finest first; malformed entries are dropped rather than
    taking the sampler down with them."""
    tiers: List[Tuple[int, int]] = []
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            interval_s, slots = part.split(":")
            interval, n = max(1, int(interval_s)), max(2, int(slots))
        except (ValueError, TypeError):
            continue
        tiers.append((interval, n))
    tiers.sort()
    return tiers or [(2, 300), (30, 240)]


def sparkline(values: List[Optional[float]]) -> str:
    """ASCII sparkline; None (a gap) renders as '.' so a fleet timeline's
    holes stay visible in the terminal."""
    present = [v for v in values if v is not None]
    if not present:
        return "." * len(values)
    lo, hi = min(present), max(present)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append(".")
        else:
            idx = int((v - lo) / span * (len(SPARK_CHARS) - 1))
            out.append(SPARK_CHARS[max(0, min(idx, len(SPARK_CHARS) - 1))])
    return "".join(out)


def render_timeline(name: str, samples: List[dict],
                    field: str = "p99_ms") -> str:
    """One terminal line for a series: sparkline + last value + span —
    the ``debug timeline`` CLI row. Timer samples render their ``field``
    (p99 by default); merged fleet samples with ``gap_nodes`` render the
    slot as a gap when NO node contributed."""
    values: List[Optional[float]] = []
    for s in samples:
        v = s.get("value")
        if isinstance(v, dict):
            v = v.get(field)
        if v is None or (s.get("nodes") == 0):
            values.append(None)
            continue
        try:
            values.append(float(v))
        except (TypeError, ValueError):
            values.append(None)
    present = [v for v in values if v is not None]
    last = f"{present[-1]:.4g}" if present else "-"
    lo = f"{min(present):.4g}" if present else "-"
    hi = f"{max(present):.4g}" if present else "-"
    span_s = 0
    if len(samples) >= 2:
        span_s = int((samples[-1]["ts_ms"] - samples[0]["ts_ms"]) / 1000)
    gaps = sum(1 for s in samples if s.get("gap_nodes"))
    gap_note = f" gaps={gaps}" if gaps else ""
    return (f"{name:<36} {sparkline(values)} "
            f"last={last} min={lo} max={hi} span={span_s}s{gap_note}")


def _fit_slope(points: List[Tuple[float, float]]) -> float:
    """Least-squares slope (value units per second) over (ts_s, value)
    points; 0.0 when the fit is degenerate."""
    n = len(points)
    if n < 2:
        return 0.0
    mean_t = sum(p[0] for p in points) / n
    mean_v = sum(p[1] for p in points) / n
    num = sum((t - mean_t) * (v - mean_v) for t, v in points)
    den = sum((t - mean_t) ** 2 for t, _ in points)
    if den <= 0.0:
        return 0.0
    return num / den


def _timer_view(value: dict) -> dict:
    """Derived read-side view of a stored timer slot delta (p50/p99 from
    the shared bucket geometry, deterministic upper-bound percentiles)."""
    n = int(value.get("n", 0))
    total = float(value.get("total", 0.0))
    buckets = value.get("buckets") or {}
    view = {"n": n,
            "mean_ms": round(total / n * 1000, 3) if n else 0.0}
    for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
        if n <= 0:
            view[key] = 0.0
            continue
        rank = max(1, -(-int(q * n * 1000) // 1000))  # ceil without math
        rank = max(1, min(n, rank))
        cum = 0
        p = 0.0
        for bi in sorted(int(i) for i in buckets):
            cum += int(buckets[str(bi)] if str(bi) in buckets
                       else buckets[bi])
            if cum >= rank:
                p = _metrics.BUCKET_BOUNDS[
                    min(bi, len(_metrics.BUCKET_BOUNDS) - 1)]
                break
        else:
            p = _metrics.BUCKET_BOUNDS[-1]
        view[key] = round(p * 1000, 3)
    return view


def _merge_timer(a: dict, b: dict) -> dict:
    buckets = dict(a.get("buckets") or {})
    for bi, c in (b.get("buckets") or {}).items():
        key = str(bi)
        buckets[key] = buckets.get(key, 0) + int(c)
    return {"n": int(a.get("n", 0)) + int(b.get("n", 0)),
            "total": float(a.get("total", 0.0)) + float(b.get("total", 0.0)),
            "buckets": buckets}


class _Tier:
    """One retention tier: wall-clock-aligned slots at a fixed interval,
    at most one sample per slot per series, newest ``slots`` kept."""

    __slots__ = ("interval", "slots", "series", "kinds", "last_slot",
                 "_prev")

    def __init__(self, interval: int, slots: int):
        self.interval = int(interval)
        self.slots = int(slots)
        # name -> deque of [slot_start_s, value]
        self.series: Dict[str, deque] = {}
        self.kinds: Dict[str, str] = {}
        self.last_slot = -1
        # counter/timer cumulative baselines: name -> (ts_s, cumulative)
        self._prev: Dict[str, Tuple[float, object]] = {}

    def _push(self, name: str, kind: str, slot: int, value) -> None:
        ring = self.series.get(name)
        if ring is None:
            ring = self.series[name] = deque(maxlen=self.slots)
            self.kinds[name] = kind
        if ring and ring[-1][0] == slot:
            ring[-1][1] = value     # same slot resampled: last write wins
        else:
            ring.append([slot, value])

    def record(self, now: float, counters: Dict[str, float],
               gauges: Dict[str, float], timers: Dict[str, dict]) -> bool:
        slot = (int(now) // self.interval) * self.interval
        if slot == self.last_slot:
            return False
        self.last_slot = slot
        for name, cur in counters.items():
            prev = self._prev.get(name)
            self._prev[name] = (now, float(cur))
            if prev is None:
                continue            # first sighting: baseline only
            dt = now - prev[0]
            if dt <= 0.0:
                continue
            rate = max(0.0, (float(cur) - float(prev[1]))) / dt
            self._push(name, "counter", slot, rate)
        for name, cur in gauges.items():
            try:
                self._push(name, "gauge", slot, float(cur))
            except (TypeError, ValueError):
                continue
        for name, st in timers.items():
            prev = self._prev.get("t:" + name)
            cum_buckets = {str(k): int(v)
                           for k, v in (st.get("buckets") or {}).items()}
            cum = (int(st.get("count", 0)), float(st.get("total", 0.0)),
                   cum_buckets)
            self._prev["t:" + name] = (now, cum)
            if prev is None:
                continue
            _, (pc, pt, pb) = prev
            dn = cum[0] - pc
            if dn < 0:              # registry reset: re-baseline
                continue
            dbuckets = {}
            for bi, c in cum_buckets.items():
                d = c - pb.get(bi, 0)
                if d > 0:
                    dbuckets[bi] = d
            self._push(name, "timer", slot,
                       {"n": dn, "total": max(0.0, cum[1] - pt),
                        "buckets": dbuckets})
        return True


class TelemetryHistory:
    """The per-node history sampler + query surface. One global instance
    (``HISTORY``) rides the obs pre-drain chain; tests build their own
    with an injected clock."""

    def __init__(self, clock: Callable[[], float] = time.time,
                 tiers: Optional[List[Tuple[int, int]]] = None,
                 registry=None):
        self._clock = clock
        self._reg = registry if registry is not None else _metrics.REGISTRY
        self._tiers = [_Tier(i, n) for i, n in
                       (tiers if tiers is not None
                        else parse_tiers(config.HISTORY_TIERS.get()))]
        self._lock = threading.Lock()
        self._sampling = threading.local()
        self._next_sample = 0.0
        self.samples_taken = 0
        self.series_dropped = 0

    # -- series selection ------------------------------------------------

    def _extra_names(self) -> List[str]:
        return [p.strip() for p in
                str(config.HISTORY_SERIES.get() or "").split(",")
                if p.strip()]

    def _select(self, state: dict):
        """Pick the tracked (counters, gauges, timers) out of a registry
        export_state payload, honoring the HISTORY_MAX_SERIES bound."""
        extras = self._extra_names()
        cap = max(1, int(config.HISTORY_MAX_SERIES.get()))
        counters, gauges, timers = {}, {}, {}
        budget = [cap]

        def _take(out, pool, name):
            if name in out or name not in pool:
                return
            if budget[0] <= 0:
                self.series_dropped += 1
                return
            budget[0] -= 1
            out[name] = pool[name]

        c_pool = state.get("counters") or {}
        g_pool = state.get("gauges") or {}
        t_pool = state.get("timers") or {}
        for name in DEFAULT_COUNTERS:
            _take(counters, c_pool, name)
        for name in DEFAULT_GAUGES:
            _take(gauges, g_pool, name)
        for name in DEFAULT_TIMERS:
            _take(timers, t_pool, name)
        for pat in extras:
            if pat.endswith("."):
                for pool, out in ((c_pool, counters), (g_pool, gauges),
                                  (t_pool, timers)):
                    for name in sorted(pool):
                        if name.startswith(pat):
                            _take(out, pool, name)
            else:
                for pool, out in ((c_pool, counters), (g_pool, gauges),
                                  (t_pool, timers)):
                    _take(out, pool, pat)
        return counters, gauges, timers

    # -- sampling --------------------------------------------------------

    def maybe_sample(self) -> bool:
        """Pre-drain hook entry: self-throttles to the finest tier
        interval with a bare clock compare, so the common drain path
        pays one float comparison. Reentrancy-guarded — taking a sample
        reads the registry, which re-enters the pre-drain chain."""
        if not config.HISTORY_ENABLED.get():
            return False
        if getattr(self._sampling, "busy", False):
            return False
        now = self._clock()
        if now < self._next_sample:
            return False
        return self.sample_now(now)

    def sample_now(self, now: Optional[float] = None) -> bool:
        if getattr(self._sampling, "busy", False):
            return False
        self._sampling.busy = True
        try:
            if now is None:
                now = self._clock()
            state = self._reg.export_state()
            counters, gauges, timers = self._select(state)
            took = False
            with self._lock:
                finest = self._tiers[0].interval if self._tiers else 2
                self._next_sample = (int(now) // finest + 1) * finest
                for tier in self._tiers:
                    if tier.record(now, counters, gauges, timers):
                        took = True
                if took:
                    self.samples_taken += 1
            return took
        finally:
            self._sampling.busy = False

    # -- queries ---------------------------------------------------------

    def _pick_tier(self, tier_s: Optional[int]) -> Optional[_Tier]:
        if not self._tiers:
            return None
        if tier_s is None:
            return self._tiers[0]
        for t in self._tiers:
            if t.interval == int(tier_s):
                return t
        return min(self._tiers, key=lambda t: abs(t.interval - int(tier_s)))

    def range(self, name: str, since_ms: float = 0,
              tier: Optional[int] = None) -> List[dict]:
        """Retained samples for a series at/after ``since_ms`` wall time,
        oldest first: [{"ts_ms", "value"}]; timer values carry the
        derived n/mean/p50/p99 view."""
        t = self._pick_tier(tier)
        if t is None:
            return []
        with self._lock:
            ring = list(t.series.get(name) or ())
            kind = t.kinds.get(name, "gauge")
        floor_s = float(since_ms) / 1000.0
        out = []
        for slot, value in ring:
            if slot < floor_s:
                continue
            if kind == "timer":
                value = _timer_view(value)
            out.append({"ts_ms": int(slot * 1000), "value": value})
        return out

    def slope(self, name: str, since_ms: float = 0,
              tier: Optional[int] = None,
              field: Optional[str] = None) -> float:
        """Least-squares trend of a series (value units per second) over
        the retained window; ``field`` picks a component of a timer view
        (e.g. ``p99_ms``)."""
        pts = []
        for sample in self.range(name, since_ms=since_ms, tier=tier):
            v = sample["value"]
            if isinstance(v, dict):
                v = v.get(field or "p99_ms", 0.0)
            try:
                pts.append((sample["ts_ms"] / 1000.0, float(v)))
            except (TypeError, ValueError):
                continue
        return _fit_slope(pts)

    def series_names(self) -> List[str]:
        with self._lock:
            names = set()
            for t in self._tiers:
                names.update(t.series)
        return sorted(names)

    def memory_bytes(self) -> int:
        """Honest bookkeeping estimate of retained-sample memory: ~64B
        per scalar sample, plus 32B per sparse timer bucket. The bound
        cfg17 reports and the knob table documents."""
        total = 0
        with self._lock:
            for t in self._tiers:
                for name, ring in t.series.items():
                    for _, value in ring:
                        if isinstance(value, dict):
                            total += 64 + 32 * len(value.get("buckets") or ())
                        else:
                            total += 64
        return total

    def summary(self) -> dict:
        with self._lock:
            tiers = [{"interval_s": t.interval, "slots": t.slots,
                      "series": len(t.series)} for t in self._tiers]
        return {"enabled": bool(config.HISTORY_ENABLED.get()),
                "tiers": tiers,
                "series": self.series_names(),
                "samples_taken": self.samples_taken,
                "series_dropped": self.series_dropped,
                "memory_bytes": self.memory_bytes()}

    def export_state(self) -> dict:
        """Mergeable history state for the ``/metrics?format=state``
        scrape — equal-tier rings merge across nodes in the federator."""
        out = []
        with self._lock:
            for t in self._tiers:
                series = {}
                for name, ring in t.series.items():
                    series[name] = {"kind": t.kinds.get(name, "gauge"),
                                    "samples": [[slot, value]
                                                for slot, value in ring]}
                out.append({"interval_s": t.interval, "slots": t.slots,
                            "series": series})
        return {"tiers": out}

    def reset(self) -> None:
        with self._lock:
            for t in self._tiers:
                t.series.clear()
                t.kinds.clear()
                t._prev.clear()
                t.last_slot = -1
            self.samples_taken = 0
            self.series_dropped = 0
            self._next_sample = 0.0


def merge_states(states: List[dict],
                 node_names: Optional[List[str]] = None) -> dict:
    """Merge equal-tier history states from several nodes into fleet
    timelines with honest per-node gap markers.

    For each tier (matched by interval) and series, the merged ring holds
    one entry per slot any node reported. ``nodes`` counts contributors;
    ``gap_nodes`` names nodes that track the series (they have at least
    one sample at/before the slot) but are missing this one — a pinned
    scrape or dropped tick shows up as named gaps on the newest slots
    instead of silently deflating the fleet sum."""
    if node_names is None:
        node_names = [f"node{i}" for i in range(len(states))]
    tiers: Dict[int, dict] = {}
    for node, state in zip(node_names, states):
        for tstate in (state or {}).get("tiers", []):
            try:
                interval = int(tstate.get("interval_s", 0))
            except (TypeError, ValueError):
                continue
            if interval <= 0:
                continue
            agg = tiers.setdefault(interval, {
                "interval_s": interval,
                "slots": int(tstate.get("slots", 0)),
                "series": {}})
            agg["slots"] = max(agg["slots"], int(tstate.get("slots", 0)))
            for name, sdata in (tstate.get("series") or {}).items():
                samples = sdata.get("samples") or []
                if not samples:
                    continue
                dst = agg["series"].setdefault(
                    name, {"kind": sdata.get("kind", "gauge"),
                           "per_node": {}})
                dst["per_node"][node] = {
                    float(s[0]): s[1] for s in samples if len(s) == 2}
    merged_tiers = []
    for interval in sorted(tiers):
        agg = tiers[interval]
        series_out = {}
        for name, dst in agg["series"].items():
            kind = dst["kind"]
            per_node = dst["per_node"]
            all_slots = sorted({s for m in per_node.values() for s in m})
            first_seen = {node: min(m) for node, m in per_node.items()}
            merged = []
            for slot in all_slots:
                value = None
                contributing = 0
                gap_nodes = []
                for node, m in per_node.items():
                    if slot in m:
                        contributing += 1
                        v = m[slot]
                        if value is None:
                            value = (dict(v) if isinstance(v, dict)
                                     else float(v))
                        elif kind == "timer":
                            value = _merge_timer(value, v)
                        else:
                            value = float(value) + float(v)
                    elif first_seen[node] <= slot:
                        gap_nodes.append(node)
                if kind == "timer" and isinstance(value, dict):
                    value = _timer_view(value)
                merged.append({"ts_ms": int(slot * 1000), "value": value,
                               "nodes": contributing,
                               "gap_nodes": sorted(gap_nodes)})
            series_out[name] = {"kind": kind, "samples": merged}
        merged_tiers.append({"interval_s": interval,
                             "slots": agg["slots"],
                             "series": series_out})
    return {"tiers": merged_tiers}


class SeriesStore:
    """Raw (ts, value) series with the doctor's windowed-delta semantics
    — the migration target for the ad-hoc ``_delta`` deques every
    windowed detector used to keep, plus the slope/projection helpers
    the predictive rules consume. Each DoctorEngine owns ONE (test
    isolation: a shared global would fire fresh doctors on preexisting
    totals)."""

    def __init__(self, maxlen: int = 512):
        self._maxlen = maxlen
        self._series: Dict[str, deque] = {}
        self._lock = threading.Lock()

    def observe(self, name: str, value: float, now: float,
                window_s: float = 3600.0) -> None:
        with self._lock:
            samples = self._series.setdefault(
                name, deque(maxlen=self._maxlen))
            samples.append((float(now), float(value)))
            while samples and now - samples[0][0] > window_s:
                samples.popleft()

    def _window(self, name: str, now: float,
                window_s: float) -> List[Tuple[float, float]]:
        with self._lock:
            samples = self._series.get(name)
            if not samples:
                return []
            return [(t, v) for t, v in samples if now - t <= window_s]

    def window(self, name: str, now: float,
               window_s: float) -> Tuple[float, float]:
        """(per-minute rate, absolute delta) over the trailing window.
        Fewer than two samples -> (0, 0): the first sighting of a
        counter contributes no delta, so a fresh doctor never fires on
        preexisting totals."""
        pts = self._window(name, now, window_s)
        if len(pts) < 2:
            return 0.0, 0.0
        dt = pts[-1][0] - pts[0][0]
        dv = pts[-1][1] - pts[0][1]
        if dt <= 0.0:
            return 0.0, dv
        return dv * 60.0 / dt, dv

    def slope(self, name: str, now: float, window_s: float) -> float:
        """Least-squares trend (units per second) over the window."""
        return _fit_slope(self._window(name, now, window_s))

    def points(self, name: str, now: float, window_s: float) -> int:
        return len(self._window(name, now, window_s))

    def last(self, name: str) -> Optional[float]:
        with self._lock:
            samples = self._series.get(name)
            return samples[-1][1] if samples else None

    def clear(self) -> None:
        with self._lock:
            self._series.clear()


HISTORY = TelemetryHistory()
