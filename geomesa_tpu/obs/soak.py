"""Deterministic doctor soak: proven detection precision, both ways.

The doctor's value claim is PRECISION — every injected fault produces
exactly one incident naming the correct cause, and clean traffic
produces none. This module proves both halves with the same miniature
fleet the replication drills use (an in-process primary + follower over
real localhost shipping sockets, counts through the real scheduler),
driven by the existing deterministic fault hooks:

  lag_spike        faults.arm_serve_delay("repl.apply")   -> replication_lag
  replica_kill     faults.arm_serve_crash("repl.apply")   -> replication_lag
                   (a NEW incident: the spike's one must resolve first)
  kernel_handicap  profiling.arm_kernel_handicap          -> slo_burn
  shed_burst       tight admission + slow device rounds   -> shed_storm

``run_soak(faulted=False)`` replays the same traffic shapes with no
fault armed and requires ZERO incidents (the false-positive guard).

Determinism notes:
  * the soak's SLO objective is count latency at target 0.99 with a
    threshold calibrated off the measured warm count — one unavoidable
    cold-compile outlier (the fresh type each half creates) stays far
    under the ticket burn bar, while the handicapped counts blow past
    the page bar
  * availability is NOT an objective here: a shed burst must be
    attributed by the shed_storm detector alone, not double-reported
    as an availability burn
  * skew/recompile detectors get out-of-reach bars: single-plan
    synthetic traffic IS skewed and fresh per-phase kernels DO compile
    — correct firings, but not the causes under test
  * ``REPL_TRACE_EVERY=1`` retains every apply trace, so replication
    incidents link real cross-process trace gids
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Callable, List, Optional

from geomesa_tpu import config
from geomesa_tpu.durability import faults
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.obs.doctor import DoctorEngine

_BOX = "BBOX(geom, -5, -5, 5, 5)"


def _drive(doctor: DoctorEngine, seconds: float,
           until: Optional[Callable[[], bool]] = None,
           traffic: Optional[Callable[[], None]] = None,
           period_s: float = 0.1) -> bool:
    """Evaluate the doctor on a cadence (optionally generating traffic
    between evaluations) until ``until()`` holds or time runs out."""
    deadline = time.monotonic() + seconds
    while True:
        if traffic is not None:
            traffic()
        doctor.evaluate()
        if until is not None and until():
            return True
        if time.monotonic() >= deadline:
            return until is None
        time.sleep(period_s)


def _new_incidents(doctor: DoctorEngine, seen_ids: set) -> List[dict]:
    return [i for i in doctor.store.all() if i["id"] not in seen_ids]


def _phase_report(name: str, rule: str, fresh: List[dict],
                  resolved: Optional[bool] = None) -> dict:
    """Score one injection: exactly one new incident, correct rule, at
    least one linked trace gid or flight event in its timeline."""
    rep = {"name": name, "expected_rule": rule,
           "new_incidents": [{"id": i["id"], "rule": i["rule"],
                              "cause": i["cause"],
                              "severity": i["severity"]} for i in fresh],
           "exactly_one": len(fresh) == 1,
           "rule_correct": bool(fresh) and
           all(i["rule"] == rule for i in fresh)}
    tl = fresh[0].get("timeline") if fresh else {}
    rep["evidence"] = bool((tl or {}).get("trace_gids")
                           or (tl or {}).get("events"))
    if resolved is not None:
        rep["resolved"] = resolved
    rep["ok"] = bool(rep["exactly_one"] and rep["rule_correct"]
                     and rep["evidence"]
                     and (resolved is None or resolved))
    return rep


def run_soak(base_dir: str, faulted: bool = True,
             journal_path: Optional[str] = None) -> dict:
    """One soak half. ``faulted=True`` injects all four faults and
    requires one correctly-attributed incident each; ``faulted=False``
    replays the same traffic shapes and requires zero incidents."""
    from geomesa_tpu.obs import profiling as _prof
    from geomesa_tpu.obs import slo as _slo
    from geomesa_tpu.replication.drills import _mk_primary, make_batch, SPEC
    from geomesa_tpu.replication.follower import Follower
    from geomesa_tpu.serve.resilience.admission import ShedError
    from geomesa_tpu.serve.scheduler import QueryScheduler, StoreBinding

    faults.reset()
    _prof.reset_kernel_handicap()
    knobs = [(config.DOCTOR_WINDOW_S, 20.0),
             (config.DOCTOR_LAG_MS, 350.0),
             (config.DOCTOR_LAG_SEQS, 10 ** 9),
             (config.DOCTOR_SHED_PER_MIN, 20.0),
             (config.DOCTOR_RECOMPILES_PER_MIN, 10.0 ** 9),
             (config.DOCTOR_SKEW_MIN, 10 ** 9),
             (config.DOCTOR_CLEAR_TICKS, 2),
             (config.REPL_TRACE_EVERY, 1)]
    saved = [(p, p._override) for p, _ in knobs]
    for p, v in knobs:
        p.set(v)
    primary = shipper = follower = sched = None
    report: dict = {"faulted": faulted, "phases": {}, "ok": False,
                    "journal": journal_path}
    try:
        primary, shipper = _mk_primary(os.path.join(base_dir, "primary"))
        follower = Follower(os.path.join(base_dir, "replica"),
                            shipper.address, follower_id="r1")
        follower.wait_for_seq(primary.durability.wal.last_seq)

        # calibrate the latency objective off the measured warm path so
        # the same soak passes on a fast laptop and a loaded CI runner
        for _ in range(4):
            primary.count("t", _BOX)
        t0 = time.perf_counter()
        for _ in range(4):
            primary.count("t", _BOX)
        warm_ms = (time.perf_counter() - t0) * 250.0  # mean of 4, in ms
        threshold_ms = max(60.0, 20.0 * warm_ms)

        # warm the scheduler's batched path too — its first burst compiles
        # coalesced-shape kernels, and those one-time stalls must land
        # BEFORE the SLO baseline or they read as a clean-run burn
        sched = QueryScheduler(StoreBinding(primary), flush_size=4,
                               window_us=200)

        def run_burst(collect_sheds: bool):
            sheds: List[BaseException] = []
            lock = threading.Lock()
            start = threading.Event()

            def one(_i):
                start.wait()
                try:
                    sched.count("t", _BOX, timeout=30)
                except ShedError as e:
                    if collect_sheds:
                        with lock:
                            sheds.append(e)

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            start.set()
            for t in threads:
                t.join()
            return sheds

        run_burst(collect_sheds=False)
        engine = _slo.SloEngine(registry=_metrics)
        engine.add(_slo.Objective(
            name="count_latency", kind="latency", target=0.99,
            timer="query.count", threshold_ms=threshold_ms))
        doctor = DoctorEngine(registry=_metrics, slo_engine=engine,
                              journal_path=journal_path or "",
                              federator=False)
        report["threshold_ms"] = round(threshold_ms, 1)
        doctor.evaluate()  # the windows' baseline sample

        def count_traffic():
            primary.count("t", _BOX)

        def active(rule):
            return [i for i in doctor.store.active()
                    if i["rule"] == rule]

        # ---- phase 1: lag spike (or clean load) -------------------------
        seen = {i["id"] for i in doctor.store.all()}
        if faulted:
            faults.arm_serve_delay("repl.apply", seconds=1.2, n=1)
        primary.load("t", make_batch(primary.schemas["t"], 1))
        _drive(doctor, 6.0, traffic=count_traffic,
               until=(lambda: bool(active("replication_lag")))
               if faulted else None)
        faults.reset()
        follower.wait_for_seq(primary.durability.wal.last_seq, timeout=10)
        resolved = _drive(doctor, 8.0, traffic=count_traffic,
                          until=lambda: not active("replication_lag"))
        if faulted:
            report["phases"]["lag_spike"] = _phase_report(
                "lag_spike", "replication_lag",
                _new_incidents(doctor, seen), resolved=resolved)

        # ---- phase 2: replica kill (or clean load + restart) ------------
        seen = {i["id"] for i in doctor.store.all()}
        if faulted:
            faults.arm_serve_crash("repl.apply", at=1)
        primary.load("t", make_batch(primary.schemas["t"], 2))
        if faulted:
            _drive(doctor, 2.0, until=lambda: follower.dead)
            _drive(doctor, 6.0, traffic=count_traffic,
                   until=lambda: bool(active("replication_lag")))
            fresh = _new_incidents(doctor, seen)
            faults.reset()
            follower.close()
            follower = Follower(os.path.join(base_dir, "replica"),
                                shipper.address, follower_id="r1")
        else:
            _drive(doctor, 2.0, traffic=count_traffic)
            fresh = []
        follower.wait_for_seq(primary.durability.wal.last_seq, timeout=15)
        resolved = _drive(doctor, 8.0, traffic=count_traffic,
                          until=lambda: not active("replication_lag"))
        if faulted:
            report["phases"]["replica_kill"] = _phase_report(
                "replica_kill", "replication_lag", fresh,
                resolved=resolved)

        # ---- phase 3: kernel handicap (or clean fresh type) -------------
        seen = {i["id"] for i in doctor.store.all()}
        if faulted:
            # kernels compiled AFTER arming carry the stretch — the fresh
            # type's count kernels compile inside the handicap
            _prof.arm_kernel_handicap("count.", 2000.0)
        primary.create_schema("h", SPEC)
        primary.load("h", make_batch(primary.schemas["h"], 3))
        for _ in range(14):
            primary.count("h", _BOX)
            doctor.evaluate()
        _prof.reset_kernel_handicap()
        if faulted:
            _drive(doctor, 4.0,
                   until=lambda: bool(active("slo_burn")))
            report["phases"]["kernel_handicap"] = _phase_report(
                "kernel_handicap", "slo_burn",
                _new_incidents(doctor, seen))

        # ---- phase 4: shed burst (or clean concurrent burst) ------------
        seen = {i["id"] for i in doctor.store.all()}
        doctor.evaluate()
        if faulted:
            config.ADMIT_INTERACTIVE.set(2)
            faults.arm_serve_delay("sched.device_wait", seconds=0.05,
                                   n=1000)
        sheds = run_burst(collect_sheds=True)
        faults.reset()
        config.ADMIT_INTERACTIVE.unset()
        _drive(doctor, 4.0,
               until=(lambda: bool(active("shed_storm")))
               if faulted else None, traffic=None)
        if faulted:
            report["phases"]["shed_burst"] = _phase_report(
                "shed_burst", "shed_storm", _new_incidents(doctor, seen))
            report["phases"]["shed_burst"]["sheds"] = len(sheds)

        # ---- verdict ----------------------------------------------------
        report["incidents"] = doctor.store.all()
        if faulted:
            report["ok"] = all(p.get("ok")
                               for p in report["phases"].values())
        else:
            opened = doctor.store.stats()["opened_total"]
            report["opened_total"] = opened
            report["ok"] = opened == 0
        _metrics.inc("drill.doctor_soak.runs")
        if report["ok"]:
            _metrics.inc("drill.doctor_soak.passed")
        return report
    finally:
        faults.reset()
        _prof.reset_kernel_handicap()
        config.ADMIT_INTERACTIVE.unset()
        for p, old in saved:
            if old is None:
                p.unset()
            else:
                p.set(old)
        if sched is not None:
            sched.shutdown(timeout=5)
        if follower is not None:
            try:
                follower.close()
            except Exception:
                pass
        if primary is not None:
            primary.close()
        # CI artifact: the incident timeline journal, copied wherever the
        # workflow wants it uploaded from
        art = os.environ.get("GEOMESA_TPU_SOAK_ARTIFACT")
        if art and journal_path and os.path.exists(journal_path):
            try:
                suffix = "faulted" if faulted else "clean"
                shutil.copyfile(journal_path, f"{art}.{suffix}.jsonl")
            except OSError:
                pass
