"""Cluster-cell chaos soak: shard-routed writes + scatter-gather reads
under cell failover, mid-ingest ownership handoff, split-brain refusal
and a fully dark shard — the cfg16 gate.

Topology (all real subprocesses, like obs/soakfleet):

    router (shard-aware scatter-gather, tools/cli `router --shard ...`)
      ├── cell s0  keys [0, MID)   = s0p (primary) + s0r (replica)
      └── cell s1  keys [MID, TOP] = s1p (primary) + s1r (replica)

Every write goes through the router's POST /types/t/features and is
split by Morton key ownership (cluster/cells.geo_key); every read is a
scatter-gather count whose envelope must flip ``partial: true`` +
``missing_shards`` the moment a cell goes dark — and never otherwise.

Chaos half (two-sided, like cfg11/cfg12: each fault must be DETECTED
where expected and NOTHING may fire anywhere else):

  steady        routed writes land on their owning cells, counts exact
  cell_failover SIGKILL s0's primary: reads keep answering (follower =
                demoted-not-dropped), the dark cell's write sub-batch is
                refused loudly, /promote?shard=s0 flips the follower to
                primary inside GEOMESA_TPU_REPL_FAILOVER_BUDGET_MS, and
                the resurrected ex-primary is fenced before it rejoins
  handoff       /handoff?shard=s1 mid-ingest: drain + fence the old
                owner BEFORE the successor accepts (cells.hand_off)
  split_brain   both fenced losers (one per cell) take a direct write
                and BOTH must refuse with 403 {"kind": "fenced"} while
                the routed path still lands every row
  shard_dark    kill BOTH s0 members: the doctor opens exactly one
                ``shard_dark`` incident naming the key range + members,
                scatter reads answer partial with the missing range,
                and the incident resolves once the cell is respawned
  recovery      full-fleet catch-up, counts exact again

Clean half replays routed writes + reads with zero faults and requires
ZERO incidents.  Both halves end with conservation: the routed count
equals every acked write and the per-cell WAL-codec fingerprints of
primary and replica stores are byte-identical (zero acked-write loss).

The orchestrator watches the fleet through its OWN in-process
ReplicaRouter (HttpEndpoints + the same ShardCells topology) handed to
DoctorEngine(router=...), with every other detector bar parked at 1e12
— so precision/recall against the fault schedule is deterministic and
only ``shard_dark`` can ever fire.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from geomesa_tpu import config
from geomesa_tpu.obs.soakfleet import (_NoWorkload, _Traffic, _free_port,
                                       _http, _wait_http, percentile_ms,
                                       score_phases)

SCOREBOARD_DEFAULT = "SOAKCELLS_scoreboard.json"

# most recent scoreboard (GET /cluster/soak and bench cfg16 read this)
LAST: Optional[dict] = None


def _log(msg: str) -> None:
    if os.environ.get("GEOMESA_TPU_SOAK_VERBOSE"):
        print(f"[soakcells +{time.monotonic() % 100000:.1f}] {msg}",
              file=sys.stderr, flush=True)


def last_run() -> Optional[dict]:
    return LAST


class CellSoak:
    """One soak half over a real two-cell subprocess cluster."""

    def __init__(self, base_dir: str, faulted: bool = True,
                 mini: bool = True):
        self.base = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.faulted = faulted
        self.mini = mini
        scale = 1.0 if mini else 3.0
        self.phase_s = float(config.SOAK_PHASE_S.get()) * scale
        self.wait_s = float(config.SOAK_WAIT_S.get())
        bits = int(config.CELL_GEO_KEY_BITS.get())
        self.mid = 1 << (2 * bits - 1)    # east/west hemisphere split
        self.top = (1 << (2 * bits)) - 1
        self.ranges = {"s0": (0, self.mid - 1),
                       "s1": (self.mid, self.top)}
        # current ROLE map — flips on failover/handoff; membership is
        # fixed (s0p/s0r always belong to cell s0)
        self.primary = {"s0": "s0p", "s1": "s1p"}
        self.replica = {"s0": "s0r", "s1": "s1r"}
        self.procs: Dict[str, subprocess.Popen] = {}
        self.ports: Dict[str, int] = {}
        self.ship_ports: Dict[str, int] = {}
        self.dirs: Dict[str, str] = {}
        self.router_port = 0
        self.rows = 0
        self.acked = 0
        self._wb = 0
        self.doctor = None
        self.obs_router = None
        self.traffic: Optional[_Traffic] = None
        self.phases: List[dict] = []
        self._seen: set = set()
        self.failover: Optional[dict] = None
        self.handoff_report: Optional[dict] = None
        self.split_brain = {"refusals": 0, "attempts": []}
        self.dark: Optional[dict] = None
        self.partial_envelope: Optional[dict] = None
        self.counts: List[dict] = []
        self.notes: List[str] = []

    # -- process management ---------------------------------------------------

    def _nodes(self) -> List[str]:
        return ["s0p", "s0r", "s1p", "s1r"]

    def _cell_spec(self, shard: str) -> str:
        lo, hi = self.ranges[shard]
        return f"{shard}={lo}:{hi}"

    def _member_spec(self, shard: str) -> str:
        p, r = sorted([self.primary[shard], self.replica[shard]])
        return f"{self._cell_spec(shard)}={p},{r}"

    def _spawn(self, args: List[str],
               extra_env: Optional[dict] = None) -> subprocess.Popen:
        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable, "-m", "geomesa_tpu.tools.cli", *args],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)

    def _node_env(self, name: str) -> dict:
        return {"GEOMESA_TPU_NODE_ID": name,
                "GEOMESA_TPU_REPL_TRACE_EVERY": "1",
                "GEOMESA_TPU_REPL_ACK_EVERY": "1"}

    def _alive(self, name: str) -> bool:
        p = self.procs.get(name)
        return p is not None and p.poll() is None

    def _signal(self, name: str, sig: int, wait_s: float = 20.0) -> None:
        p = self.procs.get(name)
        if p is None or p.poll() is not None:
            return
        p.send_signal(sig)
        try:
            p.wait(timeout=wait_s)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10.0)

    def _spawn_primary(self, shard: str, name: str,
                       ship_port: Optional[int] = None) -> None:
        """Spawn (or resurrect) ``name`` as cell ``shard``'s durable
        primary.  First spawn seeds the schema offline."""
        d = self.dirs.setdefault(name, os.path.join(self.base, name))
        if not os.path.exists(d):
            from geomesa_tpu.datastore import TpuDataStore
            from geomesa_tpu.replication.drills import SPEC
            store = TpuDataStore.open(d, params={"wal.fsync": "off"})
            try:
                store.create_schema("t", SPEC)
            finally:
                store.close()
        sp = ship_port or _free_port()
        wp = self.ports.get(name) or _free_port()
        self.ship_ports[name] = sp
        self.ports[name] = wp
        self.procs[name] = self._spawn(
            ["serve", "-s", d, "--durable",
             "--ship-port", str(sp), "--port", str(wp),
             "--cell", self._cell_spec(shard)],
            self._node_env(name))
        _wait_http(wp)

    def _spawn_replica(self, shard: str, name: str,
                       follow_port: int, wait: bool = True) -> None:
        d = self.dirs.setdefault(name, os.path.join(self.base, name))
        port = self.ports.get(name) or _free_port()
        self.ports[name] = port
        self.procs[name] = self._spawn(
            ["replica", "--dir", d, "--follow",
             f"127.0.0.1:{follow_port}", "--port", str(port),
             "--id", name, "--cell", self._cell_spec(shard)],
            self._node_env(name))
        if wait:
            _wait_http(port)

    def _spawn_router(self) -> None:
        self.router_port = _free_port()
        args = ["router", "--port", str(self.router_port)]
        for n in self._nodes():
            args += ["--endpoint", f"{n}=127.0.0.1:{self.ports[n]}"]
        for shard in ("s0", "s1"):
            args += ["--shard", self._member_spec(shard)]
        self.procs["router"] = self._spawn(
            args, {"GEOMESA_TPU_NODE_ID": "router"})
        _wait_http(self.router_port)

    def _mk_doctor(self) -> None:
        """The orchestrator's own observation plane: an in-process
        shard-aware router over the same endpoints + topology, so the
        doctor's shard_dark detector sees what the fleet router sees."""
        from geomesa_tpu.cluster.cells import ShardCells
        from geomesa_tpu.metrics import MetricsRegistry
        from geomesa_tpu.obs.doctor import DoctorEngine
        from geomesa_tpu.serve.router import HttpEndpoint, ReplicaRouter
        eps = [HttpEndpoint(n, f"http://127.0.0.1:{self.ports[n]}",
                            timeout_s=2.0) for n in self._nodes()]
        topo = ShardCells.from_specs([self._member_spec("s0"),
                                      self._member_spec("s1")])
        self.obs_router = ReplicaRouter(eps, topology=topo)
        self.doctor = DoctorEngine(
            registry=MetricsRegistry(),
            slo_engine=False,
            journal_path=os.path.join(self.base, "cells_doctor.jsonl"),
            federator=False,
            workload=_NoWorkload(),
            router=self.obs_router)

    def start(self) -> None:
        for shard in ("s0", "s1"):
            self._spawn_primary(shard, self.primary[shard])
            self._spawn_replica(shard, self.replica[shard],
                                self.ship_ports[self.primary[shard]])
        self._spawn_router()
        self._mk_doctor()
        # warm the routed read path before traffic starts sampling
        for _ in range(3):
            self._count_routed()
        self.traffic = _Traffic(self.router_port, period_s=0.02)
        self.traffic.start()

    # -- writes / reads / catch-up --------------------------------------------

    def _write_batch(self, n: int = 40) -> dict:
        """One routed write through the fleet router.  The x grid spans
        both hemispheres so every batch splits across both cells; only
        rows the envelope reports WRITTEN count as acked."""
        i = self._wb
        self._wb += 1
        feats = []
        for j in range(n):
            x = -9.5 + ((i * 7 + j * 19) % 190) * 0.1
            y = -9.5 + ((i * 11 + j * 3) % 190) * 0.1
            feats.append({
                "type": "Feature", "id": f"c{i}_{j}",
                "geometry": {"type": "Point",
                             "coordinates": [round(x, 3), round(y, 3)]},
                "properties": {"name": "abc"[j % 3], "v": (i + j) % 100,
                               "dtg": "2024-01-01T06:00:00"}})
        body = json.dumps({"type": "FeatureCollection",
                           "features": feats}).encode()
        try:
            env = _http(self.router_port, "/types/t/features",
                        method="POST", body=body, timeout=30.0)
        except urllib.error.HTTPError as e:  # non-2xx: nothing acked
            return {"written": 0, "partial": True, "error": str(e)}
        got = int(env.get("written", 0))
        self.acked += got
        self.rows += got
        return env

    def _count_routed(self, timeout: float = 30.0) -> dict:
        return _http(self.router_port, "/types/t/count?cql=INCLUDE",
                     timeout=timeout)

    def _note_count(self, phase: str, env: dict) -> bool:
        exact = (int(env.get("count", -1)) == self.rows
                 and not env.get("partial"))
        self.counts.append({"phase": phase, "count": env.get("count"),
                            "expected": self.rows,
                            "partial": bool(env.get("partial")),
                            "exact": exact})
        return exact

    def _head_seq(self, name: str) -> Optional[int]:
        try:
            hz = _http(self.ports[name], "/healthz", timeout=2.0)
        except Exception:  # noqa: BLE001
            return None
        d = hz.get("durability") or {}
        if d.get("wal_seq") is not None:
            return int(d["wal_seq"])
        r = hz.get("replication") or {}
        v = r.get("applied_seq", r.get("last_seq"))
        return int(v) if v is not None else None

    def _wait_catchup(self, shards: Optional[List[str]] = None,
                      timeout_s: Optional[float] = None) -> bool:
        """Wait until each cell's replica has applied its primary's WAL
        head (always compared against the PRIMARY — a stalled follower
        can report zero lag against a stale view of the head)."""
        shards = shards or ["s0", "s1"]
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.wait_s)
        while time.monotonic() < deadline:
            ok = True
            for shard in shards:
                rep = self.replica[shard]
                if not self._alive(rep) or \
                        not self._alive(self.primary[shard]):
                    continue
                head = self._head_seq(self.primary[shard])
                if head is None:
                    ok = False
                    continue
                try:
                    r = _http(self.ports[rep], "/healthz",
                              timeout=2.0).get("replication") or {}
                    applied = r.get("applied_seq")
                    if not r.get("connected") or applied is None \
                            or int(applied) < head:
                        ok = False
                except Exception:  # noqa: BLE001
                    ok = False
            if ok:
                return True
            time.sleep(0.1)
        return False

    def _wait_synced(self, names: Optional[List[str]] = None,
                     timeout_s: float = 20.0) -> bool:
        names = [n for n in (names or self._nodes()) if self._alive(n)]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ok = True
            for n in names:
                try:
                    d = _http(self.ports[n], "/healthz",
                              timeout=2.0).get("durability") or {}
                    if d.get("enabled") and int(d.get("unsynced_bytes")
                                                or 0) > 0:
                        ok = False
                except Exception:  # noqa: BLE001
                    ok = False
            if ok:
                return True
            time.sleep(0.1)
        return False

    def _quiesce(self, shards: Optional[List[str]] = None) -> None:
        """Catch up + fsync so a subsequent SIGKILL cannot strand an
        acked row on exactly one node of a cell."""
        self._wait_catchup(shards)
        self._wait_synced()

    # -- doctor drive / phase machinery ---------------------------------------

    def _fresh(self) -> List[dict]:
        return [i for i in self.doctor.store.all()
                if i["id"] not in self._seen]

    def _open_rule(self, rule: str) -> bool:
        return any(i["rule"] == rule for i in self._fresh())

    def _all_resolved(self) -> bool:
        fresh = self._fresh()
        return bool(fresh) and all(i["status"] == "resolved"
                                   for i in fresh)

    def _drive(self, seconds: float,
               until: Optional[Callable[[], bool]] = None,
               period_s: float = 0.15) -> bool:
        deadline = time.monotonic() + seconds
        while True:
            self.doctor.evaluate()
            if until is not None and until():
                return True
            if time.monotonic() >= deadline:
                return until is None
            time.sleep(period_s)

    def _run_phase(self, name: str, expected_rule: Optional[str],
                   body: Callable[[], Optional[dict]]) -> dict:
        self._seen = {i["id"] for i in self.doctor.store.all()}
        if self.traffic is not None:
            self.traffic.set_phase(name)
        _log(f"phase {name} start")
        t0 = time.monotonic()
        extra = body() or {}
        dur = time.monotonic() - t0
        fresh = self._fresh()
        lat = self.traffic.phase_lat(name) if self.traffic else []
        rep = {
            "name": name, "expected_rule": expected_rule,
            "duration_s": round(dur, 2),
            "p50_ms": round(percentile_ms(lat, 0.50), 3),
            "p99_ms": round(percentile_ms(lat, 0.99), 3),
            "requests": len(lat),
            "new_incidents": [{"id": i["id"], "rule": i["rule"],
                               "cause": i["cause"],
                               "severity": i["severity"],
                               "status": i["status"]} for i in fresh],
        }
        rep.update(extra)
        _log(f"phase {name} done in {dur:.1f}s incidents="
             f"{[i['rule'] for i in rep['new_incidents']]}")
        if expected_rule is None:
            rep["ok"] = not fresh
        else:
            rep["exactly_one"] = len(fresh) == 1
            rep["rule_correct"] = bool(fresh) and all(
                i["rule"] == expected_rule for i in fresh)
            rep["resolved"] = bool(fresh) and all(
                i["status"] == "resolved" for i in fresh)
            rep["ok"] = bool(rep["exactly_one"] and rep["rule_correct"]
                             and rep["resolved"])
        self.phases.append(rep)
        return rep

    # -- phase bodies ---------------------------------------------------------

    def _p_steady(self) -> dict:
        span = max(2.0, self.phase_s)
        self._drive(span * 0.4)
        e1 = self._write_batch()
        self._wait_catchup(timeout_s=15.0)
        self._drive(span * 0.3)
        e2 = self._write_batch()
        self._wait_catchup(timeout_s=15.0)
        self._drive(span * 0.3)
        exact = self._note_count("steady", self._count_routed())
        return {"counts_exact": exact,
                "write_partial": bool(e1.get("partial")
                                      or e2.get("partial")),
                "routed": {k: e1.get("routed", {}).get(k, 0)
                           + e2.get("routed", {}).get(k, 0)
                           for k in ("s0", "s1")}}

    def _p_cell_failover(self) -> dict:
        """SIGKILL cell s0's primary, fail over inside the cell within
        the budget, and fence the resurrected ex-primary before it can
        accept a write it no longer owns."""
        shard = "s0"
        old, rep = self.primary[shard], self.replica[shard]
        self._quiesce()
        p = self.procs[old]
        p.kill()
        p.wait(timeout=10.0)
        # reads survive the kill: the follower is demoted-not-dropped
        read_env = self._count_routed()
        # the dark cell's write sub-batch is refused LOUDLY (partial
        # envelope), never silently dropped — the other cell still lands
        kill_env = self._write_batch()
        new_sp = _free_port()
        res = _http(self.router_port,
                    f"/promote?port={new_sp}&shard={shard}",
                    method="POST", timeout=60.0)
        self.failover = {
            "shard": shard, "old_primary": old,
            "promoted": res.get("promoted"),
            "duration_ms": res.get("duration_ms"),
            "budget_ms": res.get("budget_ms"),
            "within_budget": bool(res.get("within_budget")),
            "epoch": (res.get("result") or {}).get("epoch"),
        }
        self.primary[shard], self.replica[shard] = rep, old
        self.ship_ports[rep] = new_sp
        # resurrect the loser as a primary that MISSED the failover
        # (true split-brain) — the runbook fences it before rejoin
        self._spawn_primary(shard, old)
        epoch = self.failover["epoch"] or 0
        fenced = _http(self.ports[old],
                       f"/replication/fence?epoch={int(epoch)}",
                       method="POST", timeout=10.0)
        post_env = self._write_batch()
        self._wait_catchup(timeout_s=15.0)
        exact = self._note_count("cell_failover", self._count_routed())
        return {"failover": self.failover,
                "read_partial_during_kill": bool(read_env.get("partial")),
                "write_partial_during_kill":
                    bool(kill_env.get("partial")),
                "loser_fenced": bool(fenced.get("fenced")),
                "post_failover_write_partial":
                    bool(post_env.get("partial")),
                "counts_exact": exact}

    def _p_handoff(self) -> dict:
        """Graceful ownership handoff on cell s1 in the middle of an
        ingest stream: drain + fence the old owner FIRST, promote the
        successor, and keep landing routed writes."""
        shard = "s1"
        old, rep = self.primary[shard], self.replica[shard]
        w1 = self._write_batch()
        res = _http(self.router_port, f"/handoff?shard={shard}",
                    method="POST", timeout=60.0)
        w2 = self._write_batch()
        self.handoff_report = {
            "shard": shard, "old_owner": res.get("old_owner"),
            "new_owner": res.get("new_owner"),
            "caught_up": bool(res.get("caught_up")),
            "head_seq": res.get("head_seq"),
            "epoch": res.get("epoch"),
            "duration_ms": res.get("duration_ms"),
        }
        self.primary[shard], self.replica[shard] = rep, old
        addr = (res.get("promoted") or {}).get("address") or ""
        try:
            self.ship_ports[rep] = int(addr.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            pass
        self._wait_catchup(shards=["s0"], timeout_s=10.0)
        return {"handoff": self.handoff_report,
                "mid_ingest_write_partial": bool(w1.get("partial")),
                "post_handoff_write_partial": bool(w2.get("partial"))}

    def _direct_write_attempt(self, name: str, x: float) -> dict:
        """Bypass the router and write straight to one node — the
        split-brain probe.  A fenced loser MUST answer 403."""
        body = json.dumps({"type": "FeatureCollection", "features": [{
            "type": "Feature", "id": f"sb_{name}",
            "geometry": {"type": "Point", "coordinates": [x, 1.0]},
            "properties": {"name": "sb", "v": 1,
                           "dtg": "2024-01-01T06:00:00"}}]}).encode()
        try:
            out = _http(self.ports[name], "/types/t/features",
                        method="POST", body=body, timeout=10.0)
            return {"node": name, "refused": False, "status": 200,
                    "response": out}
        except urllib.error.HTTPError as e:
            kind = None
            try:
                kind = json.loads(e.read().decode()).get("kind")
            except Exception:  # noqa: BLE001
                pass
            return {"node": name, "refused": e.code == 403,
                    "status": e.code, "kind": kind}
        except Exception as e:  # noqa: BLE001
            return {"node": name, "refused": False, "status": None,
                    "error": str(e)}

    def _p_split_brain(self) -> dict:
        """Both cells now hold a fenced loser — s0's resurrected
        ex-primary and s1's handed-off old owner.  Each takes a direct
        write aimed at its own key range; BOTH must refuse, and the
        routed path must still land a full batch.  Then the losers
        rejoin as replicas of the new owners and converge."""
        for loser, x in (("s0p", -5.0), ("s1p", 5.0)):
            att = self._direct_write_attempt(loser, x)
            self.split_brain["attempts"].append(att)
            if att["refused"]:
                self.split_brain["refusals"] += 1
        routed = self._write_batch()
        # rejoin: SIGINT each loser, respawn as a replica of the winner
        for shard in ("s0", "s1"):
            loser = self.replica[shard]
            self._signal(loser, signal.SIGINT)
            self._spawn_replica(shard, loser,
                                self.ship_ports[self.primary[shard]])
        self._wait_catchup(timeout_s=self.wait_s)
        exact = self._note_count("split_brain", self._count_routed())
        return {"split_brain": self.split_brain,
                "routed_write_partial": bool(routed.get("partial")),
                "counts_exact": exact}

    def _p_shard_dark(self) -> dict:
        """Kill BOTH members of cell s0: the doctor pages ``shard_dark``
        naming the key range + members, scatter reads flip partial with
        the missing range, writes refuse the dead cell's rows loudly —
        then the cell respawns and the incident resolves."""
        shard = "s0"
        self._quiesce()
        for n in (self.primary[shard], self.replica[shard]):
            p = self.procs[n]
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10.0)
        detected = self._drive(self.wait_s * 2,
                               until=lambda:
                               self._open_rule("shard_dark"))
        inc = next((i for i in self.doctor.store.all()
                    if i["rule"] == "shard_dark"), None)
        env = self._count_routed()
        missing = env.get("missing_shards") or []
        self.partial_envelope = {
            "partial": bool(env.get("partial")),
            "missing_shards": missing,
            "names_range": any(m.get("shard") == shard
                               and m.get("key_range")
                               == list(self.ranges[shard])
                               for m in missing),
        }
        dark_write = self._write_batch()
        # respawn the cell: the promoted survivor resumes as primary
        # from its own WAL, the other member rejoins as its replica
        self._spawn_primary(shard, self.primary[shard],
                            ship_port=_free_port())
        self._spawn_replica(shard, self.replica[shard],
                            self.ship_ports[self.primary[shard]])
        self._wait_catchup(timeout_s=self.wait_s)
        resolved = self._drive(self.wait_s * 2,
                               until=self._all_resolved)
        self.dark = {
            "detected": detected, "resolved": resolved,
            "incident": None if inc is None else {
                "rule": inc["rule"], "cause": inc["cause"],
                "severity": inc["severity"],
                "suspect": inc.get("suspect")},
        }
        return {"dark": self.dark,
                "partial_envelope": self.partial_envelope,
                "dark_write_partial": bool(dark_write.get("partial"))}

    def _p_recovery(self) -> dict:
        env = self._write_batch()
        self._wait_catchup(timeout_s=self.wait_s)
        self._drive(max(2.0, self.phase_s))
        exact = self._note_count("recovery", self._count_routed())
        return {"counts_exact": exact,
                "write_partial": bool(env.get("partial"))}

    def _p_clean_writes(self) -> dict:
        partial = False
        for _ in range(4):
            partial = partial or bool(self._write_batch().get("partial"))
            self._drive(0.3)
        self._wait_catchup(timeout_s=15.0)
        exact = self._note_count("writes", self._count_routed())
        return {"counts_exact": exact, "write_partial": partial}

    # -- conservation ---------------------------------------------------------

    def _shutdown(self) -> None:
        self._quiesce()
        for n in list(self.procs):
            self._signal(n, signal.SIGINT)

    def _conservation(self) -> dict:
        from geomesa_tpu.replication.drills import fingerprint_dir
        out = {"expected_rows": self.rows, "acked_ingests": self.acked}
        try:
            env = self._count_routed()
            out["final_count"] = int(env["count"])
            out["final_partial"] = bool(env.get("partial"))
        except Exception as e:  # noqa: BLE001
            out["final_count"] = -1
            out["final_partial"] = True
            out["count_error"] = str(e)
        out["loss"] = out["expected_rows"] - out["final_count"]
        self._shutdown()
        cells_out = {}
        matched = True
        for shard in ("s0", "s1"):
            prints = {}
            for n in (self.primary[shard], self.replica[shard]):
                try:
                    prints[n] = fingerprint_dir(self.dirs[n])
                except Exception as e:  # noqa: BLE001
                    prints[n] = {"error": str(e)}
            vals = list(prints.values())
            cell_ok = (len(vals) == 2 and vals[0] == vals[1]
                       and "error" not in vals[0])
            cells_out[shard] = {"fingerprints": prints,
                                "matched": cell_ok}
            matched = matched and cell_ok
        out["cells"] = cells_out
        out["fingerprints_matched"] = matched
        return out

    # -- the half -------------------------------------------------------------

    def run(self) -> dict:
        t_start = time.time()
        knobs = [
            (config.DOCTOR_WINDOW_S, 8.0),
            (config.DOCTOR_CLEAR_TICKS, 2),
            # everything but shard_dark parked: precision/recall against
            # the fault schedule must be deterministic
            (config.DOCTOR_LAG_MS, 1e12),
            (config.DOCTOR_LAG_SEQS, 1e12),
            (config.DOCTOR_RECOMPILES_PER_MIN, 1e12),
            (config.DOCTOR_SHED_PER_MIN, 1e12),
            (config.DOCTOR_BREAKER_FLAPS, 1e12),
            (config.DOCTOR_FSYNC_ERRORS, 1e12),
            (config.DOCTOR_SKEW_MIN, 1e12),
            (config.DOCTOR_REINDEX_PER_MIN, 1e12),
            (config.DOCTOR_MERGE_BREACHES_PER_MIN, 1e12),
            (config.DOCTOR_STRAGGLER_MS, 1e12),
            (config.DOCTOR_IMBALANCE_MIN, 1e12),
        ]
        saved = [(p, p._override) for p, _ in knobs]
        conservation: dict = {}
        try:
            for p, v in knobs:
                p.set(v)
            self.start()
            if self.faulted:
                self._run_phase("steady", None, self._p_steady)
                self._run_phase("cell_failover", None,
                                self._p_cell_failover)
                self._run_phase("handoff", None, self._p_handoff)
                self._run_phase("split_brain", None, self._p_split_brain)
                self._run_phase("shard_dark", "shard_dark",
                                self._p_shard_dark)
                self._run_phase("recovery", None, self._p_recovery)
            else:
                self._run_phase("steady", None, self._p_steady)
                self._run_phase("writes", None, self._p_clean_writes)
                self._run_phase("recovery", None, self._p_recovery)
            conservation = self._conservation()
        finally:
            if self.traffic is not None and self.traffic.is_alive():
                self.traffic.stop()
            for n, p in self.procs.items():
                if p.poll() is None:
                    p.kill()
                    try:
                        p.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        pass
            for p, old in saved:
                if old is None:
                    p.unset()
                else:
                    p.set(old)
            art = os.environ.get("GEOMESA_TPU_SOAK_ARTIFACT")
            if art:
                mode = "chaos" if self.faulted else "clean"
                src = os.path.join(self.base, "cells_doctor.jsonl")
                if os.path.exists(src):
                    shutil.copyfile(src, f"{art}.cells.{mode}.jsonl")
        doctor_score = score_phases(self.phases)
        report = {
            "mode": "chaos" if self.faulted else "clean",
            "mini": self.mini,
            "duration_s": round(time.time() - t_start, 1),
            "rows": self.rows, "acked": self.acked,
            "phases": self.phases,
            "doctor": doctor_score,
            "failover": self.failover,
            "handoff": self.handoff_report,
            "split_brain": self.split_brain,
            "dark": self.dark,
            "partial_envelope": self.partial_envelope,
            "counts": self.counts,
            "conservation": conservation,
            "traffic": {"requests": self.traffic.sent if self.traffic
                        else 0,
                        "errors": self.traffic.errors if self.traffic
                        else 0},
            "notes": self.notes,
        }
        by_name = {p["name"]: p for p in self.phases}
        checks = {
            "phases_ok": all(p.get("ok") for p in self.phases),
            "doctor_precision": doctor_score["precision"] == 1.0,
            "doctor_recall": doctor_score["recall"] == 1.0,
            "counts_exact": bool(self.counts) and all(
                c["exact"] for c in self.counts),
            "zero_loss": conservation.get("loss") == 0,
            "fingerprints_matched":
                bool(conservation.get("fingerprints_matched")),
        }
        if self.faulted:
            fo = self.failover or {}
            fl = by_name.get("cell_failover") or {}
            checks.update({
                "failover_within_budget": bool(fo.get("within_budget")),
                "reads_survived_primary_kill":
                    fl.get("read_partial_during_kill") is False,
                "dark_cell_write_refused_loudly":
                    fl.get("write_partial_during_kill") is True,
                "post_failover_write_full":
                    fl.get("post_failover_write_partial") is False,
                "handoff_caught_up":
                    bool((self.handoff_report or {}).get("caught_up")),
                "split_brain_refused_both":
                    self.split_brain["refusals"] == 2,
                "shard_dark_fired": bool((self.dark or {}).get(
                    "detected")),
                "shard_dark_resolved": bool((self.dark or {}).get(
                    "resolved")),
                "partial_envelope_seen": bool(
                    (self.partial_envelope or {}).get("partial")
                    and (self.partial_envelope or {}).get(
                        "names_range")),
            })
        else:
            checks["zero_incidents"] = \
                doctor_score["incidents_total"] == 0
        report["checks"] = checks
        report["ok"] = all(checks.values())
        return report


# -- entry points -------------------------------------------------------------


def run_cell_soak(base_dir: Optional[str] = None, faulted: bool = True,
                  mini: bool = True) -> dict:
    """Run one soak half, managing a scratch dir when none is given."""
    tmp = None
    if base_dir is None:
        tmp = tempfile.mkdtemp(prefix="geomesa-soakcells-")
        base_dir = tmp
    try:
        return CellSoak(base_dir, faulted=faulted, mini=mini).run()
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def scoreboard_metrics(board: dict) -> dict:
    """Flatten the scoreboard into the cfg16 gate metrics folded into
    perf/baselines.json (exact-match axes pinned in
    perfwatch._OVERRIDES, statistical axes direction-checked)."""
    m: Dict[str, float] = {}
    ch = (board.get("halves") or {}).get("chaos")
    cl = (board.get("halves") or {}).get("clean")
    if ch:
        steady = next((p for p in ch["phases"]
                       if p["name"] == "steady"), None)
        if steady:
            m["cfg16_steady_p50_ms"] = steady["p50_ms"]
            m["cfg16_steady_p99_ms"] = steady["p99_ms"]
        if ch.get("failover"):
            m["cfg16_failover_ms"] = ch["failover"]["duration_ms"]
            m["cfg16_failover_within_budget"] = float(
                ch["failover"]["within_budget"])
        if ch.get("handoff"):
            m["cfg16_handoff_ms"] = ch["handoff"]["duration_ms"]
        m["cfg16_doctor_precision"] = ch["doctor"]["precision"]
        m["cfg16_doctor_recall"] = ch["doctor"]["recall"]
        m["cfg16_acked_write_loss"] = float(
            ch["conservation"]["loss"]
            + (cl["conservation"]["loss"] if cl else 0))
        m["cfg16_fingerprints_matched"] = float(
            ch["conservation"]["fingerprints_matched"]
            and (cl is None
                 or cl["conservation"]["fingerprints_matched"]))
        m["cfg16_split_brain_refused"] = float(
            (ch.get("split_brain") or {}).get("refusals", 0))
        m["cfg16_shard_dark_fired"] = float(
            bool((ch.get("dark") or {}).get("detected")))
        m["cfg16_partial_envelope_seen"] = float(
            bool((ch.get("partial_envelope") or {}).get("partial")
                 and (ch.get("partial_envelope") or {}).get(
                     "names_range")))
    if cl:
        m["cfg16_clean_incidents"] = float(
            cl["doctor"]["incidents_total"])
    return m


def render_scoreboard(board: dict) -> str:
    """Markdown rendering of a scoreboard (written next to the JSON)."""
    lines = ["# Cluster cell soak scoreboard", ""]
    lines.append(f"- mini: {board.get('mini')}  ok: **{board.get('ok')}**")
    for mode, half in (board.get("halves") or {}).items():
        lines += ["", f"## {mode} half "
                      f"({'PASS' if half.get('ok') else 'FAIL'}, "
                      f"{half.get('duration_s')}s, "
                      f"{half.get('rows')} rows routed)", ""]
        lines.append("| phase | expected | incidents | p50 ms | p99 ms "
                     "| ok |")
        lines.append("|---|---|---|---|---|---|")
        for p in half.get("phases", []):
            rules = ", ".join(i["rule"]
                              for i in p["new_incidents"]) or "-"
            lines.append(
                f"| {p['name']} | {p.get('expected_rule') or '-'} "
                f"| {rules} | {p['p50_ms']} | {p['p99_ms']} "
                f"| {'yes' if p.get('ok') else 'NO'} |")
        d = half.get("doctor") or {}
        lines.append("")
        lines.append(f"- doctor precision **{d.get('precision')}** / "
                     f"recall **{d.get('recall')}** "
                     f"({d.get('correct')}/{d.get('incidents_total')} "
                     f"incidents correct)")
        fo = half.get("failover")
        if fo:
            lines.append(
                f"- failover: {fo['old_primary']} → {fo['promoted']} in "
                f"{fo['duration_ms']}ms (budget {fo['budget_ms']}ms, "
                f"within: {fo['within_budget']})")
        ho = half.get("handoff")
        if ho:
            lines.append(
                f"- handoff: {ho['old_owner']} → {ho['new_owner']} in "
                f"{ho['duration_ms']}ms (caught_up: {ho['caught_up']}, "
                f"epoch {ho['epoch']})")
        sb = half.get("split_brain")
        if sb and sb.get("attempts"):
            lines.append(f"- split-brain: {sb['refusals']}/"
                         f"{len(sb['attempts'])} fenced losers refused")
        pe = half.get("partial_envelope")
        if pe:
            lines.append(f"- dark-shard envelope: partial="
                         f"{pe['partial']}, names_range="
                         f"{pe['names_range']}")
        cons = half.get("conservation") or {}
        lines.append(
            f"- conservation: {cons.get('final_count')}/"
            f"{cons.get('expected_rows')} rows (loss "
            f"{cons.get('loss')}), fingerprints_matched="
            f"{cons.get('fingerprints_matched')}")
        checks = half.get("checks") or {}
        bad = [k for k, v in checks.items() if not v]
        if bad:
            lines.append(f"- FAILED checks: {', '.join(sorted(bad))}")
    metrics = board.get("metrics") or {}
    if metrics:
        lines += ["", "## cfg16 gate metrics", ""]
        lines.append("| metric | value |")
        lines.append("|---|---|")
        for k in sorted(metrics):
            lines.append(f"| {k} | {metrics[k]} |")
    return "\n".join(lines) + "\n"


def run(mini: bool = True, scoreboard_path: Optional[str] = None,
        base_dir: Optional[str] = None,
        halves: tuple = ("chaos", "clean")) -> dict:
    """Run the full soak (chaos + clean halves), write the scoreboard
    JSON + markdown, and remember it for bench cfg16."""
    global LAST
    scoreboard_path = scoreboard_path or os.environ.get(
        "GEOMESA_TPU_SOAKCELLS_SCOREBOARD", SCOREBOARD_DEFAULT)
    board: dict = {"schema": 1, "mini": mini, "halves": {},
                   "generated": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())}
    for half in halves:
        board["halves"][half] = run_cell_soak(
            base_dir=os.path.join(base_dir, half) if base_dir else None,
            faulted=(half == "chaos"), mini=mini)
    board["metrics"] = scoreboard_metrics(board)
    board["ok"] = all(h.get("ok") for h in board["halves"].values())
    with open(scoreboard_path, "w", encoding="utf-8") as f:
        json.dump(board, f, indent=2, sort_keys=True)
    md_path = os.path.splitext(scoreboard_path)[0] + ".md"
    with open(md_path, "w", encoding="utf-8") as f:
        f.write(render_scoreboard(board))
    LAST = board
    return board
